"""Generation backends ("workers") and their health state machine.

A :class:`WorkerNode` is one schedulable generation backend. The reference's
worker is always a remote sdwui HTTP process
(/root/reference/scripts/spartan/worker.py:51-758); here a backend is
pluggable:

- :class:`LocalBackend` — the in-process Engine on the local TPU mesh (the
  "master" role; the reference times local generation the same way,
  world.py:188-197);
- :class:`HTTPBackend` — a remote sdapi-v1 server (another host running
  this framework, or an actual sdwui instance) — capability parity with the
  reference's transport (worker.py:288-504);
- :class:`StubBackend` — deterministic fake for tests and failure injection
  (SURVEY.md §4 test strategy).

State machine parity (worker.py:36-41, 719-758): 5 states with guarded
transitions; a demotion to UNAVAILABLE invalidates the loaded-model cache so
a reconnect forces re-sync.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Protocol, Tuple

from stable_diffusion_webui_distributed_tpu.obs import (
    prometheus as obs_prom,
)
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    GenerationResult,
)
from stable_diffusion_webui_distributed_tpu.runtime.config import (
    BenchmarkPayload,
    WARMUP_SAMPLES,
    RECORDED_SAMPLES,
)
from stable_diffusion_webui_distributed_tpu.runtime.daemon import (
    StoppableDaemon,
)
from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger
from stable_diffusion_webui_distributed_tpu.scheduler import eta as eta_mod


class State(enum.Enum):
    IDLE = 1
    WORKING = 2
    INTERRUPTED = 3
    UNAVAILABLE = 4
    DISABLED = 5


#: Guarded transition table (reference worker.py:738-743). UNAVAILABLE is
#: reachable from anywhere except DISABLED (handled specially in set_state).
TRANSITIONS = {
    State.IDLE: {State.IDLE, State.WORKING, State.DISABLED},
    State.WORKING: {State.WORKING, State.IDLE, State.INTERRUPTED},
    State.UNAVAILABLE: {State.IDLE},
    State.INTERRUPTED: {State.WORKING, State.IDLE},
    State.DISABLED: {State.IDLE},
}


class WorkerHealth:
    """Rolling health telemetry for one worker.

    The state machine says what a worker IS (idle/working/unavailable);
    this says how it has been BEHAVING: error rate over a bounded outcome
    window, latency EWMA, consecutive-failure streak, images requeued
    away from it, and a ring of recent state transitions. Always on (it
    never touches response bytes); the summary feeds
    ``GET /internal/workers``, the ``sdtpu_worker_*`` Prometheus families
    and the fleet autoscaler's health veto (fleet/slices.py).
    """

    WINDOW = 32           # request outcomes retained
    TRANSITION_RING = 32  # state transitions retained
    EWMA_ALPHA = 0.3

    def __init__(self, label: str):
        self.label = label
        self._lock = threading.Lock()
        self._window: Deque[bool] = deque(
            maxlen=self.WINDOW)  # guarded-by: _lock
        self._transitions: Deque[Tuple[float, str, str]] = deque(
            maxlen=self.TRANSITION_RING)  # guarded-by: _lock
        self.requests = 0               # guarded-by: _lock
        self.failures = 0               # guarded-by: _lock
        self.consecutive_failures = 0   # guarded-by: _lock
        self.requeued_images = 0        # guarded-by: _lock
        self.latency_ewma_s: Optional[float] = None  # guarded-by: _lock

    def record_result(self, ok: bool,
                      latency_s: Optional[float] = None) -> None:
        """One generate outcome; metrics are bumped outside the lock."""
        with self._lock:
            self.requests += 1
            self._window.append(bool(ok))
            if ok:
                self.consecutive_failures = 0
                if latency_s is not None:
                    prev = self.latency_ewma_s
                    self.latency_ewma_s = (
                        float(latency_s) if prev is None
                        else self.EWMA_ALPHA * float(latency_s)
                        + (1.0 - self.EWMA_ALPHA) * prev)
            else:
                self.failures += 1
                self.consecutive_failures += 1
            ewma = self.latency_ewma_s
        obs_prom.worker_count("requests", worker=self.label)
        if not ok:
            obs_prom.worker_count("failures", worker=self.label)
        elif ewma is not None:
            obs_prom.set_worker_latency(self.label, ewma)

    def record_requeue(self, images: int) -> None:
        """``images`` of this worker's slice were requeued elsewhere."""
        with self._lock:
            self.requeued_images += int(images)
        obs_prom.worker_count("requeued_images", int(images),
                              worker=self.label)

    def record_transition(self, frm: str, to: str) -> None:
        at = time.time()  # sdtpu-lint: wallclock — operator-facing timeline
        with self._lock:
            self._transitions.append((at, frm, to))
        obs_prom.worker_count("transitions", worker=self.label, to=to)

    def error_rate(self) -> float:
        with self._lock:
            if not self._window:
                return 0.0
            return (sum(1 for ok in self._window if not ok)
                    / len(self._window))

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            window = list(self._window)
            return {
                "requests": self.requests,
                "failures": self.failures,
                "window": len(window),
                "error_rate": ((sum(1 for ok in window if not ok)
                                / len(window)) if window else 0.0),
                "consecutive_failures": self.consecutive_failures,
                "latency_ewma_s": self.latency_ewma_s,
                "requeued_images": self.requeued_images,
                "transitions": [{"at": at, "from": f, "to": t}
                                for at, f, t in self._transitions],
            }


#: Sanctioned chaos-injection hook (sim/chaos.py). When armed, it is
#: consulted inside :meth:`WorkerNode.request`'s try-block just before
#: ``backend.generate`` — a raised exception lands in the existing
#: failure/demote/requeue path, a sleep models a stall or slow worker.
#: ``None`` (the default) costs one identity check on the hot path.
CHAOS_HOOK = None


class Backend(Protocol):
    """What a schedulable backend must provide."""

    def generate(self, payload: GenerationPayload, start_index: int,
                 count: int) -> GenerationResult: ...

    def reachable(self) -> bool: ...

    def interrupt(self) -> None: ...

    def restart(self) -> None: ...

    def load_options(self, model: str, vae: str = "") -> None: ...

    def available_models(self) -> List[str]: ...

    def memory_info(self) -> Dict[str, Any]: ...


class WorkerNode:
    """One schedulable backend + its calibration, state, and caps."""

    def __init__(
        self,
        label: str,
        backend: Backend,
        master: bool = False,
        pixel_cap: int = 0,
        avg_ipm: Optional[float] = None,
        eta_percent_error: Optional[List[float]] = None,
        benchmark_payload: Optional[BenchmarkPayload] = None,
        model_override: Optional[str] = None,
    ):
        self.label = label
        self.backend = backend
        self.master = master
        self.pixel_cap = pixel_cap  # 0 = uncapped (reference -1, pmodels.py:34)
        self.cal = eta_mod.EtaCalibration(
            avg_ipm=avg_ipm,
            eta_percent_error=list(eta_percent_error or []),
        )
        self.benchmark_payload = benchmark_payload or BenchmarkPayload()
        # the state machine and model-sync cache are read by HTTP config
        # handlers, ping sweeps, and request threads concurrently; every
        # access outside __init__ must hold _lock (verified by sdtpu-lint
        # rule LK001)
        self.state = State.IDLE  # guarded-by: _lock
        self.loaded_model: Optional[str] = None  # guarded-by: _lock
        self.loaded_vae: Optional[str] = None  # guarded-by: _lock
        # script titles this backend supports (reference queries
        # /script-info per worker at ping time, world.py:744-763); None =
        # unknown (send everything)
        self.supported_scripts: Optional[List[str]] = None
        # checkpoint pin for this worker (reference ui.py:161-171); honored
        # by load_options and persisted via World.save_config
        self.model_override: Optional[str] = model_override
        # pin provenance: True = checked against the node's model list,
        # False = accepted while the node was unreachable (typo'd pins
        # stay visible, not latent), None = no pin / not yet checked.
        # Re-validated by World.ping_workers on the next successful ping.
        self.pin_validated: Optional[bool] = None
        # once a pin is positively refuted against a LIVE model list, ping
        # sweeps stop re-fetching it (no per-ping RPC/log spam); cleared on
        # pin change or node reconnect
        self._pin_refuted = False
        self.response_time: Optional[float] = None
        # free accelerator memory observed at first contact (the reference
        # queries /memory on a worker's first request, worker.py:319-340)
        self.free_memory: Optional[int] = None
        # interrupt rendezvous polled while a remote request is in flight
        # (None = the process-wide runtime.interrupt.STATE)
        self.interrupt_state = None
        self.interrupt_poll_s = 0.5  # reference's poll cadence
        # rolling behavioural telemetry (own lock; never nested under
        # _lock — set_state records transitions after releasing it)
        self.health = WorkerHealth(label)

        self._lock = threading.Lock()

    # -- state machine ------------------------------------------------------

    def set_state(self, state: State, expect_cycle: bool = False) -> bool:
        """Guarded transition; returns True if the state changed/held legally."""
        ok, changed = self._transition(state, expect_cycle)
        if changed is not None:
            # recorded after _lock is released (health has its own lock)
            self.health.record_transition(*changed)
        return ok

    def _transition(self, state: State, expect_cycle: bool,
                    ) -> Tuple[bool, Optional[Tuple[str, str]]]:
        """(legal, (from, to) if the state actually moved)."""
        log = get_logger()
        with self._lock:
            if state == State.UNAVAILABLE:
                if self.state == State.DISABLED:
                    log.debug("%s: disabled, refusing UNAVAILABLE", self.label)
                    return False, None
                prev = self.state
                # invalidate model cache so reconnection forces re-sync
                # (reference worker.py:747-755)
                self.loaded_model = None
                self.loaded_vae = None
                log.warning("worker '%s' unreachable; avoided until "
                            "reconnection", self.label)
                self.state = State.UNAVAILABLE
                return True, (prev.name, state.name)
            if state in TRANSITIONS.get(self.state, set()):
                if state != self.state or expect_cycle:
                    prev = self.state
                    log.debug("%s: %s -> %s", self.label, prev.name,
                              state.name)
                    self.state = state
                    return True, (prev.name, state.name)
                return True, None
            log.debug("%s: invalid transition %s -> %s", self.label,
                      self.state.name, state.name)
            return False, None

    @property
    def available(self) -> bool:
        with self._lock:
            return self.state not in (State.UNAVAILABLE, State.DISABLED)

    def current_state(self) -> State:
        """Locked state read for cross-thread callers (the scheduler's
        sweep/fan-out loops must not read ``state`` bare)."""
        with self._lock:
            return self.state

    # -- ETA ----------------------------------------------------------------

    def eta(self, payload, batch_size: Optional[int] = None,
            steps: Optional[int] = None, queue_wait: float = 0.0,
            padding_overhead: float = 1.0) -> float:
        # queue_wait/padding_overhead: serving-dispatcher additions for
        # backends behind a coalescing front end (scheduler/eta.py).
        # precision: the payload's requested serving precision scales the
        # compute part via the per-precision factor (int8 ~2x) so mixed
        # fleets predict each request at its own speed
        return eta_mod.predict_eta(self.cal, payload, self.benchmark_payload,
                                   batch_size=batch_size, steps=steps,
                                   queue_wait=queue_wait,
                                   padding_overhead=padding_overhead,
                                   precision=self._payload_precision(payload))

    @staticmethod
    def _payload_precision(payload) -> str:
        """Resolved precision name for ETA purposes (payload channel only
        — a remote backend's env defaults are not visible here, so an
        unspecified precision calibrates as the bf16 baseline)."""
        from stable_diffusion_webui_distributed_tpu.pipeline import (
            precision as precision_mod,
        )

        return precision_mod.resolve(payload).name

    # -- request lifecycle --------------------------------------------------

    def request(self, payload: GenerationPayload, start_index: int,
                count: int) -> Optional[GenerationResult]:
        """Generate images [start_index, start_index+count); returns None on
        failure (the reference logs and drops the worker's images,
        distributed.py:158-169 + worker.py:494-500)."""
        log = get_logger()
        # wait out a prior request still in flight (reference busy-wait,
        # worker.py:301-315)
        deadline = time.monotonic() + 30.0
        while self.current_state() == State.WORKING \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        self.set_state(State.WORKING)

        payload = self.filter_payload_scripts(payload)
        if self.free_memory is None:
            self._probe_memory()
        predicted = None
        if self.cal.benchmarked:
            try:
                predicted = self.eta(payload, batch_size=count)
            except ValueError:
                predicted = None
        from stable_diffusion_webui_distributed_tpu.obs import (
            spans as obs_spans,
        )

        started = time.monotonic()
        stop_watch = self._start_interrupt_watchdog()
        try:
            with obs_spans.span("worker.generate", worker=self.label,
                                start=int(start_index), count=int(count),
                                predicted_s=predicted) as wsp:
                if CHAOS_HOOK is not None:
                    CHAOS_HOOK("worker.generate", worker=self.label,
                               payload=payload, count=int(count))
                result = self.backend.generate(payload, start_index, count)
        except Exception as e:  # noqa: BLE001 — any backend failure demotes
            log.error("worker '%s' failed request: %s", self.label, e)
            self.health.record_result(False)
            self.set_state(State.UNAVAILABLE)
            return None
        finally:
            if stop_watch is not None:
                stop_watch.halt()  # hot path: signal only, never join
        elapsed = time.monotonic() - started
        self.response_time = elapsed
        self.health.record_result(True, elapsed)
        if wsp is not None:
            # predicted-vs-actual on the span itself: one request's ETA
            # calibration quality is readable straight off its trace
            wsp.attrs["actual_s"] = elapsed
        if predicted is not None:
            # precision-scoped: an int8 sample refines the int8 factor
            # only and never enters the bf16 MPE window (scheduler/eta.py)
            eta_mod.record_eta_error(self.cal, predicted, elapsed,
                                     precision=self._payload_precision(
                                         payload))
        self.set_state(State.IDLE)
        return result

    def _start_interrupt_watchdog(self) -> Optional[StoppableDaemon]:
        """Poll the local interrupt flag every 0.5 s while a request is in
        flight and fire ``backend.interrupt()`` the moment it latches — the
        reference's mid-request propagation loop
        (/root/reference/scripts/spartan/worker.py:440-448). The master's
        LocalBackend needs no watchdog: its chunked denoise loop reads the
        same flag between dispatches."""
        if self.master:
            return None
        from stable_diffusion_webui_distributed_tpu.runtime import (
            interrupt as interrupt_mod,
        )

        state = self.interrupt_state or interrupt_mod.STATE

        def watch():
            if not state.flag.interrupted:
                return
            get_logger().info(
                "interrupt: aborting in-flight request on '%s'",
                self.label)
            try:
                self.backend.interrupt()
            except Exception as e:  # noqa: BLE001
                get_logger().error(
                    "in-flight interrupt of '%s' failed: %s",
                    self.label, e)
            daemon.halt()  # fired once: the watch is done

        # immediate=False: first poll lands one period in, like the
        # reference's stop.wait(period) loop
        daemon = StoppableDaemon(f"interrupt-watch-{self.label}", watch,
                                 self.interrupt_poll_s, immediate=False)
        daemon.start()
        return daemon

    def _probe_memory(self) -> None:
        """First-contact memory probe (reference worker.py:319-340): record
        free accelerator memory, warn when it looks too tight for the
        workload; failures are non-fatal."""
        try:
            info = self.backend.memory_info()
        except Exception:  # noqa: BLE001
            self.free_memory = -1
            return
        free = None
        cuda = info.get("cuda") or {}
        if isinstance(cuda, dict):
            free = (cuda.get("system") or {}).get("free")
        if free is None:
            tpu = info.get("tpu") or {}
            # devices without memory stats (bytes_limit 0, e.g. CPU test
            # platforms) don't count as "0 bytes free"
            devs = [d for d in (tpu.get("devices") or [])
                    if d.get("bytes_limit", 0) > 0]
            if devs:
                free = sum(max(0, d["bytes_limit"]
                               - d.get("bytes_in_use", 0)) for d in devs)
        self.free_memory = int(free) if free is not None else -1
        if 0 <= self.free_memory < 2 << 30:
            get_logger().warning(
                "worker '%s' reports only %.1f GiB free accelerator memory",
                self.label, self.free_memory / (1 << 30))

    def interrupt(self) -> None:
        try:
            self.backend.interrupt()
            self.set_state(State.INTERRUPTED)
        except Exception as e:  # noqa: BLE001
            get_logger().error("interrupt of '%s' failed: %s", self.label, e)
            self.set_state(State.UNAVAILABLE)

    def restart(self) -> bool:
        """Ask this backend's server process to restart (reference
        worker.py:690-717). The node goes UNAVAILABLE with its model cache
        invalidated; the next ping sweep revives it once it's back."""
        try:
            self.backend.restart()
        except Exception as e:  # noqa: BLE001
            get_logger().error("restart of '%s' failed: %s", self.label, e)
            return False
        self.set_state(State.UNAVAILABLE)
        return True

    def reachable(self) -> bool:
        try:
            ok = self.backend.reachable()
        except Exception:  # noqa: BLE001
            return False
        if ok:
            # re-query at every ping: a restarted worker may have gained or
            # lost script support (reference re-discovers per ping sweep,
            # world.py:744-763)
            try:
                self.supported_scripts = self.backend.script_info()
            except Exception:  # noqa: BLE001
                pass  # keep the previous knowledge
        return ok

    def filter_payload_scripts(self, payload: GenerationPayload
                               ) -> GenerationPayload:
        """Strip alwayson-script args this backend doesn't support — the
        reference's per-worker compat filter (worker.py:375-404; script
        discovery at world.py:744-763)."""
        if not payload.alwayson_scripts or self.supported_scripts is None:
            return payload
        supported = {s.lower() for s in self.supported_scripts}
        kept = {k: v for k, v in payload.alwayson_scripts.items()
                if k.lower() in supported}
        if len(kept) == len(payload.alwayson_scripts):
            return payload
        dropped = set(payload.alwayson_scripts) - set(kept)
        get_logger().debug("worker '%s': dropping unsupported script args %s",
                           self.label, sorted(dropped))
        payload = payload.model_copy()
        payload.alwayson_scripts = kept
        return payload

    def load_options(self, model: str, vae: str = "") -> bool:
        """Sync the loaded checkpoint (reference worker.py:646-688)."""
        if self.model_override:
            model = self.model_override
        with self._lock:
            if self.loaded_model == model and self.loaded_vae == vae:
                return True
        try:
            t0 = time.monotonic()
            self.backend.load_options(model, vae)
            get_logger().info("worker '%s' loaded model '%s' in %.1fs",
                              self.label, model, time.monotonic() - t0)
            with self._lock:
                self.loaded_model, self.loaded_vae = model, vae
            return True
        except Exception as e:  # noqa: BLE001
            get_logger().error("model sync to '%s' failed: %s", self.label, e)
            self.set_state(State.UNAVAILABLE)
            return False

    # -- benchmark ----------------------------------------------------------

    def benchmark(self, rebenchmark: bool = False) -> Optional[float]:
        """2 warmup + 3 recorded samples of the fixed benchmark payload ->
        avg images/minute (reference worker.py:506-575, shared.py:63-64)."""
        log = get_logger()
        if self.cal.benchmarked and not rebenchmark:
            return self.cal.avg_ipm
        if not self.reachable():
            self.set_state(State.UNAVAILABLE)
            return None
        bp = self.benchmark_payload
        payload = GenerationPayload(
            prompt=bp.prompt, negative_prompt=bp.negative_prompt,
            steps=bp.steps, width=bp.width, height=bp.height,
            batch_size=bp.batch_size, sampler_name=bp.sampler_name, seed=1,
        )
        ipms = []
        for i in range(WARMUP_SAMPLES + RECORDED_SAMPLES):
            t0 = time.monotonic()
            try:
                result = self.backend.generate(payload, 0, bp.batch_size)
            except Exception as e:  # noqa: BLE001
                log.error("benchmark of '%s' failed: %s", self.label, e)
                self.set_state(State.UNAVAILABLE)
                return None
            elapsed = time.monotonic() - t0
            sample_ipm = len(result.images) / (elapsed / 60.0)
            if i < WARMUP_SAMPLES:
                log.debug("benchmark '%s' warmup %d: %.2f ipm",
                          self.label, i, sample_ipm)
            else:
                ipms.append(sample_ipm)
                log.debug("benchmark '%s' sample %d: %.2f ipm",
                          self.label, i - WARMUP_SAMPLES, sample_ipm)
        self.cal.avg_ipm = sum(ipms) / len(ipms)
        self.cal.eta_percent_error.clear()  # stale MPE dies with re-bench
        log.info("worker '%s': %.2f ipm", self.label, self.cal.avg_ipm)
        return self.cal.avg_ipm


# --------------------------------------------------------------------------
# backends
# --------------------------------------------------------------------------

class LocalBackend:
    """The in-process Engine (master role)."""

    def __init__(self, engine):
        self.engine = engine

    def generate(self, payload, start_index, count):
        return self.engine.generate_range(payload, start_index, count)

    def reachable(self) -> bool:
        return True

    def interrupt(self) -> None:
        self.engine.state.flag.interrupt()

    def restart(self) -> None:
        # the master restarts through its own /server-restart route (the
        # serve loop re-execs); a cluster restart fan-out skips it
        raise RuntimeError("local master cannot restart itself")

    def load_options(self, model: str, vae: str = "") -> None:
        # local model switching is handled by the ModelRegistry at the
        # server layer; the engine itself holds one loaded family
        self.engine.model_name = model or self.engine.model_name

    def script_info(self) -> List[str]:
        return ["controlnet"]  # natively supported in-graph

    def available_models(self) -> List[str]:
        return [self.engine.model_name]

    def memory_info(self) -> Dict[str, Any]:
        import jax

        devices = []
        for d in jax.devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 — CPU backends lack stats
                stats = {}
            devices.append({
                "id": d.id, "kind": d.device_kind,
                "bytes_in_use": stats.get("bytes_in_use", 0),
                "bytes_limit": stats.get("bytes_limit", 0),
            })
        # same shape the sdapi /memory route serves, so _probe_memory
        # parses local and remote backends identically
        return {"tpu": {"devices": devices}}


@dataclasses.dataclass
class StubBehavior:
    """Failure-injection knobs for tests."""

    seconds_per_image: float = 0.0
    fail_generate: bool = False
    fail_reachable: bool = False
    fail_after_n_requests: Optional[int] = None
    supported_scripts: Tuple[str, ...] = ("controlnet",)


class StubBackend:
    """Deterministic in-process fake worker (SURVEY §4: failure injection)."""

    def __init__(self, behavior: Optional[StubBehavior] = None):
        self.behavior = behavior or StubBehavior()
        self.requests: List[Dict[str, Any]] = []
        self.interrupted = False
        self.restarted = False
        self.options: Dict[str, str] = {}
        self.models: List[str] = ["stub-model"]

    def generate(self, payload, start_index, count):
        n = len(self.requests)
        self.requests.append(
            {"payload": payload, "start": start_index, "count": count})
        b = self.behavior
        if b.fail_generate or (
            b.fail_after_n_requests is not None
            and n >= b.fail_after_n_requests
        ):
            raise ConnectionError("stub backend injected failure")
        result = GenerationResult()
        pinned = payload.same_seed or payload.subseed_strength > 0
        for i in range(start_index, start_index + count):
            if b.seconds_per_image:
                # sleep in slices so an interrupt lands mid-flight, like a
                # real remote that returns the images finished so far
                deadline = time.monotonic() + b.seconds_per_image
                while time.monotonic() < deadline and not self.interrupted:
                    time.sleep(0.01)
            if self.interrupted:
                break
            # per-image seed/prompt arithmetic mirrors Engine._append_images
            seed_i = payload.seed + (0 if pinned else i)
            sub_i = payload.subseed + (0 if payload.same_seed else i)
            prompt_i = payload.prompt
            if payload.all_prompts and i < len(payload.all_prompts):
                prompt_i = payload.all_prompts[i]
            result.images.append(f"stub-image-{seed_i}")
            result.seeds.append(seed_i)
            result.subseeds.append(sub_i)
            result.prompts.append(prompt_i)
            result.negative_prompts.append(payload.negative_prompt)
            result.infotexts.append(f"{prompt_i}, Seed: {seed_i}")
            result.worker_labels.append("")
        return result

    def reachable(self) -> bool:
        return not self.behavior.fail_reachable

    def interrupt(self) -> None:
        self.interrupted = True

    def restart(self) -> None:
        if self.behavior.fail_reachable:
            raise ConnectionError("stub: restart failure")
        self.restarted = True

    def load_options(self, model: str, vae: str = "") -> None:
        if self.behavior.fail_generate:
            raise ConnectionError("stub: load_options failure")
        self.options = {"model": model, "vae": vae}

    def script_info(self) -> List[str]:
        return list(self.behavior.supported_scripts)

    def available_models(self) -> List[str]:
        return list(self.models)

    def memory_info(self) -> Dict[str, Any]:
        return {"ram": {"free": 1 << 30, "used": 0, "total": 1 << 30}}


class HTTPBackend:
    """Remote sdapi-v1 server over HTTP(S) — the reference's entire transport
    (worker.py:192-203 route table, 288-504 request path), kept for parity so
    a pool of this framework's servers (or legacy sdwui nodes) can be driven.
    """

    def __init__(self, address: str, port: int, tls: bool = False,
                 user: Optional[str] = None, password: Optional[str] = None,
                 verify_tls: bool = True, timeout: Optional[float] = None):
        self.address = address
        self.port = port
        self.tls = tls
        self.user = user
        self.password = password
        self.verify_tls = verify_tls
        if timeout is None:
            # control-plane probe timeout (reachable/interrupt/heartbeat
            # sweeps): the obs-plane-wide SDTPU_OBS_HTTP_TIMEOUT_S knob
            # bounds it, defaulting to the historical 3.0s
            from ..obs import stitch as obs_stitch

            timeout = obs_stitch.http_timeout_s(3.0)
        self.timeout = timeout
        import requests

        self.session = requests.Session()
        self.session.verify = verify_tls
        if user or password:
            self.session.auth = (user or "", password or "")

    def url(self, route: str) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.address}:{self.port}/sdapi/v1/{route}"

    def close(self) -> None:
        """Release pooled connections (called when a backend is replaced by
        an endpoint edit, or a transient validation probe is done)."""
        self.session.close()

    def generate(self, payload: GenerationPayload, start_index: int,
                 count: int) -> GenerationResult:
        from stable_diffusion_webui_distributed_tpu.obs import (
            spans as obs_spans,
        )

        # cross-node trace propagation: the remote roots its own spans
        # under the same request id (obs/stitch.py correlates on it).
        # Session headers, not a per-call kwarg, so every hop (including
        # the sampler-fallback retry) carries them.
        rid = obs_spans.current_request_id()
        if rid:
            self.session.headers["X-SDTPU-Request-Id"] = rid
            tp = obs_spans.traceparent()
            if tp:
                self.session.headers["traceparent"] = tp
        else:
            self.session.headers.pop("X-SDTPU-Request-Id", None)
            self.session.headers.pop("traceparent", None)
        body = payload.model_dump()
        # seed fan-out arithmetic, identical to the reference master
        # (distributed.py:297-305): offset by prior images. Same-seed
        # batches (prompt matrix) pin every image to the request seed.
        if payload.subseed_strength == 0 and not payload.same_seed:
            body["seed"] = payload.seed + start_index
        if not payload.same_seed:
            body["subseed"] = payload.subseed + start_index
        # per-image prompts: the remote gets ITS slice, indexed from 0
        if payload.all_prompts:
            body["all_prompts"] = \
                payload.all_prompts[start_index:start_index + count]
        body["batch_size"] = count
        body["n_iter"] = 1
        route = "img2img" if payload.init_images else "txt2img"
        r = self.session.post(self.url(route), json=body, timeout=3600)
        if r.status_code == 404 and "sampler" in r.text.lower():
            # legacy remote doesn't know this sampler: retry with Euler a,
            # the reference's degraded-capability fallback (worker.py:457-467)
            get_logger().warning(
                "remote %s:%d lacks sampler '%s'; retrying with Euler a",
                self.address, self.port, body.get("sampler_name"))
            body["sampler_name"] = "Euler a"
            r = self.session.post(self.url(route), json=body, timeout=3600)
        r.raise_for_status()
        data = r.json()
        result = GenerationResult(images=data.get("images", []))
        info = data.get("info")
        if isinstance(info, str):
            import json as _json

            try:
                info = _json.loads(info)
            except ValueError:
                info = {}
        info = info or {}
        result.seeds = info.get("all_seeds",
                                [body["seed"] + i for i in range(count)])
        result.subseeds = info.get("all_subseeds",
                                   [body["subseed"] + i for i in range(count)])
        result.prompts = info.get("all_prompts", [payload.prompt] * count)
        result.negative_prompts = info.get(
            "all_negative_prompts", [payload.negative_prompt] * count)
        result.infotexts = info.get("infotexts", [""] * count)
        result.worker_labels = [""] * len(result.images)
        return result

    def reachable(self) -> bool:
        try:
            r = self.session.get(self.url("memory"), timeout=self.timeout)
            return r.ok
        except Exception:  # noqa: BLE001
            return False

    def interrupt(self) -> None:
        self.session.post(self.url("interrupt"), timeout=self.timeout)

    def restart(self) -> None:
        """POST /server-restart (the reference's fleet-restart leg,
        worker.py:690-717). A server that re-execs before answering drops
        the connection or never flushes a response — both count as
        delivered; only failing to CONNECT is a real failure."""
        import requests

        try:
            self.session.post(self.url("server-restart"),
                              timeout=self.timeout)
        except requests.exceptions.ConnectTimeout:
            raise  # never reached the worker
        except (requests.exceptions.ConnectionError,
                requests.exceptions.ReadTimeout):
            return  # process went down (or stopped answering) to restart

    def load_options(self, model: str, vae: str = "") -> None:
        body = {"sd_model_checkpoint": model}
        if vae:
            body["sd_vae"] = vae
        r = self.session.post(self.url("options"), json=body, timeout=600)
        r.raise_for_status()

    def script_info(self) -> List[str]:
        r = self.session.get(self.url("script-info"), timeout=self.timeout)
        r.raise_for_status()
        names = []
        for entry in r.json():
            if isinstance(entry, dict) and entry.get("name"):
                names.append(entry["name"])
            elif isinstance(entry, str):
                names.append(entry)
        return names

    def available_models(self) -> List[str]:
        r = self.session.get(self.url("sd-models"), timeout=self.timeout)
        r.raise_for_status()
        return [m.get("model_name", m.get("title", "?")) for m in r.json()]

    def memory_info(self) -> Dict[str, Any]:
        r = self.session.get(self.url("memory"), timeout=self.timeout)
        r.raise_for_status()
        return r.json()

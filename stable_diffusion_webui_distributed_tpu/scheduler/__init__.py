"""Scheduler: the reference's World/Job/ETA/benchmark policy layer, reborn
as the multi-backend planner above the TPU compute path.

Within one mesh, parallelism is XLA's problem (parallel/). This package
balances *across* generation backends — the local mesh, other slices/hosts,
or remote sdapi servers — exactly the scheduling problem the reference
solves for a pool of HTTP GPU workers (/root/reference/scripts/spartan/
world.py, worker.py): speed-calibrated splits, stall detection, deferral,
complementary production, elastic health handling.
"""

from stable_diffusion_webui_distributed_tpu.scheduler.eta import (  # noqa: F401
    EtaCalibration,
    SAMPLER_SPEED_VS_EULER_A,
    predict_eta,
    record_eta_error,
)
from stable_diffusion_webui_distributed_tpu.scheduler.worker import (  # noqa: F401
    State,
    WorkerNode,
    LocalBackend,
    StubBackend,
    HTTPBackend,
)
from stable_diffusion_webui_distributed_tpu.scheduler.world import (  # noqa: F401
    Job,
    World,
)

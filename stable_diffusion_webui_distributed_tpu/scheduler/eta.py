"""ETA prediction: benchmark-calibrated completion-time estimates.

Pure functions over a small calibration record — no I/O, and the only
global touch is the fire-and-forget MPE gauge mirror in
:func:`_note_obs` — so the whole model is unit-testable (the reference
buries this in its Worker class,
/root/reference/scripts/spartan/worker.py:176-286; formula reproduced here):

    eta = (n / ipm) * 60                      # base from benchmark ipm
        * (steps / benchmark_steps)           # step scaling
        * (pixels / benchmark_pixels)         # resolution scaling
        +- sampler_speed_percent              # sampler table below
        + hires pseudo-pass eta               # two-pass estimate
        - eta * mpe/100                       # mean-percent-error feedback

The MPE window keeps the last 5 measurements and rejects samples with
|error| >= 500% (worker.py:476-492) so one network hiccup cannot poison the
calibration.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.runtime.config import (
    BenchmarkPayload,
)

#: Relative speed of each sampler vs "Euler a", in percent; positive = faster.
#: Measured table reproduced from the reference (worker.py:75-94) — it feeds
#: scheduling only, never the numerics.
SAMPLER_SPEED_VS_EULER_A = {
    "DPM++ 2S a Karras": -45.87,
    "Euler": 4.92,
    "LMS": 12.66,
    "Heun": -40.24,
    "DPM2": -42.50,
    "DPM2 a": -46.60,
    "DPM++ 2S a": -37.10,
    "DPM++ 2M": 7.46,
    "DPM++ SDE": -39.45,
    "DPM fast": 15.54,
    "DPM adaptive": -61.40,
    "LMS Karras": 5,
    "DPM2 Karras": -41,
    "DPM2 a Karras": -38.81,
    "DPM++ 2M Karras": 16.20,
    "DPM++ SDE Karras": -39.71,
    "DDIM": 0,
    "PLMS": 9.31,
}

#: MPE feedback constants (reference worker.py:483-490).
MPE_WINDOW = 5
MPE_REJECT_ABS_PERCENT = 500.0

#: Compute-time priors per serving precision (pipeline/precision.py),
#: relative to the bf16 baseline the benchmark ipm was measured at. int8
#: MXU peak is 2x bf16 on v5e (394 vs 197 TFLOP/s, PERF.md) but a UNet
#: eval is not 100% MXU, so the prior is deliberately conservative; live
#: samples refine it per backend (:func:`record_eta_error`).
PRECISION_PRIOR: Dict[str, float] = {
    "bf16": 1.0,
    "int8": 0.55,
    "int8+conv": 0.5,
}
#: EWMA blend + clamp for the learned per-precision factor. The clamp
#: keeps one wild sample from collapsing the factor to ~0 (which would
#: make admission accept anything "because int8 is free").
PRECISION_EWMA_ALPHA = 0.3
PRECISION_FACTOR_MIN = 0.1
PRECISION_FACTOR_MAX = 1.5


@dataclasses.dataclass
class EtaCalibration:
    """Per-backend speed calibration (persisted in WorkerModel)."""

    avg_ipm: Optional[float] = None
    eta_percent_error: List[float] = dataclasses.field(default_factory=list)
    #: learned compute-time factor per non-bf16 serving precision
    #: (actual/predicted EWMA over that precision's OWN samples; bf16
    #: samples never touch it, and non-bf16 samples never touch
    #: ``eta_percent_error`` — the two calibrations are isolated so a
    #: fleet-degraded int8 burst cannot skew bf16 ETAs)
    precision_scale: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    @property
    def benchmarked(self) -> bool:
        return self.avg_ipm is not None and self.avg_ipm > 0

    def mpe(self) -> float:
        if not self.eta_percent_error:
            return 0.0
        return sum(self.eta_percent_error) / len(self.eta_percent_error)

    def precision_factor(self, precision: str) -> float:
        """Compute-time multiplier for a resolved precision name:
        the learned per-backend factor when samples exist, else the
        :data:`PRECISION_PRIOR`; bf16/empty is always 1.0."""
        if not precision or precision == "bf16":
            return 1.0
        learned = self.precision_scale.get(precision)
        if learned is not None:
            return learned
        return PRECISION_PRIOR.get(precision, 1.0)


def predict_eta(
    cal: EtaCalibration,
    payload,
    benchmark: Optional[BenchmarkPayload] = None,
    batch_size: Optional[int] = None,
    steps: Optional[int] = None,
    _include_hr: bool = True,
    queue_wait: float = 0.0,
    padding_overhead: float = 1.0,
    precision: str = "",
) -> float:
    """Seconds to complete ``payload`` on a backend calibrated as ``cal``.

    ``payload`` needs: steps, batch_size, width, height, sampler_name,
    enable_hr (+ hr_scale / hr_second_pass_steps when enabled) — i.e. a
    :class:`GenerationPayload` or anything duck-typed like one.

    When the backend fronts a serving dispatcher, ``padding_overhead``
    (>= 1, the bucket-px / requested-px factor from shape bucketing —
    padded pixels are denoised and decoded like real ones) scales the
    compute estimate, and ``queue_wait`` (seconds spent in the coalesce
    queue, typically ``ServingDispatcher.eta_overhead()``'s observed
    average) is added on top — wait is latency, not compute, so the MPE
    feedback never rescales it.

    ``precision``: resolved serving precision name — scales the COMPUTE
    part by :meth:`EtaCalibration.precision_factor` (int8's ~2x shows up
    here instead of skewing the bf16 calibration); the wait stays
    additive.
    """
    if not cal.benchmarked:
        raise ValueError("backend not benchmarked; run the benchmark first")
    bench = benchmark or BenchmarkPayload()

    n = payload.batch_size if batch_size is None else batch_size
    s = payload.steps if steps is None else steps

    eta = (n / cal.avg_ipm) * 60.0
    eta *= s / bench.steps

    if _include_hr and getattr(payload, "enable_hr", False):
        eta += _eta_hires(cal, payload, bench, batch_size=n)

    eta *= (payload.width * payload.height) / (bench.width * bench.height)

    sampler = getattr(payload, "sampler_name", "Euler a")
    delta = SAMPLER_SPEED_VS_EULER_A.get(sampler)
    if sampler != "Euler a" and delta is not None:
        # positive table entry = faster than Euler a -> smaller eta
        eta -= eta * (delta / 100.0) if delta > 0 else -eta * abs(delta) / 100.0

    eta *= max(1.0, padding_overhead)
    eta *= cal.precision_factor(precision)

    if cal.eta_percent_error:
        eta -= eta * (cal.mpe() / 100.0)
    return eta + max(0.0, queue_wait)


def _eta_hires(cal, payload, bench, batch_size) -> float:
    """Second-pass pseudo-payload estimate (reference worker.py:205-228)."""
    steps2 = getattr(payload, "hr_second_pass_steps", 0) or payload.steps
    scale = getattr(payload, "hr_scale", 2.0)

    pseudo = dataclasses.make_dataclass(
        "PseudoPayload",
        ["steps", "batch_size", "width", "height", "sampler_name",
         "enable_hr"],
    )(
        steps=steps2,
        batch_size=batch_size,
        width=math.floor(payload.width * scale),
        height=math.floor(payload.height * scale),
        sampler_name=getattr(payload, "sampler_name", "Euler a"),
        enable_hr=False,
    )
    return predict_eta(cal, pseudo, bench, _include_hr=False)


def admission_eta(
    cal: EtaCalibration,
    payload,
    benchmark: Optional[BenchmarkPayload] = None,
    steps: Optional[int] = None,
    queue_wait: float = 0.0,
    padding_overhead: float = 1.0,
    precision: str = "",
) -> float:
    """SLO-admission variant of :func:`predict_eta` (fleet/admission.py).

    Identical model, but when this calibration has no local error history
    the correction falls back to the process-wide MPE gauge
    (``sdtpu_eta_mpe_percent``, obs/prometheus.py) — a freshly registered
    backend then still benefits from the fleet's live calibration instead
    of admitting on raw benchmark arithmetic. Wait stays additive and is
    never rescaled by either correction (it is measured, not predicted).
    """
    eta = predict_eta(cal, payload, benchmark=benchmark, steps=steps,
                      padding_overhead=padding_overhead,
                      precision=precision)
    if not cal.eta_percent_error:
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                prometheus as obs_prom,
            )

            eta -= eta * (obs_prom.ETA_GAUGE.mpe() / 100.0)
        except Exception:  # noqa: BLE001 — importable without obs
            pass
    return max(0.0, eta) + max(0.0, queue_wait)


def record_eta_error(cal: EtaCalibration, predicted: float,
                     actual: float, precision: str = "") -> None:
    """Feed one (prediction, reality) pair back into the calibration.

    percent error = (predicted - actual)/actual * 100; |e| >= 500% rejected,
    window capped at MPE_WINDOW most-recent samples (worker.py:476-492).

    Samples from a non-bf16 ``precision`` update ONLY that precision's
    learned compute factor (clamped EWMA on actual/predicted) — they
    never enter ``eta_percent_error`` or the process-wide MPE gauge, so
    a fleet-degraded int8 burst cannot skew the bf16 calibration every
    other request admits against.
    """
    if actual <= 0 or predicted <= 0:
        return
    if precision and precision != "bf16":
        error = (predicted - actual) / actual * 100.0
        if abs(error) >= MPE_REJECT_ABS_PERCENT:
            return
        f_old = cal.precision_factor(precision)
        # predicted already includes f_old, so actual/predicted is the
        # multiplicative residual; EWMA-blend it into the factor
        f_new = f_old * ((1.0 - PRECISION_EWMA_ALPHA)
                         + PRECISION_EWMA_ALPHA * (actual / predicted))
        cal.precision_scale[precision] = min(
            PRECISION_FACTOR_MAX, max(PRECISION_FACTOR_MIN, f_new))
        return
    _note_obs(predicted, actual)
    error = (predicted - actual) / actual * 100.0
    if abs(error) >= MPE_REJECT_ABS_PERCENT:
        return
    cal.eta_percent_error.append(error)
    while len(cal.eta_percent_error) > MPE_WINDOW:
        cal.eta_percent_error.pop(0)


def _note_obs(predicted: float, actual: float) -> None:
    """Mirror the sample into the live process-wide MPE gauge exposed at
    ``/internal/metrics`` (obs/prometheus.py). The calibration math above
    stays pure — this is a fire-and-forget side channel that must never
    fail a request (and keeps this module importable without obs)."""
    try:
        from stable_diffusion_webui_distributed_tpu.obs import prometheus

        prometheus.ETA_GAUGE.record(predicted, actual)
    except Exception:  # noqa: BLE001 — pragma: no cover
        pass

"""ETA prediction: benchmark-calibrated completion-time estimates.

Pure functions over a small calibration record — no I/O, and the only
global touch is the fire-and-forget MPE gauge mirror in
:func:`_note_obs` — so the whole model is unit-testable (the reference
buries this in its Worker class,
/root/reference/scripts/spartan/worker.py:176-286; formula reproduced here):

    eta = (n / ipm) * 60                      # base from benchmark ipm
        * (steps / benchmark_steps)           # step scaling
        * (pixels / benchmark_pixels)         # resolution scaling
        +- sampler_speed_percent              # sampler table below
        + hires pseudo-pass eta               # two-pass estimate
        - eta * mpe/100                       # mean-percent-error feedback

The MPE window keeps the last 5 measurements and rejects samples with
|error| >= 500% (worker.py:476-492) so one network hiccup cannot poison the
calibration.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional

from stable_diffusion_webui_distributed_tpu.runtime.config import (
    BenchmarkPayload,
)

#: Relative speed of each sampler vs "Euler a", in percent; positive = faster.
#: Measured table reproduced from the reference (worker.py:75-94) — it feeds
#: scheduling only, never the numerics.
SAMPLER_SPEED_VS_EULER_A = {
    "DPM++ 2S a Karras": -45.87,
    "Euler": 4.92,
    "LMS": 12.66,
    "Heun": -40.24,
    "DPM2": -42.50,
    "DPM2 a": -46.60,
    "DPM++ 2S a": -37.10,
    "DPM++ 2M": 7.46,
    "DPM++ SDE": -39.45,
    "DPM fast": 15.54,
    "DPM adaptive": -61.40,
    "LMS Karras": 5,
    "DPM2 Karras": -41,
    "DPM2 a Karras": -38.81,
    "DPM++ 2M Karras": 16.20,
    "DPM++ SDE Karras": -39.71,
    "DDIM": 0,
    "PLMS": 9.31,
}

#: MPE feedback constants (reference worker.py:483-490).
MPE_WINDOW = 5
MPE_REJECT_ABS_PERCENT = 500.0


@dataclasses.dataclass
class EtaCalibration:
    """Per-backend speed calibration (persisted in WorkerModel)."""

    avg_ipm: Optional[float] = None
    eta_percent_error: List[float] = dataclasses.field(default_factory=list)

    @property
    def benchmarked(self) -> bool:
        return self.avg_ipm is not None and self.avg_ipm > 0

    def mpe(self) -> float:
        if not self.eta_percent_error:
            return 0.0
        return sum(self.eta_percent_error) / len(self.eta_percent_error)


def predict_eta(
    cal: EtaCalibration,
    payload,
    benchmark: Optional[BenchmarkPayload] = None,
    batch_size: Optional[int] = None,
    steps: Optional[int] = None,
    _include_hr: bool = True,
    queue_wait: float = 0.0,
    padding_overhead: float = 1.0,
) -> float:
    """Seconds to complete ``payload`` on a backend calibrated as ``cal``.

    ``payload`` needs: steps, batch_size, width, height, sampler_name,
    enable_hr (+ hr_scale / hr_second_pass_steps when enabled) — i.e. a
    :class:`GenerationPayload` or anything duck-typed like one.

    When the backend fronts a serving dispatcher, ``padding_overhead``
    (>= 1, the bucket-px / requested-px factor from shape bucketing —
    padded pixels are denoised and decoded like real ones) scales the
    compute estimate, and ``queue_wait`` (seconds spent in the coalesce
    queue, typically ``ServingDispatcher.eta_overhead()``'s observed
    average) is added on top — wait is latency, not compute, so the MPE
    feedback never rescales it.
    """
    if not cal.benchmarked:
        raise ValueError("backend not benchmarked; run the benchmark first")
    bench = benchmark or BenchmarkPayload()

    n = payload.batch_size if batch_size is None else batch_size
    s = payload.steps if steps is None else steps

    eta = (n / cal.avg_ipm) * 60.0
    eta *= s / bench.steps

    if _include_hr and getattr(payload, "enable_hr", False):
        eta += _eta_hires(cal, payload, bench, batch_size=n)

    eta *= (payload.width * payload.height) / (bench.width * bench.height)

    sampler = getattr(payload, "sampler_name", "Euler a")
    delta = SAMPLER_SPEED_VS_EULER_A.get(sampler)
    if sampler != "Euler a" and delta is not None:
        # positive table entry = faster than Euler a -> smaller eta
        eta -= eta * (delta / 100.0) if delta > 0 else -eta * abs(delta) / 100.0

    eta *= max(1.0, padding_overhead)

    if cal.eta_percent_error:
        eta -= eta * (cal.mpe() / 100.0)
    return eta + max(0.0, queue_wait)


def _eta_hires(cal, payload, bench, batch_size) -> float:
    """Second-pass pseudo-payload estimate (reference worker.py:205-228)."""
    steps2 = getattr(payload, "hr_second_pass_steps", 0) or payload.steps
    scale = getattr(payload, "hr_scale", 2.0)

    pseudo = dataclasses.make_dataclass(
        "PseudoPayload",
        ["steps", "batch_size", "width", "height", "sampler_name",
         "enable_hr"],
    )(
        steps=steps2,
        batch_size=batch_size,
        width=math.floor(payload.width * scale),
        height=math.floor(payload.height * scale),
        sampler_name=getattr(payload, "sampler_name", "Euler a"),
        enable_hr=False,
    )
    return predict_eta(cal, pseudo, bench, _include_hr=False)


def admission_eta(
    cal: EtaCalibration,
    payload,
    benchmark: Optional[BenchmarkPayload] = None,
    steps: Optional[int] = None,
    queue_wait: float = 0.0,
    padding_overhead: float = 1.0,
) -> float:
    """SLO-admission variant of :func:`predict_eta` (fleet/admission.py).

    Identical model, but when this calibration has no local error history
    the correction falls back to the process-wide MPE gauge
    (``sdtpu_eta_mpe_percent``, obs/prometheus.py) — a freshly registered
    backend then still benefits from the fleet's live calibration instead
    of admitting on raw benchmark arithmetic. Wait stays additive and is
    never rescaled by either correction (it is measured, not predicted).
    """
    eta = predict_eta(cal, payload, benchmark=benchmark, steps=steps,
                      padding_overhead=padding_overhead)
    if not cal.eta_percent_error:
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                prometheus as obs_prom,
            )

            eta -= eta * (obs_prom.ETA_GAUGE.mpe() / 100.0)
        except Exception:  # noqa: BLE001 — importable without obs
            pass
    return max(0.0, eta) + max(0.0, queue_wait)


def record_eta_error(cal: EtaCalibration, predicted: float,
                     actual: float) -> None:
    """Feed one (prediction, reality) pair back into the calibration.

    percent error = (predicted - actual)/actual * 100; |e| >= 500% rejected,
    window capped at MPE_WINDOW most-recent samples (worker.py:476-492).
    """
    if actual <= 0 or predicted <= 0:
        return
    _note_obs(predicted, actual)
    error = (predicted - actual) / actual * 100.0
    if abs(error) >= MPE_REJECT_ABS_PERCENT:
        return
    cal.eta_percent_error.append(error)
    while len(cal.eta_percent_error) > MPE_WINDOW:
        cal.eta_percent_error.pop(0)


def _note_obs(predicted: float, actual: float) -> None:
    """Mirror the sample into the live process-wide MPE gauge exposed at
    ``/internal/metrics`` (obs/prometheus.py). The calibration math above
    stays pure — this is a fire-and-forget side channel that must never
    fail a request (and keeps this module importable without obs)."""
    try:
        from stable_diffusion_webui_distributed_tpu.obs import prometheus

        prometheus.ETA_GAUGE.record(predicted, actual)
    except Exception:  # noqa: BLE001 — pragma: no cover
        pass

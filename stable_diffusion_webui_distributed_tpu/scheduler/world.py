"""World: the multi-backend job planner and request orchestrator.

Policy parity with the reference's scheduler
(/root/reference/scripts/spartan/world.py:37-601): equal split, stall
detection against the fastest backend, deferral of stalling backends,
round-robin redistribution of deferred + remainder images under pixel caps,
complementary "bonus" production in slack time, optional step scaling, and
elastic shrink/grow per request as backends fail and reconnect.

The orchestration differences are deliberate TPU redesigns:
- jobs carry an explicit ``start_index`` into the request's global image
  range, so merging is just concatenation in index order and every backend
  reproduces its images seed-exactly (the reference re-derives this with
  ``prior_images`` arithmetic at distributed.py:284-319);
- a failed job's range is re-queued to surviving backends (the reference
  simply drops those images, distributed.py:158-169).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.obs import (
    flightrec as obs_flightrec,
    journal as obs_journal,
    spans as obs_spans,
    watchdog as obs_watchdog,
)
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    GenerationResult,
)
from stable_diffusion_webui_distributed_tpu.runtime import config as config_mod
from stable_diffusion_webui_distributed_tpu.runtime.daemon import (
    StoppableDaemon,
)
from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger
from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
    State,
    WorkerNode,
)


class Job:
    """Work assigned to one backend (reference world.py:37-72)."""

    def __init__(self, worker: WorkerNode, batch_size: int):
        self.worker = worker
        self.batch_size = batch_size
        self.complementary = False
        self.step_override: Optional[int] = None
        self.start_index = 0          # global image index of this job's range
        self.result: Optional[GenerationResult] = None
        self.thread: Optional[threading.Thread] = None
        # latched by the hang watchdog (obs/watchdog.py) when this job
        # exceeds k x its ETA; execute() then abandons the thread and
        # requeues the range
        self.stalled = False

    def __str__(self):
        prefix = "(complementary) " if self.complementary else ""
        return (f"{prefix}Job: {self.batch_size} image(s) for "
                f"'{self.worker.label}'")

    def add_work(self, payload, batch_size: int = 1) -> bool:
        """Grow the job if the pixel cap allows (world.py:62-72;
        cap 0 = uncapped here vs the reference's -1)."""
        if self.worker.pixel_cap <= 0:
            self.batch_size += batch_size
            return True
        pixels = (self.batch_size + batch_size) * payload.width * payload.height
        if pixels <= self.worker.pixel_cap:
            self.batch_size += batch_size
            return True
        get_logger().debug("worker %s hit pixel cap (%d > %d)",
                           self.worker.label, pixels, self.worker.pixel_cap)
        return False


#: alwayson scripts that re-run generation themselves (post-process loops
#: like ADetailer's per-face img2img): distributing the outer request would
#: multiply their inner passes per worker and skew gallery accounting, so
#: such requests bypass distribution and run whole on the master — the
#: reference bails out of its hook the same way
#: (/root/reference/scripts/distributed.py:207-212).
SELF_LOOPING_SCRIPTS = frozenset({"adetailer", "ddetailer", "ddsd"})

#: Sanctioned chaos-injection hook (sim/chaos.py). When armed, it is
#: consulted once at the top of :meth:`World.execute` per request —
#: this is where step-indexed fault plans ("kill worker X at request N")
#: advance their request counter. ``None`` (the default) costs one
#: identity check.
CHAOS_HOOK = None


class World:
    """Backend registry + job planner + request executor."""

    def __init__(self, cfg: Optional[config_mod.ConfigModel] = None,
                 config_path: Optional[str] = None):
        self.cfg = cfg or config_mod.ConfigModel()
        self.config_path = config_path
        # registry membership only; per-worker mutable state has its own
        # lock on WorkerNode. HTTP handlers add/remove workers while ping
        # sweeps and request planning iterate the list
        self._registry_lock = threading.Lock()
        self.workers: List[WorkerNode] = []  # guarded-by: _registry_lock
        # serializes the make_jobs/optimize_jobs planning phase: the five
        # reference phases communicate through self.jobs, so two concurrent
        # execute() calls planning at once would interleave their job lists
        # (one request fanning out another's share). Execution itself —
        # fan-out threads + join — overlaps freely; only planning is brief
        # and serialized. Not a guarded-by annotation: self.jobs is read by
        # the phase helpers (realtime_jobs, job_stall, ...) whose callers
        # hold the lock for them, which is outside the lexical convention.
        self._plan_lock = threading.Lock()
        self.jobs: List[Job] = []
        self.job_timeout: float = self.cfg.job_timeout
        self.complement_production: bool = self.cfg.complement_production
        self.step_scaling: bool = self.cfg.step_scaling
        self.thin_client_mode = self.cfg.thin_client_mode
        # checkpoint + VAE the fleet should be on; synced to non-master
        # backends before each fan-out (reference option_payload per
        # request, distributed.py:260-318 + worker.py:342-343)
        self.current_model: str = self.cfg.default_model
        self.current_vae: str = ""
        # TLS verification for remotes added at runtime (reference
        # --distributed-skip-verify-remotes, distributed.py:38-46)
        self.verify_tls: bool = True
        # optional heartbeat prober (SDTPU_HEARTBEAT_S > 0): a daemon
        # sweep of ping_workers so UNAVAILABLE nodes recover without an
        # operator ping; off by default (no thread spawned)
        self._heartbeat: Optional[StoppableDaemon] = None
        self.start_heartbeat()
        # with SDTPU_FEDERATION on, this World is the metrics prober's
        # worker source (obs/federation.py); gate off = no registration
        try:
            from ..obs import federation as obs_federation

            if obs_federation.enabled():
                obs_federation.set_source(self)
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass
        # with SDTPU_PUSH on, the push control plane subscribes to this
        # World's workers' delta streams (obs/push.py); gate off = no
        # registration
        try:
            from ..obs import push as obs_push

            if obs_push.enabled():
                obs_push.set_source(self)
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass

    # -- registry -----------------------------------------------------------

    def add_worker(self, node: WorkerNode, *,
                   front: bool = False) -> WorkerNode:
        with self._registry_lock:
            if front:
                self.workers.insert(0, node)
            else:
                self.workers.append(node)
        return node

    def workers_snapshot(self) -> List[WorkerNode]:
        """Public point-in-time registry copy for cross-object readers
        (API handlers, CLI status) — see :meth:`_workers_snapshot`."""
        return self._workers_snapshot()

    def _workers_snapshot(self) -> List[WorkerNode]:
        """Registry membership at a point in time. Iterating the live list
        races the HTTP add/remove routes; every reader below works on a
        snapshot instead (workers themselves are thread-safe)."""
        with self._registry_lock:
            return list(self.workers)

    def get_worker(self, label: str) -> Optional[WorkerNode]:
        for w in self._workers_snapshot():
            if w.label == label:
                return w
        return None

    def get_workers(self) -> List[WorkerNode]:
        """Schedulable backends (reference world.py:405-416): skips
        UNAVAILABLE/DISABLED, invalid speeds, and the master in thin-client
        mode — the world elastically shrinks per request."""
        out = []
        for w in self._workers_snapshot():
            if w.cal.avg_ipm is not None and w.cal.avg_ipm <= 0:
                get_logger().warning(
                    "invalid benchmarked speed for '%s'; re-benchmark", w.label)
                continue
            if w.master and self.thin_client_mode:
                continue
            if w.available:
                out.append(w)
        return out

    def master(self) -> Optional[WorkerNode]:
        for w in self._workers_snapshot():
            if w.master:
                return w
        return None

    # -- planning -----------------------------------------------------------

    def default_batch_size(self, total_images: int) -> int:
        """Equal share per schedulable backend (world.py:111-115). May be 0
        when there are more backends than images — the remainder phase then
        places the images and zero-share jobs go complementary (the
        reference's world.py:506-510 case)."""
        n = max(1, len(self.get_workers()))
        return total_images // n

    def make_jobs(self, payload: GenerationPayload) -> List[Job]:
        """Initial equal split (world.py:378-392)."""
        self.jobs = []
        share = self.default_batch_size(payload.total_images)
        for w in self.get_workers():
            if not w.cal.benchmarked:
                w.benchmark()
                if not w.cal.benchmarked:
                    continue
            self.jobs.append(Job(w, share))
        return self.jobs

    def realtime_jobs(self) -> List[Job]:
        return [j for j in self.jobs
                if j.worker.cal.benchmarked and not j.complementary]

    def fastest_realtime_job(self) -> Job:
        return max(self.realtime_jobs(), key=lambda j: j.worker.cal.avg_ipm)

    def slowest_realtime_job(self) -> Job:
        return min(self.realtime_jobs(), key=lambda j: j.worker.cal.avg_ipm)

    def job_stall(self, worker: WorkerNode, payload,
                  batch_size: Optional[int] = None) -> float:
        """Extra wall-clock the gallery waits on ``worker`` vs the fastest
        backend at equal share (world.py:363-376)."""
        fastest = self.fastest_realtime_job().worker
        if worker is fastest:
            return 0.0
        return (worker.eta(payload, batch_size=batch_size)
                - fastest.eta(payload, batch_size=batch_size))

    def optimize_jobs(self, payload: GenerationPayload) -> List[Job]:
        """The five-phase policy (world.py:418-601), operating on the equal
        split from :meth:`make_jobs`."""
        log = get_logger()
        share = self.default_batch_size(payload.total_images)
        total = payload.total_images

        # phase 1: stall detection — defer slow backends. The base share is
        # also clamped to each worker's pixel cap (the reference only guards
        # *additional* work, world.py:62-72, letting the equal split itself
        # exceed the cap — an oversight we fix; overflow joins the deferred
        # pool for redistribution)
        per_image_px = payload.width * payload.height
        deferred = 0
        checked = 0
        for job in self.jobs:
            cap = job.worker.pixel_cap
            fit = share if cap <= 0 else min(share, cap // per_image_px)
            # stall is judged on what the worker would actually run — a
            # slow-but-capped worker may well finish its small clamped
            # batch inside the timeout
            lag = self.job_stall(job.worker, payload,
                                 batch_size=fit if fit > 0 else share)
            if lag < self.job_timeout or lag == 0:
                job.batch_size = fit
                checked += fit
                deferred += share - fit
                if cap > 0 and fit == 0 and share > 0:
                    # cap too small for even one image of this request
                    job.complementary = True
                continue
            log.debug("worker '%s' would stall the gallery by ~%.2fs; "
                      "deferring", job.worker.label, lag)
            job.complementary = True
            if deferred + checked + share <= total:
                deferred += share
            job.batch_size = 0

        # phase 2: round-robin deferred images onto realtime jobs that can
        # absorb them within the timeout + pixel cap (world.py:450-476)
        if deferred > 0:
            rt = [j for j in self.jobs if not j.complementary]
            saturated: set = set()
            i = 0
            while deferred > 0 and rt and len(saturated) < len(rt):
                job = rt[i % len(rt)]
                i += 1
                if id(job) in saturated:
                    continue
                stall = self.job_stall(job.worker, payload,
                                       batch_size=job.batch_size + 1)
                if stall < self.job_timeout and job.add_work(payload, 1):
                    deferred -= 1
                else:
                    saturated.add(id(job))
            if deferred > 0:
                log.warning("could not redistribute %d deferred image(s)",
                            deferred)

        # phase 3: remainder round-robin, smallest jobs first (482-510)
        assigned = sum(j.batch_size for j in self.jobs)
        remainder = total - assigned
        if remainder > 0:
            rt = sorted(self.realtime_jobs(), key=lambda j: j.batch_size)
            saturated = []
            while remainder > 0 and rt and len(saturated) < len(rt):
                for job in rt:
                    if remainder < 1:
                        break
                    if job in saturated:
                        continue
                    if job.add_work(payload, 1):
                        remainder -= 1
                    else:
                        saturated.append(job)
        # a realtime job left with zero images is effectively complementary
        for job in self.jobs:
            if job.batch_size == 0:
                job.complementary = True

        # phase 4: complementary production in the slack window (519-557)
        if self.complement_production and self.realtime_jobs():
            fastest = self.fastest_realtime_job()
            for job in self.jobs:
                if not job.complementary or not job.worker.cal.benchmarked:
                    continue
                slack = fastest.worker.eta(
                    payload, batch_size=max(1, fastest.batch_size)
                ) + self.job_timeout
                secs_per_image = job.worker.eta(payload, batch_size=1)
                bonus = int(slack / secs_per_image)
                log.debug("'%s': %d complementary image(s) = %.2fs slack / "
                          "%.2fs per image", job.worker.label, bonus, slack,
                          secs_per_image)
                if bonus > 0:
                    if not job.add_work(payload, bonus):
                        # pixel-cap ceiling (world.py:540-543)
                        per_image = payload.width * payload.height
                        cap_images = (job.worker.pixel_cap // per_image
                                      if job.worker.pixel_cap > 0 else 0)
                        if cap_images > 0:
                            job.add_work(payload, cap_images)
                elif self.step_scaling:
                    # one image at reduced steps (547-557)
                    secs_per_sample = job.worker.eta(payload, batch_size=1,
                                                     steps=1)
                    realtime_samples = int(slack // secs_per_sample)
                    if realtime_samples > 0:
                        job.add_work(payload, 1)
                        job.step_override = realtime_samples
                        log.debug("'%s' downscaled to %d steps",
                                  job.worker.label, realtime_samples)

        # phase 5: drop empty jobs (597-601); keep ordering master-first
        self.jobs = [j for j in self.jobs if j.batch_size > 0]

        # assign contiguous global ranges: master (or first) job leads so
        # local images land first in the gallery, like the reference's local
        # batch preceding injected worker batches (distributed.py:110-181)
        self.jobs.sort(key=lambda j: (not j.worker.master, j.worker.label))
        start = 0
        for job in self.jobs:
            job.start_index = start
            start += job.batch_size
        return self.jobs

    def _plan_no_split(self, payload: GenerationPayload) -> Optional[List[Job]]:
        """Whole-request plan on the single fastest backend that fits it.

        DPM adaptive's PID controller consumes ONE error norm over the whole
        batch (k-diffusion semantics; samplers/kdiffusion.py:479), so its
        step trajectory — and therefore every pixel — depends on batch
        composition: a 4-image job split 2+2 across workers produces
        different images than the same job run whole. To keep output
        independent of fleet topology, adaptive requests are never split
        (PARITY.md "DPM adaptive" contract exception). Returns None when no
        single benchmarked backend's pixel cap fits the request; the caller
        falls back to splitting with a loud warning."""
        total = payload.total_images
        px = payload.width * payload.height * total
        fits = [j.worker for j in self.jobs
                if j.worker.pixel_cap <= 0 or px <= j.worker.pixel_cap]
        if not fits:
            return None
        # apply the same stall-deferral gate as optimize_jobs phase 1:
        # a backend that would hold the gallery past job_timeout (vs the
        # fastest at this batch size) is skipped — unless every fitting
        # backend stalls, in which case a slow whole-request run still
        # beats splitting (splitting would change the adaptive trajectory
        # and therefore the pixels). Disabled/unbenchmarked workers were
        # already filtered by get_workers/make_jobs.
        unstalled = [w for w in fits
                     if self.job_stall(w, payload, batch_size=total)
                     < self.job_timeout]
        pool = unstalled or fits
        # deterministic tie-break on equal avg_ipm: lowest label wins
        best = sorted(pool,
                      key=lambda w: (-(w.cal.avg_ipm or 0.0), w.label))[0]
        job = Job(best, total)
        job.start_index = 0
        return [job]

    def plan(self, payload: GenerationPayload) -> List[Job]:
        """make_jobs + optimize_jobs (reference update(), world.py:394-403).

        Raises instead of silently planning zero images when the request
        cannot be placed (e.g. every worker's pixel cap is below one image
        of this resolution) — an empty gallery must be an error, not a 200.

        DPM adaptive requests bypass optimize_jobs entirely and run whole
        on one backend (see _plan_no_split).
        """
        from stable_diffusion_webui_distributed_tpu.samplers.kdiffusion import (
            resolve_sampler,
        )

        with self._plan_lock:
            self.make_jobs(payload)
            if not self.jobs:
                raise RuntimeError("no benchmarked, reachable backends")
            if resolve_sampler(payload.sampler_name).adaptive:
                no_split = self._plan_no_split(payload)
                if no_split is not None:
                    self.jobs = no_split
                    return self.jobs
                get_logger().warning(
                    "DPM adaptive request (%d images) exceeds every single "
                    "backend's pixel cap; splitting across workers — the "
                    "PID controller's batch-global error norm makes split "
                    "output differ from a whole-batch run (PARITY.md "
                    "contract exception)", payload.total_images)
            jobs = self.optimize_jobs(payload)
        if payload.total_images > 0 and not any(
                j.batch_size > 0 for j in jobs):
            raise RuntimeError(
                "no backend can accept this request (pixel caps below one "
                f"image at {payload.width}x{payload.height}?)")
        return jobs

    # -- execution ----------------------------------------------------------

    def execute(self, payload: GenerationPayload) -> GenerationResult:
        """Plan, fan out, merge — the reference's request lifecycle
        (distributed.py:185-357) without HTTP in the hot path for the local
        backend. Failed jobs are re-queued to surviving backends (an
        improvement over the reference, which drops those images —
        SURVEY.md §5 failure handling)."""
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            apply_scripts,
            fix_seed,
        )

        from stable_diffusion_webui_distributed_tpu.runtime import (
            interrupt as interrupt_mod,
        )

        log = get_logger()
        if CHAOS_HOOK is not None:
            CHAOS_HOOK("world.execute", payload=payload)
        # a new top-level request resets the interrupt latch (webui clears
        # shared.state the same way at generation start) — otherwise a past
        # interrupt would make every remote's in-flight watchdog abort the
        # fresh fan-out at its first poll
        interrupt_mod.STATE.begin_request()
        # resolve random seeds ONCE before fan-out so every backend derives
        # the same contiguous per-image seed range (the reference fixes the
        # seed before building per-worker payloads, distributed.py:252-254)
        # native script expansion BEFORE planning: prompt matrix replaces
        # batch_size with the combination count, so jobs split the right
        # total (idempotent — a sub-range arriving over HTTP is pre-sliced)
        payload = apply_scripts(payload)
        payload = payload.model_copy()
        payload.seed = fix_seed(payload.seed)
        payload.subseed = fix_seed(payload.subseed)
        if payload.all_prompts and payload.context_chunks is None:
            # pin the request-wide context length BEFORE slicing so an
            # image's conditioning is independent of its worker slice /
            # dispatch group (engine.request_context_chunks). Thin-client
            # masters have no tokenizer; their fleets fall back to
            # per-slice padding (documented in payload.py).
            engine = next(
                (w.backend.engine for w in self._workers_snapshot()
                 if hasattr(w.backend, "engine")), None)
            if engine is not None:
                payload.context_chunks = \
                    engine.request_context_chunks(payload)

        looping = [k for k in (payload.alwayson_scripts or {})
                   if k.lower() in SELF_LOOPING_SCRIPTS]
        if looping:
            # script will re-run generation itself: bail out of
            # distribution (reference distributed.py:207-212). The solo
            # backend must be SCHEDULABLE (not disabled / thin-client
            # master) and synced to the fleet checkpoint like any job.
            schedulable = self.get_workers()
            solo = next((w for w in schedulable if w.master),
                        next(iter(schedulable), None))
            if solo is None:
                raise RuntimeError("no backend available")
            log.info("script %s re-runs generation; bypassing distribution "
                     "and running on '%s'", looping, solo.label)
            if self.current_model and not solo.master:
                if not solo.load_options(self.current_model,
                                         self.current_vae):
                    raise RuntimeError(
                        f"model sync to '{solo.label}' failed")
            result = solo.request(payload, 0, payload.total_images)
            if result is None:
                raise RuntimeError(
                    f"'{solo.label}' failed the undistributed request")
            result.parameters = payload.model_dump()
            result.worker_labels = [solo.label] * len(result.images)
            self.save_config()
            return result

        jobs = self.plan(payload)
        summary = ", ".join(
            f"{j.worker.label}:{j.batch_size}"
            + ("*" if j.complementary else "") for j in jobs)
        log.info("distributing %d image(s): %s", payload.total_images, summary)

        rid = str(getattr(payload, "request_id", "")
                  or obs_spans.current_request_id() or "")
        if obs_journal.enabled():
            # post-fix_seed payload dump: the replay anchor — re-executing
            # this exact dump reproduces every per-image seed
            obs_journal.emit(
                "planned", rid, seed=payload.seed, subseed=payload.subseed,
                total=payload.total_images,
                payload=payload.model_dump(),
                fingerprint=obs_journal.fingerprint(payload.model_dump()),
                jobs=[{"worker": j.worker.label, "batch": j.batch_size,
                       "start": j.start_index,
                       "complementary": j.complementary} for j in jobs])

        with obs_spans.span("world.execute", images=payload.total_images,
                            jobs=len(jobs)):
            for job in jobs:
                job_payload = payload
                if job.step_override is not None:
                    job_payload = payload.model_copy()
                    job_payload.steps = job.step_override
                # bind_current: fan-out threads must inherit the request
                # contextvar or RequestIdFilter/spans lose scheduler lines
                job.thread = threading.Thread(
                    target=obs_spans.bind_current(self._run_job),
                    args=(job, job_payload),
                    name=f"job-{job.worker.label}", daemon=True)
                job.thread.start()

            watchdogged = obs_watchdog.enabled()
            for job in jobs:
                if not watchdogged:
                    job.thread.join()
                    continue
                # a watchdog-latched stall abandons the (daemon) job thread
                # so its range falls into the requeue path below
                while job.thread.is_alive() and not job.stalled:
                    job.thread.join(0.1)

        # re-queue failed ranges on surviving workers (elastic recovery) —
        # but never after an interrupt: a job that died because the user
        # cancelled must not be re-fanned-out as fresh work
        if not interrupt_mod.STATE.flag.interrupted:
            failed = [j for j in jobs
                      if (j.result is None or j.stalled)
                      and not j.complementary]
            for job in failed:
                recovered = self._requeue_failed(job, payload)
                jobs.extend(recovered)
                self._note_job_failure(job, recovered, rid)

        merged = GenerationResult(parameters=payload.model_dump())
        for job in sorted(jobs, key=lambda j: j.start_index):
            # a stalled job's thread may still complete late; its range
            # was already requeued, so its result must not merge twice
            if job.result is None or job.stalled:
                continue
            r = job.result
            r.worker_labels = [job.worker.label] * len(r.images)
            # per-image worker attribution in infotext (the reference
            # rewrites gallery infotexts the same way, distributed.py:343-349)
            r.infotexts = [
                f"{t}, Worker Label: {job.worker.label}" if t else t
                for t in r.infotexts
            ]
            merged.extend(r)
        self.save_config()
        if obs_journal.enabled():
            # the journaled outcome tools/replay.py byte-compares against
            obs_journal.emit("completed", rid, images=len(merged.images),
                             seeds=list(merged.seeds),
                             infotexts=list(merged.infotexts))
        return merged

    def _note_job_failure(self, job: Job, recovered: List[Job],
                          rid: str) -> None:
        """Always-on failure bookkeeping for a failed/stalled remote job:
        a flight-recorder entry carrying the worker label, its state at
        failure and the requeue decision, the failed worker's requeue
        counter, and (when on) journal events."""
        n = sum(j.batch_size for j in recovered)
        if recovered:
            dests = ", ".join(f"{j.worker.label}:{j.batch_size}"
                              for j in recovered)
            decision = f"requeued {n}/{job.batch_size} image(s) -> {dests}"
        else:
            decision = (f"dropped {job.batch_size} image(s) "
                        f"(no survivor could absorb them)")
        state = job.worker.current_state().name
        why = "stalled past the watchdog deadline on" if job.stalled \
            else "failed"
        job.worker.health.record_requeue(n)
        obs_flightrec.RECORDER.record(
            rid, "worker_failure",
            f"worker '{job.worker.label}' {why} {job.batch_size} image(s) "
            f"[{job.start_index}..{job.start_index + job.batch_size}); "
            f"state={state}; {decision}", events=[])
        if obs_journal.enabled():
            obs_journal.emit("job_failed", rid, worker=job.worker.label,
                             batch=job.batch_size, start=job.start_index,
                             stalled=job.stalled, state=state)
            obs_journal.emit("requeued", rid,
                             from_worker=job.worker.label, recovered=n,
                             dropped=job.batch_size - n,
                             to=[j.worker.label for j in recovered])

    def _requeue_failed(self, job: Job,
                        payload: GenerationPayload) -> List[Job]:
        """Recover a failed job's image range on surviving backends.

        The range is split across survivors under their pixel caps (same
        arithmetic as :meth:`Job.add_work`), fastest backend first so the
        recovery adds minimal wall-clock; a survivor that itself fails is
        skipped and the remainder tried on the next one. The failed job's
        ``step_override`` is re-applied so recovered images match what the
        original plan promised. Returns new result-carrying jobs covering
        as much of [start_index, start_index+batch_size) as survivors could
        absorb. (The reference drops failed ranges outright,
        /root/reference/scripts/distributed.py:158-169.)
        """
        log = get_logger()
        job_payload = payload
        if job.step_override is not None:
            job_payload = payload.model_copy()
            job_payload.steps = job.step_override

        per_image_px = payload.width * payload.height
        remaining = job.batch_size
        start = job.start_index
        dead = {id(job.worker)}
        recovered: List[Job] = []

        candidates = [w for w in self.get_workers() if id(w) not in dead]
        candidates.sort(key=lambda w: -(w.cal.avg_ipm or 0.0))
        for w in candidates:
            if remaining <= 0:
                break
            fit = remaining if w.pixel_cap <= 0 else min(
                remaining, w.pixel_cap // per_image_px)
            if fit <= 0:
                continue  # capped below one image of this resolution
            if self.current_model and not w.master:
                if not w.load_options(self.current_model, self.current_vae):
                    dead.add(id(w))
                    continue
            log.warning(
                "re-queueing %d image(s) [%d..%d) from failed '%s' to '%s'",
                fit, start, start + fit, job.worker.label, w.label)
            result = w.request(job_payload, start, fit)
            if result is None:
                dead.add(id(w))  # second failure: move on to the next
                continue
            nj = Job(w, fit)
            nj.start_index = start
            nj.step_override = job.step_override
            nj.result = result
            recovered.append(nj)
            start += fit
            remaining -= fit
        if remaining > 0:
            log.error("no survivor could absorb %d image(s) [%d..%d) from "
                      "failed '%s'", remaining, start, start + remaining,
                      job.worker.label)
        return recovered

    def _run_job(self, job: Job, payload: GenerationPayload) -> None:
        rid = str(getattr(payload, "request_id", "")
                  or obs_spans.current_request_id() or "")
        get_logger().info("job '%s': %d image(s) [%d..%d)",
                          job.worker.label, job.batch_size, job.start_index,
                          job.start_index + job.batch_size)
        if obs_journal.enabled():
            obs_journal.emit("job_dispatched", rid, worker=job.worker.label,
                             batch=job.batch_size, start=job.start_index)
        # sync the loaded checkpoint before generating (the reference sends
        # an option_payload with each request when the worker's cached model
        # differs, worker.py:342-343,646-688); load_options no-ops when the
        # cache matches and respects per-worker model_override
        if self.current_model and not job.worker.master:
            if not job.worker.load_options(self.current_model,
                                           self.current_vae):
                job.result = None
                return
        eta_s = None
        if obs_watchdog.enabled() and job.worker.cal.benchmarked:
            try:
                eta_s = job.worker.eta(payload, batch_size=job.batch_size)
            except ValueError:
                eta_s = None
        stop = obs_watchdog.arm(
            rid, f"job-{job.worker.label}", eta_s,
            on_stall=lambda: setattr(job, "stalled", True))
        try:
            with obs_spans.span("scheduler.job", worker=job.worker.label,
                                batch=job.batch_size,
                                start=job.start_index):
                job.result = job.worker.request(payload, job.start_index,
                                                job.batch_size)
        finally:
            obs_watchdog.disarm(stop)
        if job.result is not None and obs_journal.enabled():
            obs_journal.emit("job_completed", rid, worker=job.worker.label,
                             batch=job.batch_size, start=job.start_index,
                             images=len(job.result.images))

    # -- cluster ops --------------------------------------------------------

    def ping_workers(self, indiscriminate: bool = False) -> Dict[str, bool]:
        """Health sweep (world.py:724-778): demote unreachable backends,
        revive reachable ones. ``indiscriminate`` probes DISABLED too."""
        results: Dict[str, bool] = {}
        threads = []

        def probe(w: WorkerNode):
            ok = w.reachable()
            results[w.label] = ok
            if ok:
                if w.state == State.UNAVAILABLE:
                    w.set_state(State.IDLE)
                    w._pin_refuted = False  # reconnect: list may differ
                if w.model_override and w.pin_validated is not True \
                        and not getattr(w, "_pin_refuted", False) \
                        and time.time() - getattr(
                            w, "_pin_checked_at", 0.0) >= 60.0:
                    # a pin accepted while the node was down (or loaded
                    # from config) gets checked on the first successful
                    # ping — typo'd pins surface here instead of at the
                    # next load_options failure (ref dropdown-constrained
                    # pins, ui.py:161-171). A positively REFUTED pin is
                    # not re-fetched every sweep (no per-ping RPC / log
                    # spam); the refuted latch clears when the pin is
                    # re-set (configure_worker) or the node reconnects
                    # from UNAVAILABLE (its model list may have changed).
                    # A node answering with an EMPTY list (still loading
                    # checkpoints?) is retried at most once a minute.
                    w._pin_checked_at = time.time()
                    try:
                        models = w.backend.available_models()
                    except Exception:  # noqa: BLE001 — stays unvalidated
                        return
                    if models:
                        w.pin_validated = w.model_override in models
                        if not w.pin_validated:
                            w._pin_refuted = True
                            get_logger().warning(
                                "worker '%s': pinned model '%s' not in its "
                                "model list", w.label, w.model_override)
            else:
                w.set_state(State.UNAVAILABLE)

        for w in self._workers_snapshot():
            if w.state == State.DISABLED and not indiscriminate:
                continue
            t = threading.Thread(target=obs_spans.bind_current(probe),
                                 args=(w,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        return results

    def start_heartbeat(self) -> Optional[StoppableDaemon]:
        """Spawn the heartbeat prober when ``SDTPU_HEARTBEAT_S`` > 0: a
        daemon running :meth:`ping_workers` every period so UNAVAILABLE
        workers recover to IDLE (and freshly dead ones are demoted)
        without operator traffic. Idempotent; returns the daemon handle,
        or None when the knob is off (the default — no thread)."""
        period = config_mod.env_float("SDTPU_HEARTBEAT_S", 0.0) or 0.0
        if period <= 0.0 or self._heartbeat is not None:
            return self._heartbeat

        def beat():
            try:
                self.ping_workers()
            except Exception as e:  # noqa: BLE001 — sweep must survive
                get_logger().debug("heartbeat sweep failed: %s", e)

        # immediate=False: nothing to probe at t=0, the fleet just pinged
        self._heartbeat = StoppableDaemon("worker-heartbeat", beat, period,
                                          immediate=False)
        self._heartbeat.start()
        return self._heartbeat

    def stop_heartbeat(self) -> None:
        if self._heartbeat is not None:
            self._heartbeat.stop(timeout_s=2.0)
            self._heartbeat = None

    def health_summary(self) -> Dict[str, Dict]:
        """Per-worker behavioural health + state: the autoscaler's
        residency/health input (fleet/slices.py) and the enriched
        ``GET /internal/workers`` body."""
        out: Dict[str, Dict] = {}
        for w in self._workers_snapshot():
            s = w.health.summary()
            s["state"] = w.current_state().name
            s["avg_ipm"] = w.cal.avg_ipm
            out[w.label] = s
        return out

    def interrupt_all(self) -> None:
        """Fan-out interrupt (world.py:173-179)."""
        for w in self._workers_snapshot():
            if w.state == State.WORKING:
                threading.Thread(target=obs_spans.bind_current(w.interrupt),
                                 daemon=True).start()

    def restart_all(self) -> Dict[str, bool]:
        """Fleet restart fan-out (reference ui.py:274-280 "Restart All
        Workers" -> worker.py:690-717 per-node /server-restart). The master
        is skipped — it restarts via its own /server-restart route."""
        results: Dict[str, bool] = {}
        threads = []

        def run(w: WorkerNode):
            results[w.label] = w.restart()

        for w in self._workers_snapshot():
            if w.master or w.state == State.DISABLED:
                continue
            t = threading.Thread(target=obs_spans.bind_current(run),
                                 args=(w,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        return results

    _UNSET = object()

    def configure_worker(self, label: str, model_override=_UNSET,
                         pixel_cap=_UNSET, disabled=_UNSET) -> bool:
        """Runtime worker-config surface (the reference's Worker Config tab,
        ui.py:90-214): set a checkpoint pin, pixel cap, or enable/disable —
        applied live and persisted. Returns False for an unknown label."""
        w = self.get_worker(label)
        if w is None:
            return False
        if model_override is not self._UNSET:
            w.model_override = model_override or None
            # provenance resets with the pin; the API layer promotes it to
            # True/False per its validation outcome, and ping_workers
            # re-checks anything not yet True
            w.pin_validated = None if w.model_override is None else False
            w._pin_refuted = False
            w._pin_checked_at = 0.0  # a fresh pin validates on next ping
        if pixel_cap is not self._UNSET and pixel_cap is not None:
            w.pixel_cap = max(0, int(pixel_cap))
        if disabled is not self._UNSET and disabled is not None:
            if disabled:
                w.set_state(State.DISABLED)
            elif w.state == State.DISABLED:
                w.set_state(State.IDLE)
        self.save_config()
        return True

    def add_remote_worker(self, label: str, address: str, port: int, *,
                          tls: bool = False, user: Optional[str] = None,
                          password: Optional[str] = None,
                          pixel_cap: int = 0) -> WorkerNode:
        """Register a new HTTP remote live (the reference's Worker Config
        "Add Worker" flow, ui.py:90-159): the node joins the registry
        immediately and is persisted. Raises ValueError on a duplicate
        label or missing address."""
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        if not label:
            raise ValueError("label required")
        if self.get_worker(label) is not None:
            raise ValueError(f"worker '{label}' already exists")
        if not address:
            raise ValueError("address required")
        backend = HTTPBackend(address, int(port), tls=tls, user=user,
                              password=password, verify_tls=self.verify_tls)
        node = WorkerNode(label, backend, pixel_cap=max(0, int(pixel_cap)),
                          benchmark_payload=self.cfg.benchmark_payload)
        self.add_worker(node)
        self.save_config()
        return node

    @staticmethod
    def _merged_endpoint(old, address, port, tls, user, password):
        """Current backend + pending field edits -> (address, port, tls,
        user, password). None keeps the stored value; empty strings clear
        credentials. ONE place owns this merge: the edit itself and the
        pre-edit validation probe must target the same endpoint."""
        new_address = address if address is not None else old.address
        if not new_address:
            raise ValueError("address required")
        return (new_address,
                int(port) if port is not None else old.port,
                bool(tls) if tls is not None else old.tls,
                (user if user is not None else old.user) or None,
                (password if password is not None else old.password) or None)

    def _remote_backend_of(self, label: str):
        """The worker's HTTPBackend, or raise/None per CRUD conventions."""
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        w = self.get_worker(label)
        if w is None:
            return None, None
        if w.master:
            raise ValueError("master has no remote endpoint to edit")
        if not isinstance(w.backend, HTTPBackend):
            raise ValueError(f"worker '{label}' is not an HTTP remote")
        return w, w.backend

    def candidate_backend(self, label: str, *, address=None, port=None,
                          tls=None, user=None, password=None):
        """A TRANSIENT HTTPBackend for the endpoint these pending edits
        would produce — used to validate (e.g. probe /sd-models) before
        the edit is applied. Caller must ``close()`` it. Returns None for
        an unknown label."""
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        w, old = self._remote_backend_of(label)
        if w is None:
            return None
        a, p, t, u, pw = self._merged_endpoint(old, address, port, tls,
                                               user, password)
        return HTTPBackend(a, p, tls=t, user=u, password=pw,
                           verify_tls=self.verify_tls)

    def update_worker_endpoint(self, label: str, *, address=None, port=None,
                               tls=None, user=None, password=None) -> bool:
        """In-place edit of a remote worker's address/port/tls/credentials
        (the reference's save-worker flow, ui.py:100-159, which updates a
        registered worker without re-adding it). Unspecified (None) fields
        keep their current values; empty strings clear credentials. On a
        real change the backend is rebuilt — a different endpoint is a
        different process, so cached sync state (loaded model/VAE, script
        support, memory) is forgotten and an UNAVAILABLE node gets a fresh
        chance; an ADDRESS change additionally resets speed calibration
        (the old machine's benchmark means nothing on the new one).
        Returns False for an unknown label; raises on the master."""
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        w, old = self._remote_backend_of(label)
        if w is None:
            return False
        merged = self._merged_endpoint(old, address, port, tls, user,
                                       password)
        if merged == (old.address, old.port, old.tls, old.user,
                      old.password):
            # no-op edit (the panel form re-sends unchanged fields): keep
            # the live backend, its sync caches, and the worker's state —
            # a rebuild would force a needless checkpoint re-sync and
            # revive a genuinely-down node
            return True
        a, p, t, u, pw = merged
        w.backend = HTTPBackend(a, p, tls=t, user=u, password=pw,
                                verify_tls=self.verify_tls)
        old.close()
        w.loaded_model = None
        w.loaded_vae = None
        w.supported_scripts = None
        w.free_memory = None
        if a != old.address:
            w.cal = type(w.cal)()  # fresh machine: re-benchmark from zero
        if w.state == State.UNAVAILABLE:
            w.set_state(State.IDLE)
        self.save_config()
        return True

    def remove_worker(self, label: str) -> bool:
        """Drop a non-master worker from the registry and the persisted
        config (reference Worker Config "Remove" flow, ui.py:173-186).
        Returns False for an unknown label; raises on the master — the
        reference's UI simply never offers it for removal."""
        w = self.get_worker(label)
        if w is None:
            return False
        if w.master:
            raise ValueError("cannot remove the master worker")
        with self._registry_lock:
            self.workers.remove(w)
        self.save_config()
        return True

    def apply_settings(self, settings: Dict) -> Dict:
        """Runtime scheduler settings (the reference's Settings tab fields,
        ui.py:26-55): job_timeout / complement_production / step_scaling,
        applied live and persisted. Returns the applied subset."""
        applied = {}
        if "job_timeout" in settings and settings["job_timeout"] is not None:
            self.job_timeout = float(settings["job_timeout"])
            applied["job_timeout"] = self.job_timeout
        for key in ("complement_production", "step_scaling"):
            if key in settings and settings[key] is not None:
                setattr(self, key, bool(settings[key]))
                applied[key] = getattr(self, key)
        if "thin_client_mode" in settings \
                and settings["thin_client_mode"] is not None:
            self.thin_client_mode = bool(settings["thin_client_mode"])
            applied["thin_client_mode"] = self.thin_client_mode
        if applied:
            self.save_config()
        return applied

    def benchmark_all(self, rebenchmark: bool = False) -> Dict[str, float]:
        """Benchmark every schedulable backend; remotes in parallel, master
        serial (the reference's executor quirk at world.py:262-263 lands in
        the same place: master synchronous, remotes threaded)."""
        out: Dict[str, float] = {}
        threads = []

        def run(w: WorkerNode):
            ipm = w.benchmark(rebenchmark)
            if ipm:
                out[w.label] = ipm

        for w in self.get_workers():
            if w.master:
                run(w)
            else:
                t = threading.Thread(target=obs_spans.bind_current(run),
                                     args=(w,), daemon=True)
                t.start()
                threads.append(t)
        for t in threads:
            t.join()
        self.save_config()
        return out

    def run_user_script(self) -> bool:
        """Execute the operator's ``sync*`` script, if present — the
        reference's user-script button (ui.py:26-55): a file named
        ``sync*`` under ``<config dir>/user/`` (typically an
        rsync-models-to-workers hook), launched via its shebang line
        (``sh`` when it has none). Returns False with a logged hint when
        no script exists."""
        import os
        import subprocess

        log = get_logger()
        base = os.path.dirname(os.path.abspath(
            self.config_path or config_mod.default_config_path()))
        user_dir = os.path.join(base, "user")
        script = None
        if os.path.isdir(user_dir):
            for name in sorted(os.listdir(user_dir)):
                path = os.path.join(user_dir, name)
                if name.startswith("sync") and os.path.isfile(path):
                    script = path
                    break  # first in sort order wins
        if script is None:
            log.error(
                "couldn't find user script: place a file named sync* "
                "under %s", user_dir)
            return False
        with open(script, "r", encoding="utf-8", errors="replace") as f:
            first = f.readline().strip()
        cmd = (first[2:].split() + [script] if first.startswith("#!")
               else ["sh", script])
        log.info("running user script %s", script)
        rc = subprocess.call(cmd)
        if rc != 0:
            log.error("user script exited %d", rc)
        return rc == 0

    def sync_models(self, model: str, vae: str = "") -> None:
        """Checkpoint-change fan-out (world.py:784-811): push the new model
        to every non-master backend without an override, in threads."""
        threads = []
        for w in self._workers_snapshot():
            if w.master or not w.available:
                continue
            t = threading.Thread(target=obs_spans.bind_current(w.load_options),
                                 args=(model, vae), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    # -- persistence --------------------------------------------------------

    def save_config(self) -> None:
        """Write calibration back into the config model (world.py:705-722).

        A master entry persisted earlier survives even when this World was
        built without a local engine (status/ping runs) — otherwise those
        commands would erase the master's calibration."""
        workers = self._workers_snapshot()
        worker_entries = []
        if not any(w.master for w in workers):
            for entry in self.cfg.workers:
                for label, wm in entry.items():
                    if wm.master:
                        worker_entries.append({label: wm})
        for w in workers:
            model = config_mod.WorkerModel(
                avg_ipm=w.cal.avg_ipm,
                master=w.master,
                eta_percent_error=list(w.cal.eta_percent_error),
                pixel_cap=w.pixel_cap,
                disabled=w.state == State.DISABLED,
                model_override=w.model_override,
            )
            # keep address/port/credentials when the backend is remote
            backend = w.backend
            if hasattr(backend, "address"):
                model.address = backend.address
                model.port = backend.port
                model.tls = getattr(backend, "tls", False)
                model.user = getattr(backend, "user", None)
                model.password = getattr(backend, "password", None)
            worker_entries.append({w.label: model})
        self.cfg.workers = worker_entries
        self.cfg.job_timeout = int(self.job_timeout)
        self.cfg.complement_production = self.complement_production
        self.cfg.step_scaling = self.step_scaling
        self.cfg.thin_client_mode = self.thin_client_mode
        if self.config_path:
            config_mod.save_config(self.cfg, self.config_path)

    def master_calibration(self) -> Optional[config_mod.WorkerModel]:
        """The persisted master entry, if any (its calibration outlives the
        process even though its LocalBackend cannot be serialized)."""
        for entry in self.cfg.workers:
            for _, wm in entry.items():
                if wm.master:
                    return wm
        return None

    @classmethod
    def from_config(cls, cfg: config_mod.ConfigModel,
                    config_path: Optional[str] = None,
                    backend_factory=None,
                    verify_tls: bool = True) -> "World":
        """Rebuild a World from a persisted config: remote entries become
        HTTP backends; calibration survives restarts (world.py:661-703).

        Entries flagged ``master`` are NOT instantiated unless a
        ``backend_factory`` is given — a master's backend is the in-process
        engine, which the caller attaches itself (see cli._build_world);
        resurrecting it as an HTTP backend would dial our own port.
        """
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        world = cls(cfg, config_path)
        world.verify_tls = verify_tls
        for entry in cfg.workers:
            for label, wm in entry.items():
                if backend_factory is not None:
                    backend = backend_factory(label, wm)
                elif wm.master:
                    continue  # caller attaches the local engine
                else:
                    backend = HTTPBackend(wm.address, wm.port, tls=wm.tls,
                                          user=wm.user, password=wm.password,
                                          verify_tls=verify_tls)
                node = WorkerNode(
                    label, backend, master=wm.master,
                    pixel_cap=wm.pixel_cap, avg_ipm=wm.avg_ipm,
                    eta_percent_error=wm.eta_percent_error,
                    benchmark_payload=cfg.benchmark_payload,
                    model_override=wm.model_override,
                )
                if wm.disabled:
                    node.set_state(State.DISABLED)
                world.add_worker(node)
        return world

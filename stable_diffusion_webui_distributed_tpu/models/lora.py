"""LoRA adapters: kohya-format safetensors merged into Flax param trees.

The reference never touches LoRA math — each sdwui worker applies adapters
itself from the ``<lora:name:weight>`` prompt syntax, and the reference only
fans out ``/refresh-loras`` so workers re-scan their directories
(/root/reference/scripts/spartan/worker.py:577-581). Here the framework owns
the application: adapters are merged into the (already converted) Flax
params as ``W += weight * (alpha/rank) * up @ down``. Merging happens on
request boundaries host-side; the jitted graph sees ordinary params, so
switching adapters never retriggers compilation (params are inputs, not
constants — SURVEY.md §7 hard part #2).

Key format (kohya sd-scripts, the webui ecosystem standard):
``lora_unet_<ldm_module_path_with_underscores>.{lora_up,lora_down}.weight``
+ ``.alpha``; text encoder under ``lora_te_`` (``lora_te1_``/``lora_te2_``
for SDXL's two encoders).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from stable_diffusion_webui_distributed_tpu.models.configs import (
    ModelFamily,
    UNetConfig,
)
from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger

Array = np.ndarray


def load_lora(path: str) -> Dict[str, Array]:
    from stable_diffusion_webui_distributed_tpu.models.convert import (
        load_safetensors,
    )

    return load_safetensors(path)


def group_lora(sd: Dict[str, Array]) -> Dict[str, Dict[str, Array]]:
    """{module_key: {"up": .., "down": .., "alpha": ..}}."""
    groups: Dict[str, Dict[str, Array]] = {}
    for key, value in sd.items():
        if "." not in key:
            continue
        module, _, leaf = key.partition(".")
        g = groups.setdefault(module, {})
        if leaf.startswith("lora_up"):
            g["up"] = value
        elif leaf.startswith("lora_down"):
            g["down"] = value
        elif leaf == "alpha":
            g["alpha"] = value
    return groups


# --------------------------------------------------------------------------
# kohya module key -> (my param path, fused column slice)
# --------------------------------------------------------------------------

def _unet_block_index_maps(cfg: UNetConfig):
    """Replay ldm input/output block numbering (same walk as convert.py) to
    map block numbers -> my module names."""
    levels = list(zip(cfg.block_out_channels, cfg.down_blocks))
    in_map: Dict[int, str] = {}
    n = 1
    for level, (_, depth) in enumerate(levels):
        for i in range(cfg.layers_per_block):
            if depth is not None:
                in_map[n] = f"down_{level}_attn_{i}"
            n += 1
        if level < len(levels) - 1:
            n += 1  # downsample block: no attention
    out_map: Dict[int, str] = {}
    n = 0
    for level in reversed(range(len(levels))):
        _, depth = levels[level]
        for i in range(cfg.layers_per_block + 1):
            if depth is not None:
                out_map[n] = f"up_{level}_attn_{i}"
            n += 1
    return in_map, out_map


#: leaf name inside a transformer block -> (my path suffix, fused slot)
#: fused slot: (index, of) into the fused kernel's output columns
_ATTN_LEAVES = {
    "attn1_to_q": ("attn1/qkv", (0, 3)),
    "attn1_to_k": ("attn1/qkv", (1, 3)),
    "attn1_to_v": ("attn1/qkv", (2, 3)),
    "attn1_to_out_0": ("attn1/out_proj", None),
    "attn2_to_q": ("attn2/q", None),
    "attn2_to_k": ("attn2/kv", (0, 2)),
    "attn2_to_v": ("attn2/kv", (1, 2)),
    "attn2_to_out_0": ("attn2/out_proj", None),
    "ff_net_0_proj": ("geglu/proj", None),
    "ff_net_2": ("ff_out", None),
}


def _resolve_unet_key(module: str, cfg: UNetConfig
                      ) -> Optional[Tuple[List[str], Optional[Tuple[int, int]]]]:
    """kohya unet module key -> (path into my unet params, fused slot)."""
    in_map, out_map = _unet_block_index_maps(cfg)

    m = re.match(r"lora_unet_input_blocks_(\d+)_1_(.+)", module)
    base = None
    if m:
        base = in_map.get(int(m.group(1)))
        rest = m.group(2)
    else:
        m = re.match(r"lora_unet_output_blocks_(\d+)_1_(.+)", module)
        if m:
            base = out_map.get(int(m.group(1)))
            rest = m.group(2)
        else:
            m = re.match(r"lora_unet_middle_block_1_(.+)", module)
            if m:
                base = "mid_attn"
                rest = m.group(1)
    if base is None:
        return None

    if rest == "proj_in":
        return [base, "proj_in"], None
    if rest == "proj_out":
        return [base, "proj_out"], None
    m = re.match(r"transformer_blocks_(\d+)_(.+)", rest)
    if not m:
        return None
    block = f"block_{m.group(1)}"
    leaf = _ATTN_LEAVES.get(m.group(2))
    if leaf is None:
        return None
    suffix, slot = leaf
    return [base, block, *suffix.split("/")], slot


def _resolve_te_key(module: str, prefix: str
                    ) -> Optional[Tuple[List[str], Optional[Tuple[int, int]]]]:
    """kohya text-encoder module key -> path into my CLIP params."""
    m = re.match(
        rf"{prefix}_text_model_encoder_layers_(\d+)_(.+)", module)
    if not m:
        return None
    layer = f"layer_{m.group(1)}"
    rest = m.group(2)
    table = {
        "self_attn_q_proj": (["attn", "qkv"], (0, 3)),
        "self_attn_k_proj": (["attn", "qkv"], (1, 3)),
        "self_attn_v_proj": (["attn", "qkv"], (2, 3)),
        "self_attn_out_proj": (["attn", "out_proj"], None),
        "mlp_fc1": (["fc1"], None),
        "mlp_fc2": (["fc2"], None),
    }
    hit = table.get(rest)
    if hit is None:
        return None
    path, slot = hit
    return [layer, *path], slot


def _delta(g: Dict[str, Array]) -> Optional[Array]:
    """up @ down * alpha/rank, in torch (O, I) orientation."""
    up, down = g.get("up"), g.get("down")
    if up is None or down is None:
        return None
    if up.ndim == 4:  # 1x1 conv LoRA
        up = up[:, :, 0, 0]
    if down.ndim == 4:
        if down.shape[2:] != (1, 1):
            return None  # 3x3 conv (LoCon) unsupported for now
        down = down[:, :, 0, 0]
    rank = down.shape[0]
    alpha = float(g["alpha"]) if "alpha" in g else float(rank)
    return (up @ down) * (alpha / rank)


def merge_lora(
    params: Dict,
    lora_sd: Dict[str, Array],
    weight: float,
    family: ModelFamily,
    te_weight: Optional[float] = None,
) -> Tuple[Dict, int, int]:
    """Return a new params dict with the adapter merged at ``weight``.

    ``te_weight`` optionally scales text-encoder modules differently
    (webui's ``<lora:name:unet_w:te_w>`` dual-multiplier form); defaults to
    ``weight``. ``params`` is the engine's component dict ({"unet": ..,
    "text_encoder": .., ...}). Only touched leaves are re-allocated;
    everything else is shared. Returns (new_params, applied, skipped).
    """
    import jax.numpy as jnp

    if te_weight is None:
        te_weight = weight
    groups = group_lora(lora_sd)
    applied = skipped = 0
    out = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in params.items()}

    def patch(component: str, path: List[str],
              slot: Optional[Tuple[int, int]], delta: Array) -> bool:
        w = te_weight if component.startswith("text_encoder") else weight
        tree = out.get(component)
        if tree is None:
            return False
        # copy-on-write walk to the leaf dict
        node = tree
        for part in path[:-1]:
            child = node.get(part)
            if child is None:
                return False
            child = dict(child)
            node[part] = child
            node = child
        leaf = node.get(path[-1])
        if leaf is None or "kernel" not in leaf:
            return False
        kernel = leaf["kernel"]
        dk = jnp.asarray(delta.T, kernel.dtype) * w  # (I, O_sub)
        if slot is not None:
            idx, of = slot
            cols = kernel.shape[-1] // of
            if dk.shape != (kernel.shape[0], cols):
                return False
            start = idx * cols
            kernel = kernel.at[:, start:start + cols].add(dk)
        else:
            if dk.shape != kernel.shape:
                return False
            kernel = kernel + dk
        node[path[-1]] = {**leaf, "kernel": kernel}
        return True

    for module, g in groups.items():
        delta = _delta(g)
        if delta is None:
            skipped += 1
            continue
        resolved = None
        if module.startswith("lora_unet_"):
            r = _resolve_unet_key(module, family.unet)
            if r:
                resolved = ("unet", *r)
        elif module.startswith("lora_te1_"):
            r = _resolve_te_key(module, "lora_te1")
            if r:
                resolved = ("text_encoder", *r)
        elif module.startswith("lora_te2_"):
            r = _resolve_te_key(module, "lora_te2")
            if r:
                resolved = ("text_encoder_2", *r)
        elif module.startswith("lora_te_"):
            r = _resolve_te_key(module, "lora_te")
            if r:
                resolved = ("text_encoder", *r)
        if resolved is None:
            skipped += 1
            continue
        component, path, slot = resolved
        if patch(component, path, slot, delta):
            applied += 1
        else:
            skipped += 1

    if skipped:
        get_logger().debug("lora: %d module(s) applied, %d skipped",
                           applied, skipped)
    return out, applied, skipped


# --------------------------------------------------------------------------
# prompt syntax
# --------------------------------------------------------------------------

_LORA_TAG = re.compile(
    r"<lora:([^:>]+)(?::([0-9.+-]+))?(?::([0-9.+-]+))?>")


def extract_lora_tags(prompt: str
                      ) -> Tuple[str, List[Tuple[str, float, float]]]:
    """Strip webui ``<lora:name[:weight[:te_weight]]>`` extra-network tags.

    Returns (clean_prompt, [(name, unet_weight, te_weight), ...]). A single
    weight applies to both; omitted weights default to 1.0.
    """
    tags: List[Tuple[str, float, float]] = []

    def keep(m: re.Match) -> str:
        def num(g, default):
            try:
                return float(g) if g else default
            except ValueError:
                return default

        w = num(m.group(2), 1.0)
        te_w = num(m.group(3), w)
        tags.append((m.group(1), w, te_w))
        return ""

    clean = _LORA_TAG.sub(keep, prompt)
    return re.sub(r"\s{2,}", " ", clean).strip(), tags

"""LoRA adapters: kohya-format safetensors merged into Flax param trees.

The reference never touches LoRA math — each sdwui worker applies adapters
itself from the ``<lora:name:weight>`` prompt syntax, and the reference only
fans out ``/refresh-loras`` so workers re-scan their directories
(/root/reference/scripts/spartan/worker.py:577-581). Here the framework owns
the application: adapters are merged into the (already converted) Flax
params as ``W += weight * (alpha/rank) * up @ down``. Merging happens on
request boundaries host-side; the jitted graph sees ordinary params, so
switching adapters never retriggers compilation (params are inputs, not
constants — SURVEY.md §7 hard part #2).

Key format (kohya sd-scripts, the webui ecosystem standard):
``lora_unet_<ldm_module_path_with_underscores>.{lora_up,lora_down}.weight``
+ ``.alpha``; text encoder under ``lora_te_`` (``lora_te1_``/``lora_te2_``
for SDXL's two encoders).
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from stable_diffusion_webui_distributed_tpu.models.configs import (
    ModelFamily,
    UNetConfig,
)
from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger

Array = np.ndarray


def load_lora(path: str) -> Dict[str, Array]:
    from stable_diffusion_webui_distributed_tpu.models.convert import (
        load_safetensors,
    )

    return load_safetensors(path)


def group_lora(sd: Dict[str, Array]) -> Dict[str, Dict[str, Array]]:
    """{module_key: {"up": .., "down": .., "alpha": ..}}."""
    groups: Dict[str, Dict[str, Array]] = {}
    for key, value in sd.items():
        if "." not in key:
            continue
        module, _, leaf = key.partition(".")
        g = groups.setdefault(module, {})
        if leaf.startswith("lora_up"):
            g["up"] = value
        elif leaf.startswith("lora_down"):
            g["down"] = value
        elif leaf == "alpha":
            g["alpha"] = value
    return groups


# --------------------------------------------------------------------------
# kohya module key -> (my param path, fused column slice)
# --------------------------------------------------------------------------

def _unet_block_index_maps(cfg: UNetConfig):
    """Replay ldm input/output block numbering (same walk as convert.py) to
    map block numbers -> my module names."""
    levels = list(zip(cfg.block_out_channels, cfg.down_blocks))
    in_map: Dict[int, str] = {}
    n = 1
    for level, (_, depth) in enumerate(levels):
        for i in range(cfg.layers_per_block):
            if depth is not None:
                in_map[n] = f"down_{level}_attn_{i}"
            n += 1
        if level < len(levels) - 1:
            n += 1  # downsample block: no attention
    out_map: Dict[int, str] = {}
    n = 0
    for level in reversed(range(len(levels))):
        _, depth = levels[level]
        for i in range(cfg.layers_per_block + 1):
            if depth is not None:
                out_map[n] = f"up_{level}_attn_{i}"
            n += 1
    return in_map, out_map


#: leaf name inside a transformer block -> (my path suffix, fused slot)
#: fused slot: (index, of) into the fused kernel's output columns
_ATTN_LEAVES = {
    "attn1_to_q": ("attn1/qkv", (0, 3)),
    "attn1_to_k": ("attn1/qkv", (1, 3)),
    "attn1_to_v": ("attn1/qkv", (2, 3)),
    "attn1_to_out_0": ("attn1/out_proj", None),
    "attn2_to_q": ("attn2/q", None),
    "attn2_to_k": ("attn2/kv", (0, 2)),
    "attn2_to_v": ("attn2/kv", (1, 2)),
    "attn2_to_out_0": ("attn2/out_proj", None),
    "ff_net_0_proj": ("geglu/proj", None),
    "ff_net_2": ("ff_out", None),
}


def _resolve_unet_key(module: str, cfg: UNetConfig
                      ) -> Optional[Tuple[List[str], Optional[Tuple[int, int]]]]:
    """kohya unet module key -> (path into my unet params, fused slot)."""
    in_map, out_map = _unet_block_index_maps(cfg)

    m = re.match(r"lora_unet_input_blocks_(\d+)_1_(.+)", module)
    base = None
    if m:
        base = in_map.get(int(m.group(1)))
        rest = m.group(2)
    else:
        m = re.match(r"lora_unet_output_blocks_(\d+)_1_(.+)", module)
        if m:
            base = out_map.get(int(m.group(1)))
            rest = m.group(2)
        else:
            m = re.match(r"lora_unet_middle_block_1_(.+)", module)
            if m:
                base = "mid_attn"
                rest = m.group(1)
    if base is None:
        return None

    if rest == "proj_in":
        return [base, "proj_in"], None
    if rest == "proj_out":
        return [base, "proj_out"], None
    m = re.match(r"transformer_blocks_(\d+)_(.+)", rest)
    if not m:
        return None
    block = f"block_{m.group(1)}"
    leaf = _ATTN_LEAVES.get(m.group(2))
    if leaf is None:
        return None
    suffix, slot = leaf
    return [base, block, *suffix.split("/")], slot


def _resolve_te_key(module: str, prefix: str
                    ) -> Optional[Tuple[List[str], Optional[Tuple[int, int]]]]:
    """kohya text-encoder module key -> path into my CLIP params."""
    m = re.match(
        rf"{prefix}_text_model_encoder_layers_(\d+)_(.+)", module)
    if not m:
        return None
    layer = f"layer_{m.group(1)}"
    rest = m.group(2)
    table = {
        "self_attn_q_proj": (["attn", "qkv"], (0, 3)),
        "self_attn_k_proj": (["attn", "qkv"], (1, 3)),
        "self_attn_v_proj": (["attn", "qkv"], (2, 3)),
        "self_attn_out_proj": (["attn", "out_proj"], None),
        "mlp_fc1": (["fc1"], None),
        "mlp_fc2": (["fc2"], None),
    }
    hit = table.get(rest)
    if hit is None:
        return None
    path, slot = hit
    return [layer, *path], slot


def _delta(g: Dict[str, Array]) -> Optional[Array]:
    """up @ down * alpha/rank, in torch (O, I) orientation."""
    up, down = g.get("up"), g.get("down")
    if up is None or down is None:
        return None
    if up.ndim == 4:  # 1x1 conv LoRA
        up = up[:, :, 0, 0]
    if down.ndim == 4:
        if down.shape[2:] != (1, 1):
            return None  # 3x3 conv (LoCon) unsupported for now
        down = down[:, :, 0, 0]
    rank = down.shape[0]
    alpha = float(g["alpha"]) if "alpha" in g else float(rank)
    return (up @ down) * (alpha / rank)


def merge_lora(
    params: Dict,
    lora_sd: Dict[str, Array],
    weight: float,
    family: ModelFamily,
    te_weight: Optional[float] = None,
) -> Tuple[Dict, int, int]:
    """Return a new params dict with the adapter merged at ``weight``.

    ``te_weight`` optionally scales text-encoder modules differently
    (webui's ``<lora:name:unet_w:te_w>`` dual-multiplier form); defaults to
    ``weight``. ``params`` is the engine's component dict ({"unet": ..,
    "text_encoder": .., ...}). Only touched leaves are re-allocated;
    everything else is shared. Returns (new_params, applied, skipped).
    """
    import jax.numpy as jnp

    if te_weight is None:
        te_weight = weight
    groups = group_lora(lora_sd)
    applied = skipped = 0
    out = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in params.items()}

    def patch(component: str, path: List[str],
              slot: Optional[Tuple[int, int]], delta: Array) -> bool:
        w = te_weight if component.startswith("text_encoder") else weight
        tree = out.get(component)
        if tree is None:
            return False
        # copy-on-write walk to the leaf dict
        node = tree
        for part in path[:-1]:
            child = node.get(part)
            if child is None:
                return False
            child = dict(child)
            node[part] = child
            node = child
        leaf = node.get(path[-1])
        if leaf is None or "kernel" not in leaf:
            return False
        kernel = leaf["kernel"]
        dk = jnp.asarray(delta.T, kernel.dtype) * w  # (I, O_sub)
        if slot is not None:
            idx, of = slot
            cols = kernel.shape[-1] // of
            if dk.shape != (kernel.shape[0], cols):
                return False
            start = idx * cols
            kernel = kernel.at[:, start:start + cols].add(dk)
        else:
            if dk.shape != kernel.shape:
                return False
            kernel = kernel + dk
        node[path[-1]] = {**leaf, "kernel": kernel}
        return True

    for module, g in groups.items():
        delta = _delta(g)
        if delta is None:
            skipped += 1
            continue
        resolved = None
        if module.startswith("lora_unet_"):
            r = _resolve_unet_key(module, family.unet)
            if r:
                resolved = ("unet", *r)
        elif module.startswith("lora_te1_"):
            r = _resolve_te_key(module, "lora_te1")
            if r:
                resolved = ("text_encoder", *r)
        elif module.startswith("lora_te2_"):
            r = _resolve_te_key(module, "lora_te2")
            if r:
                resolved = ("text_encoder_2", *r)
        elif module.startswith("lora_te_"):
            r = _resolve_te_key(module, "lora_te")
            if r:
                resolved = ("text_encoder", *r)
        if resolved is None:
            skipped += 1
            continue
        component, path, slot = resolved
        if patch(component, path, slot, delta):
            applied += 1
        else:
            skipped += 1

    if skipped:
        get_logger().debug("lora: %d module(s) applied, %d skipped",
                           applied, skipped)
    return out, applied, skipped


# --------------------------------------------------------------------------
# traced adapters (SDTPU_LORA_TRACED): factors as jit ARGUMENTS
# --------------------------------------------------------------------------
#
# The merge path above bakes the adapter into the param tree — correct,
# but an adapter switch costs a host-side merge and (via the model epoch)
# retires every cache entry keyed on the engine fingerprint. The traced
# path instead hands the up/down factors to the jitted chunk executable
# as ordinary inputs (SwiftDiffusion, arxiv 2407.02031): shapes are held
# static by padding every site to a rank-bucket ladder and a slot-count
# ladder, so ONE executable serves any adapter combination inside a
# (rank_bucket, slot_count) cell and switching adapters recompiles
# nothing. Delta math at each Dense site, in flax (I, O) orientation:
#
#     y = x @ W + sum_s ((x @ down_s^T) @ up_s^T)        (scale in up_s)
#
# with ``down`` padded to [slots, rank_bucket, I] and ``up`` to
# [slots, O, rank_bucket]; zero padding is exact (extra ranks/slots
# contribute 0). Fused sites (attn qkv / kv) stack each adapter's
# sub-modules along the rank axis with the up rows placed block-wise, so
# a single site tensor carries q+k+v at effective rank <= 3r.

DEFAULT_RANK_LADDER: Tuple[int, ...] = (8, 16, 32, 64)
DEFAULT_SLOT_LADDER: Tuple[int, ...] = (1, 2, 4)

_SITE_RE = re.compile(r"^(down_\d+_attn_\d+|mid_attn|up_\d+_attn_\d+)$")
_BLOCK_RE = re.compile(r"^block_\d+$")
_LAYER_RE = re.compile(r"^layer_\d+$")

#: Dense leaves inside one transformer block that can carry a delta.
_BLOCK_LEAVES = (("attn1", "qkv"), ("attn1", "out_proj"), ("attn2", "q"),
                 ("attn2", "kv"), ("attn2", "out_proj"), ("geglu", "proj"),
                 ("ff_out",))
_TE_LEAVES = (("attn", "qkv"), ("attn", "out_proj"), ("fc1",), ("fc2",))


def traced_enabled() -> bool:
    """Live read of the traced-LoRA master knob (SDTPU_LORA_TRACED) —
    default OFF; the off path keeps the merge semantics byte-for-byte."""
    from stable_diffusion_webui_distributed_tpu.runtime.config import (
        env_flag,
    )

    return env_flag("SDTPU_LORA_TRACED", False)


def _ladder_strict(raw: str) -> Tuple[int, ...]:
    vals = tuple(sorted({int(p.strip()) for p in raw.split(",") if
                         p.strip()}))
    if not vals or any(v <= 0 for v in vals):
        raise ValueError("ladder needs positive ints")
    return vals


def rank_ladder() -> Tuple[int, ...]:
    from stable_diffusion_webui_distributed_tpu.runtime.config import (
        env_parsed,
    )

    return env_parsed("SDTPU_LORA_RANKS", _ladder_strict,
                      DEFAULT_RANK_LADDER, "comma list of ranks")


def slot_ladder() -> Tuple[int, ...]:
    from stable_diffusion_webui_distributed_tpu.runtime.config import (
        env_parsed,
    )

    return env_parsed("SDTPU_LORA_SLOTS", _ladder_strict,
                      DEFAULT_SLOT_LADDER, "comma list of slot counts")


def _bucket(value: int, ladder: Tuple[int, ...]) -> Optional[int]:
    for rung in ladder:
        if value <= rung:
            return rung
    return None


def bucket_rank(rank: int) -> Optional[int]:
    """Quantize an effective site rank onto the static ladder (None when
    it exceeds the top rung — the set then falls back to the merge
    path). The ladder is what keeps a request-derived rank from minting
    executables (sdtpu-lint RC001 discipline)."""
    return _bucket(int(rank), rank_ladder())


def bucket_slots(n: int) -> Optional[int]:
    """Quantize an adapter count onto the slot ladder."""
    return _bucket(int(n), slot_ladder())


def site_inventory(params: Dict) -> Dict[str, Dict[Tuple[str, ...],
                                                   Tuple[int, int]]]:
    """Every Dense site a kohya adapter can target, per component:
    {component: {path_tuple: (in_dim, out_dim)}} from the engine's actual
    param tree. The FULL inventory (not just touched sites) is what keeps
    the traced pytree STRUCTURE constant across adapter sets, so one
    executable serves them all."""
    out: Dict[str, Dict[Tuple[str, ...], Tuple[int, int]]] = {}

    def kernel_of(tree, path):
        node = tree
        for part in path:
            node = node.get(part) if isinstance(node, dict) else None
            if node is None:
                return None
        k = node.get("kernel") if isinstance(node, dict) else None
        return None if k is None or getattr(k, "ndim", 0) != 2 else k

    unet = params.get("unet") or {}
    sites: Dict[Tuple[str, ...], Tuple[int, int]] = {}
    for name, sub in unet.items():
        if not _SITE_RE.match(name) or not isinstance(sub, dict):
            continue
        for proj in ("proj_in", "proj_out"):
            k = kernel_of(sub, (proj,))
            if k is not None:
                sites[(name, proj)] = (int(k.shape[0]), int(k.shape[1]))
        for block in sub:
            if not _BLOCK_RE.match(block):
                continue
            for leaf in _BLOCK_LEAVES:
                k = kernel_of(sub, (block,) + leaf)
                if k is not None:
                    sites[(name, block) + leaf] = (int(k.shape[0]),
                                                   int(k.shape[1]))
    out["unet"] = sites
    for comp in ("text_encoder", "text_encoder_2"):
        tree = params.get(comp)
        csites: Dict[Tuple[str, ...], Tuple[int, int]] = {}
        if isinstance(tree, dict):
            for name, sub in tree.items():
                if not _LAYER_RE.match(name) or not isinstance(sub, dict):
                    continue
                for leaf in _TE_LEAVES:
                    k = kernel_of(sub, leaf)
                    if k is not None:
                        csites[(name,) + leaf] = (int(k.shape[0]),
                                                  int(k.shape[1]))
        out[comp] = csites
    return out


class TracedSet:
    """One resolved adapter set in traced form: zero-padded factor trees
    plus the content address that replaces the model-epoch bump in cache
    keys. ``tree`` holds, per component, a nested dict mirroring the
    module paths with ``{"down": [S, rb, I], "up": [S, O, rb]}`` float32
    leaves (scale folded into ``up``)."""

    __slots__ = ("sig", "rank_bucket", "slots", "tree", "content",
                 "te_content", "specs", "applied", "skipped", "srcs")

    def __init__(self, sig: str, rank_bucket: int, slots: int, tree: Dict,
                 content: str, te_content: str, specs: Tuple,
                 applied: int, skipped: int, srcs: Tuple) -> None:
        self.sig = sig
        self.rank_bucket = rank_bucket
        self.slots = slots
        self.tree = tree
        self.content = content
        self.te_content = te_content
        self.specs = specs
        self.applied = applied
        self.skipped = skipped
        self.srcs = srcs  # adapter state dicts (id-staleness guard)


def _zero_tree(inventory: Dict, rb: int, sc: int) -> Dict:
    """Full-inventory zero factor tree at (rank_bucket, slot_count)."""
    tree: Dict = {}
    for comp, sites in inventory.items():
        ctree: Dict = {}
        for path, (i_dim, o_dim) in sites.items():
            node = ctree
            for part in path[:-1]:
                node = node.setdefault(part, {})
            node[path[-1]] = {
                "down": np.zeros((sc, rb, i_dim), np.float32),
                "up": np.zeros((sc, o_dim, rb), np.float32),
            }
        tree[comp] = ctree
    return tree


def _site_leaf(tree: Dict, comp: str, path: Tuple[str, ...]):
    node = tree.get(comp)
    for part in path:
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    return node


def _resolve_module(module: str, family: ModelFamily):
    """kohya module key -> (component, path_tuple, fused_slot) or None."""
    if module.startswith("lora_unet_"):
        r = _resolve_unet_key(module, family.unet)
        return ("unet", tuple(r[0]), r[1]) if r else None
    for prefix, comp in (("lora_te1_", "text_encoder"),
                         ("lora_te2_", "text_encoder_2"),
                         ("lora_te_", "text_encoder")):
        if module.startswith(prefix):
            r = _resolve_te_key(module, prefix.rstrip("_"))
            return (comp, tuple(r[0]), r[1]) if r else None
    return None


def _factor_pair(g: Dict[str, Array]):
    """(up [O_sub, r], down [r, I], alpha) or None (unsupported form)."""
    up, down = g.get("up"), g.get("down")
    if up is None or down is None:
        return None
    if up.ndim == 4:
        up = up[:, :, 0, 0]
    if down.ndim == 4:
        if down.shape[2:] != (1, 1):
            return None  # 3x3 conv (LoCon) unsupported, same as merge
        down = down[:, :, 0, 0]
    rank = int(down.shape[0])
    alpha = float(g["alpha"]) if "alpha" in g else float(rank)
    return np.asarray(up, np.float32), np.asarray(down, np.float32), alpha


def build_traced_set(specs, provider, family: ModelFamily,
                     params: Dict) -> Optional[TracedSet]:
    """Resolve ``specs`` ([(name, unet_w, te_w), ...], the
    extract_lora_tags form) into a :class:`TracedSet`, or None when the
    set cannot be bucketed (unknown adapter, rank/slot ladder exceeded)
    — the caller then falls back to the merge path."""
    inventory = site_inventory(params)
    sc = bucket_slots(max(1, len(specs)))
    if sc is None:
        return None

    # pass 1 — resolve every contribution and find the effective rank
    # per site (fused sites stack sub-modules along the rank axis)
    contribs = []   # (slot_idx, comp, path, fused, up, down, scale)
    site_rank: Dict[Tuple[str, Tuple[str, ...]], int] = {}
    hasher = hashlib.sha256()
    te_hasher = hashlib.sha256()
    te_touched = False
    srcs = []
    applied = skipped = 0
    for slot, (name, w, te_w) in enumerate(specs):
        sd = provider(name) if provider else None
        if sd is None:
            return None  # unresolvable adapter: merge path owns the warn
        srcs.append(sd)
        hasher.update(f"{name}|{w}|{te_w}".encode())
        groups = group_lora(sd)
        for module in sorted(groups):
            g = groups[module]
            pair = _factor_pair(g)
            resolved = _resolve_module(module, family)
            if pair is None or resolved is None:
                skipped += 1
                continue
            up, down, alpha = pair
            comp, path, fused = resolved
            leaf_dims = site_inventory_lookup(inventory, comp, path)
            if leaf_dims is None:
                skipped += 1
                continue
            weight = te_w if comp.startswith("text_encoder") else w
            scale = weight * alpha / down.shape[0]
            # effective rank is PER SLOT: each adapter owns its own rank
            # axis, and fused sub-modules (q+k+v) stack within it
            key = (slot, comp, path)
            site_rank[key] = site_rank.get(key, 0) + int(down.shape[0])
            contribs.append((slot, comp, path, fused, up, down, scale))
            hasher.update(module.encode())
            hasher.update(up.tobytes())
            hasher.update(down.tobytes())
            hasher.update(np.float32(scale).tobytes())
            if comp.startswith("text_encoder"):
                te_touched = True
                te_hasher.update(module.encode())
                te_hasher.update(up.tobytes())
                te_hasher.update(down.tobytes())
                te_hasher.update(np.float32(scale).tobytes())
            applied += 1
    if not contribs:
        return None
    rb = bucket_rank(max(site_rank.values()))
    if rb is None:
        return None

    # pass 2 — allocate the full-inventory zero tree and place factors
    tree = _zero_tree(inventory, rb, sc)
    cursor: Dict[Tuple[int, str, Tuple[str, ...]], int] = {}
    for slot, comp, path, fused, up, down, scale in contribs:
        leaf = _site_leaf(tree, comp, path)
        i_dim, o_dim = leaf["down"].shape[2], leaf["up"].shape[1]
        r = int(down.shape[0])
        if down.shape[1] != i_dim:
            continue  # dim mismatch (wrong-family adapter): stays zero
        ck = (slot, comp, path)
        at = cursor.get(ck, 0)
        if at + r > rb:
            continue
        cursor[ck] = at + r
        leaf["down"][slot, at:at + r, :] = down
        if fused is None:
            if up.shape[0] != o_dim:
                continue
            leaf["up"][slot, :, at:at + r] = up * scale
        else:
            idx, of = fused
            cols = o_dim // of
            if up.shape[0] != cols:
                continue
            leaf["up"][slot, idx * cols:(idx + 1) * cols, at:at + r] = \
                up * scale

    sig = f"lora:r{rb}s{sc}"
    return TracedSet(sig, rb, sc, tree, hasher.hexdigest(),
                     te_hasher.hexdigest() if te_touched else "",
                     tuple(specs), applied, skipped, tuple(srcs))


def site_inventory_lookup(inventory: Dict, comp: str,
                          path: Tuple[str, ...]):
    sites = inventory.get(comp)
    return sites.get(path) if sites else None


def zero_set(params: Dict, family: ModelFamily, rb: int,
             sc: int) -> TracedSet:
    """All-zero traced set at an explicit (rank_bucket, slot_count) —
    the warmup sweep's stand-in adapter (exact no-op contribution, same
    executable as any real set in the cell)."""
    rb = bucket_rank(rb) or rank_ladder()[-1]
    sc = bucket_slots(sc) or slot_ladder()[-1]
    tree = _zero_tree(site_inventory(params), rb, sc)
    return TracedSet(f"lora:r{rb}s{sc}", rb, sc, tree, "zero", "",
                     (), 0, 0, ())


def delta_out(x, site):
    """Traced delta at one Dense site: ``sum_s (x @ down_s^T) @ up_s^T``.

    ``site`` leaves are [S, rb, I] / [S, O, rb] (shared across the batch,
    the text-encoder form) or [B, S, rb, I] / [B, S, O, rb] (per-row sets,
    the batched UNet form). Returns the [B, T, O] contribution in
    ``x.dtype``; zero padding contributes exactly 0."""
    import jax.numpy as jnp

    down, up = site["down"], site["up"]
    if down.ndim == 4:  # per-row heterogeneous sets
        h = jnp.einsum("bti,bsri->bstr", x, down.astype(x.dtype))
        return jnp.einsum("bstr,bsor->bto", h, up.astype(x.dtype))
    h = jnp.einsum("bti,sri->bstr", x, down.astype(x.dtype))
    return jnp.einsum("bstr,sor->bto", h, up.astype(x.dtype))


def apply_site(y, x, lora, key: str):
    """``y + delta_out(x, lora[key])`` in ``y.dtype`` — the one-line hook
    the model code calls after each Dense site. Identity when ``lora`` is
    None (the default trace: the gated-off graph stays byte-identical) or
    the site is absent from the inventory."""
    site = None if lora is None else lora.get(key)
    if site is None:
        return y
    return y + delta_out(x, site).astype(y.dtype)


def stack_row_sets(sets: List[TracedSet], batch: int):
    """Stack per-row adapter sets into the batched [B, S, ...] delta tree
    for a coalesced group. All sets must share one (rank_bucket, slots)
    cell — the dispatcher's group key guarantees it. Short lists pad by
    repeating the last row (the pad-and-drop rows of the batch ladder)."""
    import jax.numpy as jnp
    from jax import tree_util

    assert sets, "stack_row_sets needs at least one row"
    cell = {(s.rank_bucket, s.slots) for s in sets}
    assert len(cell) == 1, f"heterogeneous cells in one group: {cell}"
    rows = list(sets) + [sets[-1]] * (batch - len(sets))
    return tree_util.tree_map(
        lambda *leaves: jnp.stack([jnp.asarray(a) for a in leaves]),
        *[r.tree for r in rows])


def broadcast_set(ts: TracedSet, batch: int):
    """One set for every row: the solo-dispatch batched tree."""
    import jax.numpy as jnp
    from jax import tree_util

    return tree_util.tree_map(
        lambda a: jnp.broadcast_to(jnp.asarray(a),
                                   (batch,) + a.shape),
        ts.tree)


# --------------------------------------------------------------------------
# prompt syntax
# --------------------------------------------------------------------------

_LORA_TAG = re.compile(
    r"<lora:([^:>]+)(?::([0-9.+-]+))?(?::([0-9.+-]+))?>")


def extract_lora_tags(prompt: str
                      ) -> Tuple[str, List[Tuple[str, float, float]]]:
    """Strip webui ``<lora:name[:weight[:te_weight]]>`` extra-network tags.

    Returns (clean_prompt, [(name, unet_weight, te_weight), ...]). A single
    weight applies to both; omitted weights default to 1.0.
    """
    tags: List[Tuple[str, float, float]] = []

    def keep(m: re.Match) -> str:
        def num(g, default):
            try:
                return float(g) if g else default
            except ValueError:
                return default

        w = num(m.group(2), 1.0)
        te_w = num(m.group(3), w)
        tags.append((m.group(1), w, te_w))
        return ""

    clean = _LORA_TAG.sub(keep, prompt)
    return re.sub(r"\s{2,}", " ", clean).strip(), tags

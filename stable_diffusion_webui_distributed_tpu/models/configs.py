"""Model architecture configs: SD 1.5, SDXL base/refiner, and tiny test models.

Shapes follow the published Stable Diffusion architectures (the ones every
sdwui node in the reference deployment serves remotely). A ``TINY`` family is
provided so the full pipeline runs in seconds on CPU for tests — same code
path, ~100k params.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    """Text-encoder transformer config (CLIP / OpenCLIP family)."""

    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_length: int = 77
    # "quick_gelu" (OpenAI CLIP, SD1.5) or "gelu" (OpenCLIP bigG, SDXL).
    hidden_act: str = "quick_gelu"
    # Project pooled EOS embedding (OpenCLIP bigG); 0 disables.
    projection_dim: int = 0
    # Which hidden state feeds cross-attention: 0 = final layer norm output,
    # 1 = penultimate layer ("clip skip 2" — SDXL always uses penultimate).
    default_skip: int = 0
    # webui re-applies the final LayerNorm to clip-skipped hidden states for
    # SD1.x; SDXL (sgm) uses the raw penultimate states.
    layernorm_skipped: bool = True


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    """Denoising UNet config (SD family).

    ``down_blocks`` entries are transformer depths per block: ``None`` means a
    plain ResNet block (no attention); an int is the number of transformer
    layers in each attention block at that resolution.
    """

    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    down_blocks: Tuple[Optional[int], ...] = (1, 1, 1, None)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    # Per-block head count; None derives heads from head_dim=64 (SDXL rule).
    num_attention_heads: Optional[int] = 8
    mid_block_depth: Optional[int] = 1  # transformer depth in the mid block
    # SDXL micro-conditioning: pooled text (1280) + 6 fourier-embedded
    # time_ids (6*256) -> MLP -> added to the timestep embedding.
    addition_embed_dim: int = 0  # 0 = disabled (SD1.5)
    addition_time_embed_dim: int = 256
    projection_input_dim: int = 2816


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    """AutoencoderKL config."""

    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    scaling_factor: float = 0.18215
    # Decode in f32 even under bf16 policy (visible banding otherwise).
    force_decoder_f32: bool = True


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    """A complete diffusion model family: text encoder(s) + UNet + VAE."""

    name: str = "sd15"
    text_encoder: CLIPTextConfig = dataclasses.field(default_factory=CLIPTextConfig)
    # SDXL's second (OpenCLIP bigG) encoder; None for SD1.5.
    text_encoder_2: Optional[CLIPTextConfig] = None
    unet: UNetConfig = dataclasses.field(default_factory=UNetConfig)
    vae: VAEConfig = dataclasses.field(default_factory=VAEConfig)
    # v-prediction (SD2.x-style) vs epsilon-prediction.
    prediction_type: str = "epsilon"

    @property
    def vae_scale_factor(self) -> int:
        """Image->latent downsampling: one 2x per VAE level transition
        (8 for every real SD family; derived so tiny test VAEs agree)."""
        return 2 ** (len(self.vae.block_out_channels) - 1)

    @property
    def inpaint(self) -> bool:
        """Inpainting-specialized checkpoint (ldm "hybrid" conditioning):
        the UNet eats [latent, mask, masked-image latent] — latent + 1 +
        latent channels (sd-v1-5-inpainting and friends)."""
        return self.unet.in_channels == 2 * self.vae.latent_channels + 1

    @property
    def context_dim(self) -> int:
        return self.unet.cross_attention_dim


# Alias kept for readability at call sites that only care about dimensions.
SDModelConfig = ModelFamily


SD15 = ModelFamily(name="sd15")

# SD 2.x: OpenCLIP ViT-H text encoder (penultimate layer + final LN, the
# ldm FrozenOpenCLIPEmbedder convention), 1024-dim cross-attention,
# head_dim-64 attention. "sd21" is the 768-v v-prediction model; "sd21-base"
# the 512 epsilon model (same weights layout — select via the <ckpt>.json
# family sidecar, as webui selects via the .yaml).
SD2_TEXT = CLIPTextConfig(hidden_size=1024, intermediate_size=4096,
                          num_layers=24, num_heads=16, hidden_act="gelu",
                          default_skip=1, layernorm_skipped=True)
_SD2_UNET = UNetConfig(cross_attention_dim=1024, num_attention_heads=None)

SD21 = ModelFamily(name="sd21", text_encoder=SD2_TEXT, unet=_SD2_UNET,
                   prediction_type="v_prediction")
SD21_BASE = ModelFamily(name="sd21-base", text_encoder=SD2_TEXT,
                        unet=_SD2_UNET)

SDXL_TEXT_L = CLIPTextConfig(hidden_size=768, intermediate_size=3072,
                             num_layers=12, num_heads=12, default_skip=1,
                             layernorm_skipped=False)
SDXL_TEXT_G = CLIPTextConfig(hidden_size=1280, intermediate_size=5120,
                             num_layers=32, num_heads=20, hidden_act="gelu",
                             projection_dim=1280, default_skip=1,
                             layernorm_skipped=False)

SDXL_BASE = ModelFamily(
    name="sdxl-base",
    text_encoder=SDXL_TEXT_L,
    text_encoder_2=SDXL_TEXT_G,
    unet=UNetConfig(
        block_out_channels=(320, 640, 1280),
        down_blocks=(None, 2, 10),
        cross_attention_dim=2048,
        num_attention_heads=None,  # heads = channels // 64
        mid_block_depth=10,
        addition_embed_dim=1280,
    ),
    vae=VAEConfig(scaling_factor=0.13025),
)

# SDXL refiner: single 1280-wide text encoder (bigG), 4-level UNet with
# depth-4 transformers, aesthetic-score conditioning (2560 proj input).
SDXL_REFINER = ModelFamily(
    name="sdxl-refiner",
    text_encoder=SDXL_TEXT_G,
    text_encoder_2=None,
    unet=UNetConfig(
        block_out_channels=(384, 768, 1536, 1536),
        down_blocks=(None, 4, 4, None),
        cross_attention_dim=1280,
        num_attention_heads=None,
        mid_block_depth=4,
        addition_embed_dim=1280,
        projection_input_dim=2560,
    ),
    vae=VAEConfig(scaling_factor=0.13025),
)

# Tiny family for CPU tests: same code path, trivially small.
TINY = ModelFamily(
    name="tiny",
    text_encoder=CLIPTextConfig(
        vocab_size=1024, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, max_length=77,
    ),
    unet=UNetConfig(
        block_out_channels=(32, 64),
        down_blocks=(1, 1),
        layers_per_block=1,
        cross_attention_dim=32,
        num_attention_heads=4,
        mid_block_depth=1,
    ),
    vae=VAEConfig(block_out_channels=(32, 32), layers_per_block=1),
)

# Tiny SDXL-shaped family: exercises dual encoders + micro-conditioning.
TINY_XL = ModelFamily(
    name="tiny-xl",
    text_encoder=CLIPTextConfig(
        vocab_size=1024, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, default_skip=1, layernorm_skipped=False,
    ),
    text_encoder_2=CLIPTextConfig(
        vocab_size=1024, hidden_size=48, intermediate_size=96,
        num_layers=2, num_heads=4, hidden_act="gelu",
        projection_dim=48, default_skip=1, layernorm_skipped=False,
    ),
    unet=UNetConfig(
        block_out_channels=(32, 64),
        down_blocks=(None, 2),
        layers_per_block=1,
        cross_attention_dim=80,
        num_attention_heads=4,
        mid_block_depth=2,
        addition_embed_dim=48,
        addition_time_embed_dim=8,
        projection_input_dim=48 + 6 * 8,
    ),
    vae=VAEConfig(block_out_channels=(32, 32), layers_per_block=1,
                  scaling_factor=0.13025),
)

# Tiny refiner-shaped family: single projected text encoder + the refiner's
# 5-element micro-conditioning (aesthetic score instead of target size).
TINY_REFINER = ModelFamily(
    name="tiny-refiner",
    text_encoder=CLIPTextConfig(
        vocab_size=1024, hidden_size=48, intermediate_size=96,
        num_layers=2, num_heads=4, hidden_act="gelu",
        projection_dim=48, default_skip=1, layernorm_skipped=False,
    ),
    unet=UNetConfig(
        block_out_channels=(32, 64),
        down_blocks=(None, 2),
        layers_per_block=1,
        cross_attention_dim=48,
        num_attention_heads=4,
        mid_block_depth=2,
        addition_embed_dim=48,
        addition_time_embed_dim=8,
        projection_input_dim=48 + 5 * 8,
    ),
    vae=VAEConfig(block_out_channels=(32, 32), layers_per_block=1,
                  scaling_factor=0.13025),
)

# Tiny v-prediction family: exercises the v-pred denoiser branch on CPU.
TINY_V = dataclasses.replace(TINY, name="tiny-v",
                             prediction_type="v_prediction")

# Inpainting-specialized variants (ldm "hybrid" conditioning, 9-channel
# conv_in: latent + mask + masked-image latent — sd-v1-5-inpainting,
# stable-diffusion-2-inpainting, sd_xl_base inpainting ports; webui
# detects these via the .yaml, here via conv_in shape at load).
SD15_INPAINT = dataclasses.replace(
    SD15, name="sd15-inpaint",
    unet=dataclasses.replace(SD15.unet, in_channels=9))
SD2_INPAINT = dataclasses.replace(
    SD21_BASE, name="sd2-inpaint",
    unet=dataclasses.replace(SD21_BASE.unet, in_channels=9))
SDXL_INPAINT = dataclasses.replace(
    SDXL_BASE, name="sdxl-inpaint",
    unet=dataclasses.replace(SDXL_BASE.unet, in_channels=9))
TINY_INPAINT = dataclasses.replace(
    TINY, name="tiny-inpaint",
    unet=dataclasses.replace(TINY.unet, in_channels=9))

FAMILIES = {f.name: f for f in (SD15, SD21, SD21_BASE, SDXL_BASE,
                                SDXL_REFINER, SD15_INPAINT, SD2_INPAINT,
                                SDXL_INPAINT, TINY, TINY_XL, TINY_REFINER,
                                TINY_V, TINY_INPAINT)}

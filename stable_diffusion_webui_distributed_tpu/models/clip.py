"""CLIP / OpenCLIP text encoders in Flax.

Replaces the text-conditioning stage the reference outsources to each sdwui
node (the ``prompt``/``negative_prompt`` fields of the payloads built at
/root/reference/scripts/distributed.py:239-265 are encoded by webui's bundled
CLIP on every worker). TPU-first choices: one fused QKV projection per layer
(bigger MXU matmuls than three separate GEMMs), bf16 compute with f32
layer-norm statistics, static 77-token sequence length (no dynamic shapes).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models.configs import CLIPTextConfig
from stable_diffusion_webui_distributed_tpu.models.lora import (
    apply_site as _lora_site,
)


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    if name == "gelu":
        return nn.gelu
    raise ValueError(f"unknown activation {name}")


def tower_fingerprint(cfg: Optional[CLIPTextConfig]) -> tuple:
    """Architecture identity of one text tower for content addressing.

    The embed cache (cache/keys.py) folds this into every conditioning
    key: two engines whose towers differ in ANY field that changes the
    computed hidden states (depth, width, activation, skip semantics,
    projection) must never share cached conditioning, even if their
    model names collide. ``None`` (no second tower) fingerprints as the
    empty tuple so SD1.x and SDXL keys can't alias.
    """
    if cfg is None:
        return ()
    return (cfg.vocab_size, cfg.hidden_size, cfg.intermediate_size,
            cfg.num_layers, cfg.num_heads, cfg.max_length, cfg.hidden_act,
            cfg.projection_dim, cfg.default_skip, cfg.layernorm_skipped)


class CLIPAttention(nn.Module):
    cfg: CLIPTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array,
                 lora=None) -> jax.Array:
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        # Fused QKV: one (hidden, 3*hidden) matmul keeps the MXU busy.
        qkv = nn.Dense(3 * c.hidden_size, dtype=self.dtype, name="qkv")(x)
        qkv = _lora_site(qkv, x, lora, "qkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], c.num_heads, head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        out = jax.nn.dot_product_attention(
            q, k, v, bias=mask.astype(q.dtype), scale=1.0 / head_dim**0.5
        )
        out = out.reshape(x.shape[0], x.shape[1], c.hidden_size)
        y = nn.Dense(c.hidden_size, dtype=self.dtype, name="out_proj")(out)
        return _lora_site(y, out, lora, "out_proj")


class CLIPLayer(nn.Module):
    cfg: CLIPTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array,
                 lora=None) -> jax.Array:
        c = self.cfg
        # Pre-LN transformer; layer norms in f32 for stable statistics.
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + CLIPAttention(c, dtype=self.dtype, name="attn")(
            h, mask, lora=None if lora is None else lora.get("attn"))
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        f = nn.Dense(c.intermediate_size, dtype=self.dtype, name="fc1")(h)
        f = _lora_site(f, h, lora, "fc1")
        f = _act(c.hidden_act)(f)
        h = nn.Dense(c.hidden_size, dtype=self.dtype, name="fc2")(f)
        h = _lora_site(h, f, lora, "fc2")
        return x + h


class CLIPTextModel(nn.Module):
    """Causal text transformer.

    ``__call__`` returns ``(context, pooled)``:

    - ``context``: the hidden states fed to UNet cross-attention, taken
      ``skip`` layers before the end (``skip=0`` → final-LN output, the SD1.5
      default; ``skip=1`` → penultimate layer, webui's "clip skip 2" and the
      SDXL convention).
    - ``pooled``: the EOS-position embedding of the *final* layer (after
      final LN), passed through ``text_projection`` when configured — SDXL's
      micro-conditioning input.
    """

    cfg: CLIPTextConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,            # (B, T) int32
        skip: Optional[int] = None,
        eos_index: Optional[jax.Array] = None,  # (B,) position of EOS token
        inject_values: Optional[jax.Array] = None,  # (B, T, H) learned vecs
        inject_mask: Optional[jax.Array] = None,    # (B, T, 1) 1 = replace
        lora=None,  # traced adapter tree (models/lora.py), None = no-op
    ):
        c = self.cfg
        skip = c.default_skip if skip is None else skip
        B, T = input_ids.shape

        tok = nn.Embed(c.vocab_size, c.hidden_size, dtype=self.dtype,
                       name="token_embedding")(input_ids)
        if inject_values is not None:
            # textual inversion: placeholder rows take their learned
            # vectors (models/embeddings.py); vectors are call arguments,
            # so switching embeddings never recompiles
            m = inject_mask.astype(self.dtype)
            tok = tok * (1.0 - m) + inject_values.astype(self.dtype) * m
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(0.01),
            (c.max_length, c.hidden_size),
        )
        x = tok + pos[None, :T].astype(self.dtype)

        causal = jnp.triu(jnp.full((T, T), -1e9), k=1)[None, None]

        hidden = None
        for i in range(c.num_layers):
            x = CLIPLayer(c, dtype=self.dtype, name=f"layer_{i}")(
                x, causal,
                lora=None if lora is None else lora.get(f"layer_{i}"))
            if i == c.num_layers - 1 - skip:
                hidden = x
        assert hidden is not None, f"skip={skip} exceeds depth {c.num_layers}"

        final_ln = nn.LayerNorm(dtype=jnp.float32, name="final_ln")
        final = final_ln(x)
        if skip == 0:
            context = final
        elif c.layernorm_skipped:
            # webui SD1.x clip-skip: earlier hidden state re-normalized by
            # the (shared) final LayerNorm.
            context = final_ln(hidden)
        else:
            context = hidden  # raw penultimate (SDXL/sgm convention)

        if eos_index is None:
            eos_index = jnp.argmax(input_ids, axis=-1)  # EOS has max token id
        pooled = jnp.take_along_axis(
            final, eos_index[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
        if c.projection_dim:
            pooled = nn.Dense(c.projection_dim, use_bias=False,
                              dtype=self.dtype, name="text_projection")(pooled)
        return context.astype(self.dtype), pooled.astype(self.dtype)


def pad_encoded_context(ctx: jax.Array, n_chunks: int,
                        tokens_per_chunk: int = 77) -> jax.Array:
    """Zero-pad an encoded ``(B, L, D)`` context along the sequence axis to
    ``n_chunks * tokens_per_chunk`` rows.

    Ragged conditioning encodes every prompt at its TRUE chunk count (so the
    embed cache key no longer depends on whatever the longest prompt in the
    group happened to be) and pads the *encoded* rows up to the group's
    context length afterwards. The padded rows are excluded from
    cross-attention by the per-row ``ctx_true`` mask, so their value never
    matters — zeros keep them inert in any unmasked consumer.
    """
    want = n_chunks * tokens_per_chunk
    have = ctx.shape[1]
    if have >= want:
        return ctx
    return jnp.pad(ctx, ((0, 0), (0, want - have), (0, 0)))

"""Textual-inversion embeddings (webui "extra networks" style).

Every sdwui worker in the reference deployment resolves embedding names
mentioned in the prompt text against its ``embeddings/`` directory and
splices the learned vectors into CLIP's token-embedding stream (the
reference ships prompts verbatim over HTTP, distributed.py:239-265, and
relies on webui to do this per node). This module owns it natively.

Supported file formats (webui's loader accepts all of these):

- ``.safetensors`` with ``emb_params`` (SD1/SD2 single-encoder), or
  ``clip_l``/``clip_g`` keys (SDXL dual-encoder).
- torch ``.pt`` with ``string_to_param`` (the classic A1111 training
  output), loaded via torch (CPU) when available.
- diffusers ``.bin``/``.pt`` minimal form: one tensor keyed by any name.

Injection model: the tokenizer emits ``n_vectors`` placeholder tokens per
mention; the text encoder replaces those rows of the token-embedding
lookup with the learned vectors (models/clip.py ``inject_*`` args) — the
vectors are jit *arguments*, so switching embeddings never recompiles.
"""

from __future__ import annotations

import os
from collections.abc import Mapping
from typing import Dict, List, Optional, Tuple

import numpy as np

from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger

#: embedding-file suffixes scanned by discover()
_SUFFIXES = (".safetensors", ".pt", ".bin")


class Embedding:
    """One loaded embedding: per-encoder vector stacks."""

    def __init__(self, name: str, clip_l: np.ndarray,
                 clip_g: Optional[np.ndarray] = None):
        # (n_vectors, hidden) float32
        self.name = name
        self.clip_l = np.asarray(clip_l, np.float32)
        self.clip_g = None if clip_g is None else np.asarray(clip_g,
                                                             np.float32)
        if self.clip_l.ndim == 1:
            self.clip_l = self.clip_l[None]
        if self.clip_g is not None and self.clip_g.ndim == 1:
            self.clip_g = self.clip_g[None]
        if self.clip_g is not None and \
                len(self.clip_g) != len(self.clip_l):
            raise ValueError(
                f"embedding '{name}': clip_l has {len(self.clip_l)} "
                f"vectors but clip_g has {len(self.clip_g)}")

    @property
    def n_vectors(self) -> int:
        return self.clip_l.shape[0]


def _from_state_dict(name: str, sd: Dict[str, np.ndarray]) -> Embedding:
    lowered = {k.lower(): v for k, v in sd.items()}
    if "clip_l" in lowered or "clip_g" in lowered:
        return Embedding(name, lowered["clip_l"], lowered.get("clip_g"))
    if "emb_params" in lowered:
        return Embedding(name, lowered["emb_params"])
    if "string_to_param" in sd:  # nested .pt layout
        inner = sd["string_to_param"]
        key = "*" if "*" in inner else next(iter(inner))
        return Embedding(name, np.asarray(inner[key], np.float32))
    if len(sd) == 1:  # diffusers minimal: {token: tensor}
        return Embedding(name, next(iter(sd.values())))
    raise ValueError(
        f"embedding '{name}': unrecognized keys {sorted(sd)[:4]}")


def load_embedding(path: str) -> Embedding:
    """Load one embedding file (see module docstring for formats)."""
    name = os.path.splitext(os.path.basename(path))[0]
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return _from_state_dict(name, load_file(path))
    # torch .pt / .bin
    import torch

    # weights_only: .pt embeddings are routinely downloaded from sharing
    # sites; a full unpickle would execute arbitrary code from a malicious
    # file. The safe unpickler covers every layout we parse (tensors,
    # Parameters, dict/str/int containers).
    sd = torch.load(path, map_location="cpu", weights_only=True)
    if "string_to_param" in sd:
        inner = {k: v.detach().float().numpy()
                 for k, v in sd["string_to_param"].items()}
        return _from_state_dict(name, {"string_to_param": inner})
    return _from_state_dict(
        name,
        {k: (v.detach().float().numpy() if hasattr(v, "detach") else
             np.asarray(v, np.float32))
         for k, v in sd.items()
         if hasattr(v, "shape")})


class EmbeddingStore:
    """Directory-backed registry: prompt-name -> lazily loaded Embedding.

    Matching is case-insensitive on the file stem, like webui's embedding
    database. Files that fail to load are logged and skipped (a bad file
    must not take down the node)."""

    def __init__(self, directory: Optional[str]):
        self._paths: Dict[str, str] = {}   # lowercase name -> path
        self._cache: Dict[str, Optional[Embedding]] = {}
        #: bumped on every rescan — consumers (engine cond cache) use it to
        #: invalidate anything derived from the file set
        self.generation = 0
        self.rescan(directory)

    def rescan(self, directory: Optional[str]) -> None:
        """Re-discover the directory in place. Engines hold a reference to
        this store, so a registry refresh must mutate it rather than build
        a new one (or generation would keep seeing the old file set)."""
        self.directory = directory
        self._paths = {}
        self._cache = {}
        self.generation += 1
        if directory and os.path.isdir(directory):
            for fn in sorted(os.listdir(directory)):
                if fn.endswith(_SUFFIXES):
                    stem = os.path.splitext(fn)[0]
                    self._paths[stem.lower()] = os.path.join(directory, fn)

    def names(self) -> List[str]:
        return sorted(self._paths)

    def lookup(self, name: str) -> Optional[Embedding]:
        key = name.lower()
        if key not in self._paths:
            return None
        if key not in self._cache:
            try:
                self._cache[key] = load_embedding(self._paths[key])
            except Exception as e:  # noqa: BLE001 — skip bad files
                get_logger().error("embedding '%s' failed to load: %s",
                                   name, e)
                self._cache[key] = None
        return self._cache[key]

    def vector_counts(self) -> "LazyCounts":
        """name -> n_vectors mapping for the tokenizer's placeholder runs.

        Lazy: iterating / truth-testing touches only the discovered file
        names; a file is loaded the first time its COUNT is read — i.e.
        only for embeddings actually mentioned in a prompt. An eager
        version unpickled every file in the directory on the node's first
        request for any prompt at all."""
        return LazyCounts(self)


class LazyCounts(Mapping):
    """Read-through name -> n_vectors view over an EmbeddingStore."""

    def __init__(self, store: EmbeddingStore):
        self._store = store

    def __iter__(self):
        return iter(self._store._paths)

    def __len__(self) -> int:
        return len(self._store._paths)

    def __getitem__(self, name: str) -> int:
        emb = self._store.lookup(name)
        if emb is None:  # unloadable file: absent (Mapping.get -> default)
            raise KeyError(name)
        return emb.n_vectors


#: (chunk_row, column, embedding_name, vector_index) — where tokenizer
#: placeholders landed; the engine turns these into injection arrays.
Injection = Tuple[int, int, str, int]


def build_injection_arrays(
    injections: List[Injection],
    n_chunks: int,
    width: int,
    store,
    hidden_l: int,
    hidden_g: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Injection list -> (mask (n,w,1), values_l (n,w,Hl), values_g (n,w,Hg)).

    Rows whose vectors don't match the encoder width are skipped with a
    log line (an SD1.5 embedding mentioned under SDXL, say) — degraded
    capability beats a crashed request, like the reference's sampler-404
    fallback (worker.py:457-467).
    """
    mask = np.zeros((n_chunks, width, 1), np.float32)
    val_l = np.zeros((n_chunks, width, hidden_l), np.float32)
    val_g = np.zeros((n_chunks, width, max(hidden_g, 1)), np.float32)
    for row, col, name, vec in injections:
        if row >= n_chunks:
            continue  # truncated by the max_chunks cap
        emb = store.lookup(name) if store is not None else None
        if emb is None:
            continue
        if emb.clip_l.shape[1] != hidden_l:
            get_logger().warning(
                "embedding '%s' width %d != encoder width %d; skipped",
                name, emb.clip_l.shape[1], hidden_l)
            continue
        if hidden_g and emb.clip_g is None:
            get_logger().warning(
                "embedding '%s' has no clip_g vectors for this SDXL "
                "encoder; skipped", name)
            continue
        if hidden_g and emb.clip_g.shape[1] != hidden_g:
            get_logger().warning(
                "embedding '%s' clip_g width %d != encoder width %d; "
                "skipped", name, emb.clip_g.shape[1], hidden_g)
            continue
        mask[row, col, 0] = 1.0
        val_l[row, col] = emb.clip_l[vec]
        if hidden_g and emb.clip_g is not None:
            val_g[row, col] = emb.clip_g[vec]
    return mask, val_l, val_g

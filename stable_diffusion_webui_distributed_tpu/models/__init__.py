"""Flax model zoo: CLIP text encoders, UNet, VAE — the compute substrate.

The reference delegates all of this to each node's AUTOMATIC1111 webui over
HTTP (/root/reference/scripts/spartan/worker.py:432-435 calls
``/sdapi/v1/txt2img``; the UNet/CLIP/VAE live in upstream webui). This
framework has no external substrate: the full diffusion stack is implemented
here as Flax modules compiled by XLA, designed TPU-first (NHWC layouts, bf16
matmuls with f32-pinned normalization, static shapes, scan-friendly loops).
"""

from stable_diffusion_webui_distributed_tpu.models.configs import (  # noqa: F401
    CLIPTextConfig,
    ModelFamily,
    SDModelConfig,
    UNetConfig,
    VAEConfig,
    SD15,
    SDXL_BASE,
    TINY,
)

"""CLIP BPE tokenizer, implemented natively (no network, no HF hub).

Every sdwui worker in the reference deployment tokenizes prompts with the
CLIP BPE vocabulary bundled in its webui install; the reference itself only
ships prompt *strings* over HTTP (payload fields built at
/root/reference/scripts/distributed.py:239-265). This framework encodes
prompts itself: a faithful byte-level BPE implementation that loads the
standard ``vocab.json`` + ``merges.txt`` pair from the model directory, and a
deterministic hash fallback so tiny-model tests need no vocabulary files.

The special-token ids (start 49406, end 49407) and the 77-token window match
the OpenAI CLIP release used by every SD checkpoint.
"""

from __future__ import annotations

import functools
import gzip
import html
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

BOS = 49406
EOS = 49407
MAX_LEN = 77

# OpenAI CLIP's pretokenizer: contractions, letter-only runs, SINGLE digits,
# punctuation runs (underscore counts as punctuation, not a word char).
# Original pattern: 's|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+
# expressed with Python-re unicode classes: [^\W\d_]+ == \p{L}+, \d == one
# decimal digit, (?:[^\s\w]|_)+ == run of non-space non-letter non-digit.
# Digits tokenize one-by-one ('4k' -> '4','k') exactly like every webui
# worker's bundled CLIP tokenizer, keeping conditioning seed-exact fleet-wide.
_WORD_RE = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d|[^\W\d_]+|\d|(?:[^\s\w]|_)+",
    re.IGNORECASE,
)


@functools.lru_cache()
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2/CLIP byte<->unicode table: every byte maps to a printable char."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _clean(text: str) -> str:
    text = html.unescape(html.unescape(text))
    return re.sub(r"\s+", " ", text).strip().lower()


class CLIPTokenizer:
    """Byte-level BPE with the CLIP end-of-word convention (``</w>``)."""

    def __init__(self, vocab: Dict[str, int], merges: Sequence[Tuple[str, str]]):
        self.vocab = vocab
        self.ranks = {m: i for i, m in enumerate(merges)}
        self.byte_encoder = _bytes_to_unicode()
        self._cache: Dict[str, List[str]] = {}
        self.bos = vocab.get("<|startoftext|>", BOS)
        self.eos = vocab.get("<|endoftext|>", EOS)

    @classmethod
    def load(cls, model_dir: str) -> "CLIPTokenizer":
        """Load ``vocab.json`` + ``merges.txt`` (or ``bpe_*.txt.gz``) from a dir."""
        vocab_path = os.path.join(model_dir, "vocab.json")
        merges_path = os.path.join(model_dir, "merges.txt")
        if os.path.exists(vocab_path) and os.path.exists(merges_path):
            with open(vocab_path, encoding="utf-8") as f:
                vocab = json.load(f)
            with open(merges_path, encoding="utf-8") as f:
                lines = f.read().split("\n")
            merges = [
                tuple(l.split()) for l in lines
                if l and not l.startswith("#") and len(l.split()) == 2
            ]
            return cls(vocab, merges)
        # Original CLIP release format: one gzipped merges file defines the
        # vocab implicitly (bytes + bytes</w> + merged pairs + specials).
        gz = [p for p in os.listdir(model_dir) if p.endswith(".txt.gz")] \
            if os.path.isdir(model_dir) else []
        if gz:
            with gzip.open(os.path.join(model_dir, gz[0]), "rt",
                           encoding="utf-8") as f:
                merges = [tuple(l.split()) for l in
                          f.read().split("\n")[1:48894 + 1] if l]
            chars = list(_bytes_to_unicode().values())
            tokens = chars + [c + "</w>" for c in chars]
            tokens += ["".join(m) for m in merges]
            tokens += ["<|startoftext|>", "<|endoftext|>"]
            vocab = {t: i for i, t in enumerate(tokens)}
            return cls(vocab, merges)
        raise FileNotFoundError(
            f"no CLIP vocabulary (vocab.json+merges.txt or *.txt.gz) in {model_dir}"
        )

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        word: List[str] = list(token[:-1]) + [token[-1] + "</w>"]
        while len(word) > 1:
            pairs = [(word[i], word[i + 1]) for i in range(len(word) - 1)]
            best = min(pairs, key=lambda p: self.ranks.get(p, 1 << 30))
            if best not in self.ranks:
                break
            merged: List[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and (word[i], word[i + 1]) == best:
                    merged.append(word[i] + word[i + 1])
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        self._cache[token] = word
        return word

    def encode(self, text: str) -> List[int]:
        """Raw BPE ids, no specials, no truncation."""
        ids: List[int] = []
        for w in _WORD_RE.findall(_clean(text)):
            w = "".join(self.byte_encoder[b] for b in w.encode("utf-8"))
            for piece in self._bpe(w):
                ids.append(self.vocab.get(piece, self.eos))
        return ids

    def __call__(self, texts: Sequence[str], max_length: int = MAX_LEN) -> np.ndarray:
        """Batch-encode to (B, max_length) int32 with BOS/EOS + EOS padding
        (CLIP pads with EOS; the pooled embedding reads argmax position)."""
        out = np.full((len(texts), max_length), self.eos, dtype=np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)[: max_length - 2]
            out[row, 0] = self.bos
            out[row, 1:1 + len(ids)] = ids
            out[row, 1 + len(ids)] = self.eos
        return out


class FallbackTokenizer:
    """Deterministic hash tokenizer for tests / tiny models.

    NOT a real vocabulary — maps each whitespace word to a stable id in
    ``[2, vocab_size)``. Lets the full pipeline run without CLIP vocab files.
    """

    def __init__(self, vocab_size: int = 1024):
        self.vocab_size = vocab_size
        self.bos = 0
        self.eos = 1

    def encode(self, text: str) -> List[int]:
        import hashlib

        ids = []
        for w in _clean(text).split():
            h = int(hashlib.sha256(w.encode()).hexdigest(), 16)
            ids.append(2 + h % (self.vocab_size - 2))
        return ids

    def __call__(self, texts: Sequence[str], max_length: int = MAX_LEN) -> np.ndarray:
        out = np.full((len(texts), max_length), self.eos, dtype=np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)[: max_length - 2]
            out[row, 0] = self.bos
            out[row, 1:1 + len(ids)] = ids
            out[row, 1 + len(ids)] = self.eos
        return out


def load_tokenizer(model_dir: Optional[str], vocab_size: int = 49408):
    """Best tokenizer available: real CLIP BPE if vocab files exist, else
    the deterministic fallback (logged once)."""
    if model_dir:
        try:
            return CLIPTokenizer.load(model_dir)
        except (FileNotFoundError, OSError):
            pass
    from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger

    get_logger().warning(
        "no CLIP vocab files found%s; using deterministic fallback tokenizer "
        "(fine for tests; supply vocab.json+merges.txt for real prompts)",
        f" in {model_dir}" if model_dir else "",
    )
    return FallbackTokenizer(vocab_size)

"""ESRGAN-family (RRDBNet) image upscalers, natively in JAX.

The reference fleet gets ESRGAN/RealESRGAN hires upscaling for free from
every sdwui worker's bundled model zoo (the webui hires-fix upscaler
dropdown the reference's ETA model accounts for at
/root/reference/scripts/spartan/worker.py:205-228). Here the architecture
is implemented natively: standard RRDBNet x4 — conv_first, nb x RRDB
(3 residual-dense blocks of 5 growth convs each), trunk conv, two nearest
x2 upsample convs, HR conv, final conv; LeakyReLU(0.2) activations.

Both public checkpoint layouts load:
- new arch (BasicSR / RealESRGAN): ``conv_first.*, body.N.rdb1.conv1.*,
  conv_body.*, conv_up1/2.*, conv_hr.*, conv_last.*``
- old arch (original ESRGAN): ``model.0.*, model.1.sub.N.RDB1.conv1.0.*,
  model.1.sub.{nb}.*, model.3/6/8/10.*`` — translated on load.

Weight files are ``.pth`` (torch pickles, loaded CPU-side) or
``.safetensors``. Inference is a jitted NHWC graph; the RRDB trunk runs as
one ``lax.scan`` over stacked block weights so 23-block models compile
fast and the MXU sees uniform convs.
"""

from __future__ import annotations

import functools
import re
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

_OLD_HEAD = {"0": "conv_first", "3": "conv_up1", "6": "conv_up2",
             "8": "conv_hr", "10": "conv_last"}


def _normalize_keys(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Old-arch ESRGAN keys -> new-arch names; new-arch passes through."""
    out = {}
    for key, v in sd.items():
        m = re.match(
            r"model\.1\.sub\.(\d+)\.RDB(\d)\.conv(\d)\.0\.(weight|bias)",
            key)
        if m:
            out[f"body.{m.group(1)}.rdb{m.group(2)}.conv{m.group(3)}."
                f"{m.group(4)}"] = v
            continue
        m = re.match(r"model\.1\.sub\.(\d+)\.(weight|bias)", key)
        if m:  # the trailing conv inside the trunk = conv_body
            out[f"conv_body.{m.group(2)}"] = v
            continue
        m = re.match(r"model\.(\d+)\.(weight|bias)", key)
        if m and m.group(1) in _OLD_HEAD:
            out[f"{_OLD_HEAD[m.group(1)]}.{m.group(2)}"] = v
            continue
        if key.startswith("model."):
            continue  # old-arch activation/upsample placeholders
        out[key.replace(".RDB", ".rdb")] = v
    return out


def convert_esrgan(sd: Dict) -> Dict:
    """torch state dict -> {conv_first, body(stacked), conv_body, conv_up1,
    conv_up2, conv_hr, conv_last} with NHWC-ready HWIO kernels."""
    sd = _normalize_keys({k: np.asarray(v) for k, v in sd.items()})

    def conv(name: str) -> Dict[str, jnp.ndarray]:
        w = sd[f"{name}.weight"]  # torch (O, I, kh, kw)
        return {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0)),
                "bias": jnp.asarray(sd[f"{name}.bias"])}

    in_ch = sd["conv_first.weight"].shape[1]
    if in_ch != 3:
        raise ValueError(
            f"unsupported RRDBNet input of {in_ch} channels (pixel-unshuffle"
            " x2 variants not supported; use an x4 model)")

    nb = 1 + max(int(re.match(r"body\.(\d+)\.", k).group(1))
                 for k in sd if k.startswith("body."))
    blocks: List[Dict] = []
    for i in range(nb):
        blocks.append({
            f"rdb{j}": {f"conv{k}": conv(f"body.{i}.rdb{j}.conv{k}")
                        for k in range(1, 6)}
            for j in range(1, 4)
        })
    body = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {
        "conv_first": conv("conv_first"),
        "body": body,
        "conv_body": conv("conv_body"),
        "conv_up1": conv("conv_up1"),
        "conv_up2": conv("conv_up2"),
        "conv_hr": conv("conv_hr"),
        "conv_last": conv("conv_last"),
    }


def _conv2d(p, x):
    return jax.lax.conv_general_dilated(
        x, p["kernel"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + p["bias"]


def _lrelu(x):
    return jnp.where(x >= 0, x, 0.2 * x)


def _rdb(p, x):
    cur = x
    for k in range(1, 5):
        cur = jnp.concatenate([cur, _lrelu(_conv2d(p[f"conv{k}"], cur))],
                              axis=-1)
    return x + 0.2 * _conv2d(p["conv5"], cur)


def _rrdb(p, x):
    y = _rdb(p["rdb1"], x)
    y = _rdb(p["rdb2"], y)
    y = _rdb(p["rdb3"], y)
    return x + 0.2 * y


def _nearest2x(x):
    return jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)


def rrdbnet_apply(params: Dict, img: jax.Array) -> jax.Array:
    """(B, H, W, 3) in [0,1] -> (B, 4H, 4W, 3)."""
    fea = _conv2d(params["conv_first"], img.astype(jnp.float32))
    trunk, _ = jax.lax.scan(
        lambda x, bp: (_rrdb(bp, x), None), fea, params["body"])
    fea = fea + _conv2d(params["conv_body"], trunk)
    fea = _lrelu(_conv2d(params["conv_up1"], _nearest2x(fea)))
    fea = _lrelu(_conv2d(params["conv_up2"], _nearest2x(fea)))
    return _conv2d(params["conv_last"],
                   _lrelu(_conv2d(params["conv_hr"], fea)))


def load_esrgan(path: str) -> Dict:
    """Load + convert a .pth / .safetensors RRDBNet checkpoint."""
    if path.lower().endswith(".safetensors"):
        from safetensors.numpy import load_file

        sd = dict(load_file(path))
    else:
        import torch

        sd = torch.load(path, map_location="cpu")
        if isinstance(sd, dict):
            for nest in ("params_ema", "params", "state_dict"):
                if nest in sd and isinstance(sd[nest], dict):
                    sd = sd[nest]
                    break
        sd = {k: v.detach().cpu().numpy() for k, v in sd.items()
              if hasattr(v, "detach")}
    return convert_esrgan(sd)


def make_upscaler(params: Dict):
    """-> upscale(imgs (B,H,W,3) [0,1], target_w, target_h): apply the
    model (repeatedly if needed) then lanczos-resize to the exact target —
    webui's upscale-then-shrink convention for fractional factors."""
    apply = jax.jit(functools.partial(rrdbnet_apply, params))

    def upscale(imgs, target_w: int, target_h: int):
        x = jnp.asarray(imgs, jnp.float32)
        while x.shape[1] < target_h or x.shape[2] < target_w:
            x = jnp.clip(apply(x), 0.0, 1.0)
        if (x.shape[1], x.shape[2]) != (target_h, target_w):
            x = jax.image.resize(
                x, (x.shape[0], target_h, target_w, x.shape[3]), "lanczos3")
            x = jnp.clip(x, 0.0, 1.0)
        return x

    return upscale

"""AutoencoderKL (VAE) in Flax: image <-> latent codec.

In the reference deployment this runs inside each sdwui worker; the master
only ever sees finished PNGs come back over HTTP
(/root/reference/scripts/distributed.py:103-106 decodes base64). Here the
decode stage is on the critical path after every denoise, so it is built to
overlap with the next batch's UNet work (separate jit unit) and defaults to
f32 (bf16 decode shows visible banding).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models.configs import VAEConfig
from stable_diffusion_webui_distributed_tpu.models.unet import GroupNorm32


class VAEResBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.silu(GroupNorm32(name="norm1")(x))
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv1")(h)
        h = nn.silu(GroupNorm32(name="norm2")(h))
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class VAEAttention(nn.Module):
    """Single-head spatial self-attention (the mid-block attn)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, H, W, C = x.shape
        h = GroupNorm32(name="norm")(x).reshape(B, H * W, C)
        qkv = nn.Dense(3 * C, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q[:, :, None]  # single head
        k = k[:, :, None]
        v = v[:, :, None]
        out = jax.nn.dot_product_attention(q, k, v, scale=1.0 / C**0.5)
        out = nn.Dense(C, dtype=self.dtype, name="out_proj")(out[:, :, 0])
        return x + out.reshape(B, H, W, C)


class Encoder(nn.Module):
    cfg: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.Conv(c.block_out_channels[0], (3, 3), padding=1,
                    dtype=self.dtype, name="conv_in")(images.astype(self.dtype))
        for level, ch in enumerate(c.block_out_channels):
            for i in range(c.layers_per_block):
                x = VAEResBlock(ch, dtype=self.dtype,
                                name=f"down_{level}_res_{i}")(x)
            if level < len(c.block_out_channels) - 1:
                x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=((0, 1), (0, 1)),
                            dtype=self.dtype, name=f"down_{level}_ds")(x)
        ch = c.block_out_channels[-1]
        x = VAEResBlock(ch, dtype=self.dtype, name="mid_res_0")(x)
        x = VAEAttention(dtype=self.dtype, name="mid_attn")(x)
        x = VAEResBlock(ch, dtype=self.dtype, name="mid_res_1")(x)
        x = nn.silu(GroupNorm32(name="norm_out")(x))
        # 2*latent moments (mean, logvar).
        x = nn.Conv(2 * c.latent_channels, (3, 3), padding=1,
                    dtype=self.dtype, name="conv_out")(x)
        return nn.Conv(2 * c.latent_channels, (1, 1), dtype=self.dtype,
                       name="quant_conv")(x)


class Decoder(nn.Module):
    cfg: VAEConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, latents: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.Conv(c.latent_channels, (1, 1), dtype=self.dtype,
                    name="post_quant_conv")(latents.astype(self.dtype))
        ch = c.block_out_channels[-1]
        x = nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype, name="conv_in")(x)
        x = VAEResBlock(ch, dtype=self.dtype, name="mid_res_0")(x)
        x = VAEAttention(dtype=self.dtype, name="mid_attn")(x)
        x = VAEResBlock(ch, dtype=self.dtype, name="mid_res_1")(x)
        for idx, level in enumerate(reversed(range(len(c.block_out_channels)))):
            ch = c.block_out_channels[level]
            for i in range(c.layers_per_block + 1):
                x = VAEResBlock(ch, dtype=self.dtype,
                                name=f"up_{level}_res_{i}")(x)
            if idx < len(c.block_out_channels) - 1:
                B, H, W, C = x.shape
                x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
                x = nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype,
                            name=f"up_{level}_us")(x)
        x = nn.silu(GroupNorm32(name="norm_out")(x))
        x = nn.Conv(c.in_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        return x.astype(jnp.float32)


class VAE(nn.Module):
    """Full codec. ``encode`` returns latent *moments*; sampling + scaling are
    done by the pipeline (so the RNG discipline stays in one place)."""

    cfg: VAEConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        dec_dtype = jnp.float32 if self.cfg.force_decoder_f32 else self.dtype
        self.encoder = Encoder(self.cfg, dtype=self.dtype)
        self.decoder = Decoder(self.cfg, dtype=dec_dtype)

    def encode(self, images: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """images (B,H,W,3) in [-1,1] -> (mean, logvar), each (B,h,w,C)."""
        moments = self.encoder(images)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, latents: jax.Array) -> jax.Array:
        """latents (B,h,w,C), already un-scaled -> images (B,H,W,3) in [-1,1]."""
        return self.decoder(latents)

    def __call__(self, images: jax.Array, key: jax.Array) -> jax.Array:
        mean, logvar = self.encode(images)
        z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
            key, mean.shape, mean.dtype
        )
        return self.decode(z)

"""Checkpoint conversion: SD single-file (ldm) state dicts -> Flax param trees.

webui nodes in the reference deployment load ``*.safetensors`` single-file
checkpoints by name (synced across workers via ``/sdapi/v1/options``,
/root/reference/scripts/spartan/worker.py:646-688). This module lets the same
files drive the TPU framework: it maps the ldm key layout —
``model.diffusion_model.*`` (UNet), ``first_stage_model.*`` (VAE),
``cond_stage_model.transformer.*`` / ``conditioner.embedders.*`` (text
encoders) — onto this package's Flax modules, fusing separate q/k/v
projections into the single QKV matmuls the TPU modules use.

Layout transforms (torch -> flax):
  Linear  (O, I)        -> kernel (I, O)
  Conv2d  (O, I, kh, kw) -> kernel (kh, kw, I, O)
  1x1 Conv used as Linear -> kernel (I, O)
  GroupNorm/LayerNorm weight -> scale
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from stable_diffusion_webui_distributed_tpu.models.configs import (
    CLIPTextConfig,
    ModelFamily,
    UNetConfig,
    VAEConfig,
)

Array = np.ndarray
StateDict = Dict[str, Array]


class MissingKeys(KeyError):
    """Raised with the full list of absent checkpoint keys."""


class _Puller:
    """Tracks which checkpoint keys were consumed; reports leftovers."""

    def __init__(self, sd: StateDict):
        self.sd = sd
        self.used: set = set()
        self.missing: List[str] = []

    def take(self, key: str) -> Array:
        if key not in self.sd:
            self.missing.append(key)
            return np.zeros((1,), np.float32)
        self.used.add(key)
        return np.asarray(self.sd[key])

    def has(self, key: str) -> bool:
        return key in self.sd

    def finish(self, scope: str) -> None:
        if self.missing:
            raise MissingKeys(
                f"{scope}: {len(self.missing)} keys absent, first 10: "
                f"{self.missing[:10]}"
            )


def _linear(p: _Puller, key: str, bias: bool = True) -> Dict[str, Array]:
    w = p.take(f"{key}.weight")
    if w.ndim == 4:  # 1x1 conv used as linear (SD1.x proj_in/out, VAE attn)
        w = w[:, :, 0, 0]
    out = {"kernel": w.T}
    if bias:
        out["bias"] = p.take(f"{key}.bias")
    return out


def _conv(p: _Puller, key: str) -> Dict[str, Array]:
    w = p.take(f"{key}.weight")
    return {"kernel": w.transpose(2, 3, 1, 0), "bias": p.take(f"{key}.bias")}


def _norm(p: _Puller, key: str) -> Dict[str, Array]:
    return {"scale": p.take(f"{key}.weight"), "bias": p.take(f"{key}.bias")}


def _gn(p: _Puller, key: str) -> Dict[str, Dict[str, Array]]:
    return {"gn": _norm(p, key)}


def _fused(mats: Sequence[Array], biases: Optional[Sequence[Array]] = None):
    """Concatenate separate projection weights into one fused kernel."""
    out = {"kernel": np.concatenate([m.T for m in mats], axis=1)}
    if biases is not None:
        out["bias"] = np.concatenate(list(biases))
    return out


# --------------------------------------------------------------------------
# Text encoders
# --------------------------------------------------------------------------

def convert_clip_hf(sd: StateDict, cfg: CLIPTextConfig, prefix: str) -> Dict:
    """HF ``text_model`` layout (SD1.x ``cond_stage_model.transformer``,
    SDXL ``conditioner.embedders.0.transformer``)."""
    p = _Puller(sd)
    out: Dict = {
        "token_embedding": {
            "embedding": p.take(f"{prefix}.embeddings.token_embedding.weight")
        },
        "position_embedding": p.take(
            f"{prefix}.embeddings.position_embedding.weight"
        ),
        "final_ln": _norm(p, f"{prefix}.final_layer_norm"),
    }
    for i in range(cfg.num_layers):
        lp = f"{prefix}.encoder.layers.{i}"
        qw = p.take(f"{lp}.self_attn.q_proj.weight")
        kw = p.take(f"{lp}.self_attn.k_proj.weight")
        vw = p.take(f"{lp}.self_attn.v_proj.weight")
        qb = p.take(f"{lp}.self_attn.q_proj.bias")
        kb = p.take(f"{lp}.self_attn.k_proj.bias")
        vb = p.take(f"{lp}.self_attn.v_proj.bias")
        out[f"layer_{i}"] = {
            "ln1": _norm(p, f"{lp}.layer_norm1"),
            "ln2": _norm(p, f"{lp}.layer_norm2"),
            "attn": {
                "qkv": _fused([qw, kw, vw], [qb, kb, vb]),
                "out_proj": _linear(p, f"{lp}.self_attn.out_proj"),
            },
            "fc1": _linear(p, f"{lp}.mlp.fc1"),
            "fc2": _linear(p, f"{lp}.mlp.fc2"),
        }
    if cfg.projection_dim:
        # HF keeps text_projection outside text_model, on the wrapper.
        parent = prefix.rsplit(".text_model", 1)[0]
        out["text_projection"] = {
            "kernel": p.take(f"{parent}.text_projection.weight").T
        }
    p.finish(f"clip[{prefix}]")
    return out


def convert_clip_openai(sd: StateDict, cfg: CLIPTextConfig, prefix: str) -> Dict:
    """OpenCLIP ``model`` layout (SDXL ``conditioner.embedders.1.model``):
    fused ``in_proj_weight``, ``resblocks`` naming, raw ``text_projection``."""
    p = _Puller(sd)
    out: Dict = {
        "token_embedding": {"embedding": p.take(f"{prefix}.token_embedding.weight")},
        "position_embedding": p.take(f"{prefix}.positional_embedding"),
        "final_ln": _norm(p, f"{prefix}.ln_final"),
    }
    for i in range(cfg.num_layers):
        lp = f"{prefix}.transformer.resblocks.{i}"
        out[f"layer_{i}"] = {
            "ln1": _norm(p, f"{lp}.ln_1"),
            "ln2": _norm(p, f"{lp}.ln_2"),
            "attn": {
                "qkv": {
                    "kernel": p.take(f"{lp}.attn.in_proj_weight").T,
                    "bias": p.take(f"{lp}.attn.in_proj_bias"),
                },
                "out_proj": _linear(p, f"{lp}.attn.out_proj"),
            },
            "fc1": _linear(p, f"{lp}.mlp.c_fc"),
            "fc2": _linear(p, f"{lp}.mlp.c_proj"),
        }
    if cfg.projection_dim:
        # open_clip stores text_projection as (width, embed_dim), applied as
        # x @ proj -> already (I, O): no transpose.
        out["text_projection"] = {"kernel": p.take(f"{prefix}.text_projection")}
    p.finish(f"openclip[{prefix}]")
    return out


# --------------------------------------------------------------------------
# UNet
# --------------------------------------------------------------------------

def _res_block(p: _Puller, key: str, has_skip: bool) -> Dict:
    out = {
        "norm1": _gn(p, f"{key}.in_layers.0"),
        "conv1": _conv(p, f"{key}.in_layers.2"),
        "time_proj": _linear(p, f"{key}.emb_layers.1"),
        "norm2": _gn(p, f"{key}.out_layers.0"),
        "conv2": _conv(p, f"{key}.out_layers.3"),
    }
    if has_skip:
        w = p.take(f"{key}.skip_connection.weight")
        out["skip"] = {"kernel": w.transpose(2, 3, 1, 0),
                       "bias": p.take(f"{key}.skip_connection.bias")}
    return out


def _transformer(p: _Puller, key: str, depth: int) -> Dict:
    out: Dict = {
        "norm": _gn(p, f"{key}.norm"),
        "proj_in": _linear(p, f"{key}.proj_in"),
        "proj_out": _linear(p, f"{key}.proj_out"),
    }
    for d in range(depth):
        bp = f"{key}.transformer_blocks.{d}"
        qw = p.take(f"{bp}.attn1.to_q.weight")
        kw = p.take(f"{bp}.attn1.to_k.weight")
        vw = p.take(f"{bp}.attn1.to_v.weight")
        out[f"block_{d}"] = {
            "ln1": _norm(p, f"{bp}.norm1"),
            "ln2": _norm(p, f"{bp}.norm2"),
            "ln3": _norm(p, f"{bp}.norm3"),
            "attn1": {
                "qkv": _fused([qw, kw, vw]),
                "out_proj": _linear(p, f"{bp}.attn1.to_out.0"),
            },
            "attn2": {
                "q": {"kernel": p.take(f"{bp}.attn2.to_q.weight").T},
                "kv": _fused([
                    p.take(f"{bp}.attn2.to_k.weight"),
                    p.take(f"{bp}.attn2.to_v.weight"),
                ]),
                "out_proj": _linear(p, f"{bp}.attn2.to_out.0"),
            },
            "geglu": {"proj": _linear(p, f"{bp}.ff.net.0.proj")},
            "ff_out": _linear(p, f"{bp}.ff.net.2"),
        }
    return out


def convert_unet(sd: StateDict, cfg: UNetConfig,
                 prefix: str = "model.diffusion_model") -> Dict:
    """ldm UNet layout -> :class:`~...models.unet.UNet` params.

    Replays the ldm module-numbering scheme (input_blocks gain an index per
    res/downsample entry, output_blocks append upsample to the level's last
    block) so the mapping is generated from the config, not hard-coded.
    """
    p = _Puller(sd)
    out: Dict = {
        "time_fc1": _linear(p, f"{prefix}.time_embed.0"),
        "time_fc2": _linear(p, f"{prefix}.time_embed.2"),
        "conv_in": _conv(p, f"{prefix}.input_blocks.0.0"),
        "norm_out": _gn(p, f"{prefix}.out.0"),
        "conv_out": _conv(p, f"{prefix}.out.2"),
    }
    if cfg.addition_embed_dim:
        out["add_fc1"] = _linear(p, f"{prefix}.label_emb.0.0")
        out["add_fc2"] = _linear(p, f"{prefix}.label_emb.0.2")

    levels = list(zip(cfg.block_out_channels, cfg.down_blocks))
    n = 1
    prev_ch = cfg.block_out_channels[0]
    for level, (ch, depth) in enumerate(levels):
        for i in range(cfg.layers_per_block):
            key = f"{prefix}.input_blocks.{n}"
            out[f"down_{level}_res_{i}"] = _res_block(p, f"{key}.0",
                                                      has_skip=prev_ch != ch)
            if depth is not None:
                out[f"down_{level}_attn_{i}"] = _transformer(p, f"{key}.1", depth)
            prev_ch = ch
            n += 1
        if level < len(levels) - 1:
            out[f"down_{level}_ds"] = {
                "conv": _conv(p, f"{prefix}.input_blocks.{n}.0.op")
            }
            n += 1

    out["mid_res_0"] = _res_block(p, f"{prefix}.middle_block.0", has_skip=False)
    mid_idx = 1
    if cfg.mid_block_depth is not None:
        out["mid_attn"] = _transformer(p, f"{prefix}.middle_block.1",
                                       cfg.mid_block_depth)
        mid_idx = 2
    out["mid_res_1"] = _res_block(p, f"{prefix}.middle_block.{mid_idx}",
                                  has_skip=False)

    n = 0
    for level in reversed(range(len(levels))):
        ch, depth = levels[level]
        for i in range(cfg.layers_per_block + 1):
            key = f"{prefix}.output_blocks.{n}"
            # concat skip always changes channel count -> always has skip conv
            out[f"up_{level}_res_{i}"] = _res_block(p, f"{key}.0", has_skip=True)
            idx = 1
            if depth is not None:
                out[f"up_{level}_attn_{i}"] = _transformer(p, f"{key}.1", depth)
                idx = 2
            if i == cfg.layers_per_block and level > 0:
                out[f"up_{level}_us"] = {
                    "conv": _conv(p, f"{key}.{idx}.conv")
                }
            n += 1

    p.finish("unet")
    return out


# --------------------------------------------------------------------------
# VAE
# --------------------------------------------------------------------------

def _vae_res(p: _Puller, key: str, has_skip: bool) -> Dict:
    out = {
        "norm1": _gn(p, f"{key}.norm1"),
        "conv1": _conv(p, f"{key}.conv1"),
        "norm2": _gn(p, f"{key}.norm2"),
        "conv2": _conv(p, f"{key}.conv2"),
    }
    if has_skip:
        out["skip"] = _linear(p, f"{key}.nin_shortcut")
        out["skip"]["kernel"] = out["skip"]["kernel"][None, None] \
            if out["skip"]["kernel"].ndim == 2 else out["skip"]["kernel"]
    return out


def _vae_attn(p: _Puller, key: str) -> Dict:
    q = p.take(f"{key}.q.weight")[:, :, 0, 0]
    k = p.take(f"{key}.k.weight")[:, :, 0, 0]
    v = p.take(f"{key}.v.weight")[:, :, 0, 0]
    return {
        "norm": _gn(p, f"{key}.norm"),
        "qkv": _fused([q, k, v], [
            p.take(f"{key}.q.bias"),
            p.take(f"{key}.k.bias"),
            p.take(f"{key}.v.bias"),
        ]),
        "out_proj": _linear(p, f"{key}.proj_out"),
    }


def convert_vae(sd: StateDict, cfg: VAEConfig,
                prefix: str = "first_stage_model") -> Dict:
    p = _Puller(sd)
    enc: Dict = {
        "conv_in": _conv(p, f"{prefix}.encoder.conv_in"),
        "mid_res_0": _vae_res(p, f"{prefix}.encoder.mid.block_1", False),
        "mid_attn": _vae_attn(p, f"{prefix}.encoder.mid.attn_1"),
        "mid_res_1": _vae_res(p, f"{prefix}.encoder.mid.block_2", False),
        "norm_out": _gn(p, f"{prefix}.encoder.norm_out"),
        "conv_out": _conv(p, f"{prefix}.encoder.conv_out"),
        "quant_conv": _conv(p, f"{prefix}.quant_conv"),
    }
    prev = cfg.block_out_channels[0]
    for level, ch in enumerate(cfg.block_out_channels):
        for i in range(cfg.layers_per_block):
            enc[f"down_{level}_res_{i}"] = _vae_res(
                p, f"{prefix}.encoder.down.{level}.block.{i}",
                has_skip=(i == 0 and prev != ch))
        prev = ch
        if level < len(cfg.block_out_channels) - 1:
            enc[f"down_{level}_ds"] = _conv(
                p, f"{prefix}.encoder.down.{level}.downsample.conv")

    dec: Dict = {
        "post_quant_conv": _conv(p, f"{prefix}.post_quant_conv"),
        "conv_in": _conv(p, f"{prefix}.decoder.conv_in"),
        "mid_res_0": _vae_res(p, f"{prefix}.decoder.mid.block_1", False),
        "mid_attn": _vae_attn(p, f"{prefix}.decoder.mid.attn_1"),
        "mid_res_1": _vae_res(p, f"{prefix}.decoder.mid.block_2", False),
        "norm_out": _gn(p, f"{prefix}.decoder.norm_out"),
        "conv_out": _conv(p, f"{prefix}.decoder.conv_out"),
    }
    prev = cfg.block_out_channels[-1]
    for level in reversed(range(len(cfg.block_out_channels))):
        ch = cfg.block_out_channels[level]
        for i in range(cfg.layers_per_block + 1):
            dec[f"up_{level}_res_{i}"] = _vae_res(
                p, f"{prefix}.decoder.up.{level}.block.{i}",
                has_skip=(i == 0 and prev != ch))
        prev = ch
        if level > 0:
            dec[f"up_{level}_us"] = _conv(
                p, f"{prefix}.decoder.up.{level}.upsample.conv")

    p.finish("vae")
    return {"encoder": enc, "decoder": dec}


# --------------------------------------------------------------------------
# Whole-checkpoint entry points
# --------------------------------------------------------------------------

def convert_ldm(sd: StateDict, family: ModelFamily) -> Dict[str, Optional[Dict]]:
    """Convert a full single-file state dict for ``family``; returns params
    per component: ``{"text_encoder", "text_encoder_2", "unet", "vae"}``."""
    is_xl = family.text_encoder_2 is not None
    if is_xl:
        te = convert_clip_hf(sd, family.text_encoder,
                             "conditioner.embedders.0.transformer.text_model")
        te2 = convert_clip_openai(sd, family.text_encoder_2,
                                  "conditioner.embedders.1.model")
    else:
        # single-encoder layouts: SDXL refiner (embedders.0.model), SD2.x
        # (cond_stage_model.model, OpenCLIP), SD1.x (HF text_model)
        if any(k.startswith("conditioner.embedders.0.model.") for k in sd):
            te = convert_clip_openai(sd, family.text_encoder,
                                     "conditioner.embedders.0.model")
        elif any(k.startswith("cond_stage_model.model.") for k in sd):
            te = convert_clip_openai(sd, family.text_encoder,
                                     "cond_stage_model.model")
        else:
            te = convert_clip_hf(sd, family.text_encoder,
                                 "cond_stage_model.transformer.text_model")
        te2 = None
    return {
        "text_encoder": te,
        "text_encoder_2": te2,
        "unet": convert_unet(sd, family.unet),
        "vae": convert_vae(sd, family.vae),
    }


def load_safetensors(path: str) -> StateDict:
    """Read a ``.safetensors`` file to a numpy state dict (no torch needed)."""
    from safetensors import safe_open

    out: StateDict = {}
    with safe_open(path, framework="np") as f:
        for k in f.keys():
            t = f.get_tensor(k)
            if t.dtype == np.float16:
                t = t.astype(np.float32)
            out[k] = t
    return out


def load_checkpoint(path: str, family: ModelFamily) -> Dict[str, Optional[Dict]]:
    """Load + convert a single-file checkpoint (.safetensors or torch .ckpt)."""
    if path.endswith(".safetensors"):
        sd = load_safetensors(path)
    else:
        import torch

        raw = torch.load(path, map_location="cpu", weights_only=True)
        raw = raw.get("state_dict", raw)
        sd = {k: v.float().numpy() for k, v in raw.items()
              if hasattr(v, "numpy")}
    return convert_ldm(sd, family)


def detect_family(sd: StateDict) -> str:
    """Guess the model family from checkpoint keys (webui does the same when
    a user drops in an arbitrary checkpoint). Inpainting-specialized
    checkpoints are detected by their 9-channel conv_in (webui reads this
    from the .yaml; the weights say it just as clearly)."""
    conv_in = sd.get("model.diffusion_model.input_blocks.0.0.weight")
    inpaint = conv_in is not None and conv_in.ndim == 4 \
        and conv_in.shape[1] == 9
    if "conditioner.embedders.1.model.text_projection" in sd or any(
        k.startswith("conditioner.embedders.1.") for k in sd
    ):
        return "sdxl-inpaint" if inpaint else "sdxl-base"
    if any(k.startswith("conditioner.embedders.0.model.") for k in sd):
        return "sdxl-refiner"
    if any(k.startswith("cond_stage_model.model.") for k in sd):
        # SD2.x; v-pred (768-v) vs epsilon (512-base) is not inferable from
        # keys — default to the v-prediction 768 model, overridable via the
        # <ckpt>.json family sidecar (webui reads the .yaml the same way).
        # 9-channel conv_in marks stable-diffusion-2-inpainting (epsilon).
        return "sd2-inpaint" if inpaint else "sd21"
    return "sd15-inpaint" if inpaint else "sd15"

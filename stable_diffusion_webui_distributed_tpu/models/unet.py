"""Denoising UNet (SD 1.x / SDXL families) in Flax, TPU-first.

The reference never touches this network — it lives inside every remote
sdwui process the reference fans requests out to
(/root/reference/scripts/spartan/worker.py:432-435). Here it is the hot loop.

TPU-first choices:
- NHWC everywhere (flax Conv default): feeds the MXU's native conv layout.
- bf16 matmuls/convs with f32 GroupNorm statistics and f32 residual adds at
  block boundaries — bit-growth control without banding artifacts.
- One fused QKV matmul for self-attention, fused KV for cross-attention.
- Static shapes: spatial dims are compile-time constants; the time step and
  conditioning are data, so one compilation serves every prompt/seed/step
  count at a given resolution bucket.
- ``remat`` on transformer blocks (optional) trades FLOPs for HBM at big
  batch sizes.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models.configs import UNetConfig
from stable_diffusion_webui_distributed_tpu.models.lora import (
    apply_site as _lora_site,
)
from stable_diffusion_webui_distributed_tpu.parallel.sharding import (
    channel_concat,
)
from stable_diffusion_webui_distributed_tpu.ops.quant import (
    conv as _conv,
    linear as _linear,
)


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal embedding, (B,) -> (B, dim). f32: frequencies span 1e4."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class GroupNorm32(nn.Module):
    """GroupNorm with f32 statistics regardless of activation dtype."""

    num_groups: int = 32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig = x.dtype
        groups = min(self.num_groups, x.shape[-1])
        y = nn.GroupNorm(num_groups=groups, dtype=jnp.float32, name="gn")(
            x.astype(jnp.float32)
        )
        return y.astype(orig)


class ResBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32
    quant_convs: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, temb: jax.Array) -> jax.Array:
        qc = self.quant_convs
        h = nn.silu(GroupNorm32(name="norm1")(x))
        h = _conv(qc, self.out_channels, padding=1, dtype=self.dtype,
                  name="conv1")(h)
        t = nn.Dense(self.out_channels, dtype=self.dtype, name="time_proj")(
            nn.silu(temb)
        )
        h = h + t[:, None, None]
        h = nn.silu(GroupNorm32(name="norm2")(h))
        h = _conv(qc, self.out_channels, padding=1, dtype=self.dtype,
                  name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = _conv(qc, self.out_channels, (1, 1), padding=0,
                      dtype=self.dtype, name="skip")(x)
        return (x.astype(jnp.float32) + h.astype(jnp.float32)).astype(self.dtype)


class Attention(nn.Module):
    """Self- or cross-attention over flattened spatial tokens.

    ``impl``: "xla" (compiler-fused), "flash" (Pallas online-softmax kernel
    for the latent self-attention hot spot), "ring" (sequence-parallel
    over the mesh's ``sp`` axis for token counts beyond one chip — requires
    ``mesh``), or "ragged" (per-row true-length masked kernel,
    ops/ragged_attention.py). Cross-attention's 77-token context always
    takes the XLA path, as does any shape the chosen impl can't tile.

    ``true_len`` (traced (B,) int32, optional) forces the ragged path
    regardless of ``impl``: for self-attention the row's valid spatial
    prefix, for cross-attention the row's valid context prefix — the
    ragged-dispatch contract where heterogeneous rows share one
    bucket-shaped executable.
    """

    num_heads: int
    dtype: jnp.dtype = jnp.float32
    impl: str = "xla"
    mesh: Optional[object] = None
    quant_linears: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array] = None,
                 true_len: Optional[jax.Array] = None,
                 lora=None) -> jax.Array:
        B, T, C = x.shape
        head_dim = C // self.num_heads
        qz = self.quant_linears
        if context is None:
            qkv = _linear(qz, 3 * C, use_bias=False, dtype=self.dtype,
                          name="qkv")(x)
            qkv = _lora_site(qkv, x, lora, "qkv")
            q, k, v = jnp.split(qkv, 3, axis=-1)
            ctx_len = T
        else:
            q = _linear(qz, C, use_bias=False, dtype=self.dtype, name="q")(x)
            q = _lora_site(q, x, lora, "q")
            kv = _linear(qz, 2 * C, use_bias=False, dtype=self.dtype,
                         name="kv")(context)
            kv = _lora_site(kv, context, lora, "kv")
            k, v = jnp.split(kv, 2, axis=-1)
            ctx_len = context.shape[1]

        q = q.reshape(B, T, self.num_heads, head_dim)
        k = k.reshape(B, ctx_len, self.num_heads, head_dim)
        v = v.reshape(B, ctx_len, self.num_heads, head_dim)
        sp = (self.mesh.shape.get("sp", 1)
              if (self.impl == "ring" and self.mesh is not None) else 1)
        dp_ok = (self.mesh is None
                 or B % max(1, self.mesh.shape.get("dp", 1)) == 0)
        if context is None and (true_len is not None
                                or self.impl == "ragged"):
            from stable_diffusion_webui_distributed_tpu.ops.ragged_attention import (
                ragged_attention,
            )

            tl = (true_len if true_len is not None
                  else jnp.full((B,), T, jnp.int32))
            out = ragged_attention(q, k, v, tl, scale=1.0 / head_dim**0.5)
        elif context is not None and true_len is not None:
            # ragged cross-attention: mask padded context rows; the 77·n
            # token context is small, so the dense masked form suffices
            from stable_diffusion_webui_distributed_tpu.ops.ragged_attention import (
                ragged_attention_reference,
            )

            out = ragged_attention_reference(q, k, v, true_len,
                                             scale=1.0 / head_dim**0.5)
        elif self.impl == "ring" and context is None and sp > 1 \
                and T % sp == 0 and dp_ok:
            from stable_diffusion_webui_distributed_tpu.ops.ring_attention import (
                ring_attention,
            )

            out = ring_attention(q, k, v, self.mesh,
                                 scale=1.0 / head_dim**0.5)
        elif self.impl == "flash" and context is None:
            from stable_diffusion_webui_distributed_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v, scale=1.0 / head_dim**0.5)
        else:
            out = jax.nn.dot_product_attention(
                q, k, v, scale=1.0 / head_dim**0.5)
        out = out.reshape(B, T, C)
        y = _linear(self.quant_linears, C, dtype=self.dtype,
                    name="out_proj")(out)
        return _lora_site(y, out, lora, "out_proj")


class GEGLU(nn.Module):
    dim_out: int
    dtype: jnp.dtype = jnp.float32
    quant_linears: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, lora=None) -> jax.Array:
        h = _linear(self.quant_linears, 2 * self.dim_out, dtype=self.dtype,
                    name="proj")(x)
        h = _lora_site(h, x, lora, "proj")
        a, g = jnp.split(h, 2, axis=-1)
        return a * nn.gelu(g)


class TransformerBlock(nn.Module):
    """self-attn -> cross-attn -> GEGLU MLP, each with pre-LN + residual."""

    num_heads: int
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "xla"
    mesh: Optional[object] = None
    quant_linears: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, context: jax.Array,
                 true_len: Optional[jax.Array] = None,
                 ctx_true: Optional[jax.Array] = None,
                 lora=None) -> jax.Array:
        C = x.shape[-1]
        qz = self.quant_linears

        def sub(key):
            return None if lora is None else lora.get(key)

        x = x + Attention(self.num_heads, dtype=self.dtype,
                          impl=self.attention_impl, mesh=self.mesh,
                          quant_linears=qz, name="attn1")(
            nn.LayerNorm(dtype=jnp.float32, name="ln1")(x),
            true_len=true_len, lora=sub("attn1"),
        )
        x = x + Attention(self.num_heads, dtype=self.dtype,
                          quant_linears=qz, name="attn2")(
            nn.LayerNorm(dtype=jnp.float32, name="ln2")(x), context,
            true_len=ctx_true, lora=sub("attn2"),
        )
        h = nn.LayerNorm(dtype=jnp.float32, name="ln3")(x)
        g = GEGLU(4 * C, dtype=self.dtype, quant_linears=qz,
                  name="geglu")(h, lora=sub("geglu"))
        h = _linear(qz, C, dtype=self.dtype, name="ff_out")(g)
        h = _lora_site(h, g, lora, "ff_out")
        return x + h


class SpatialTransformer(nn.Module):
    """GN -> linear proj-in -> depth x TransformerBlock -> proj-out + residual."""

    depth: int
    num_heads: int
    use_remat: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "xla"
    mesh: Optional[object] = None
    quant_linears: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, context: jax.Array,
                 true_rows: Optional[jax.Array] = None,
                 ctx_true: Optional[jax.Array] = None,
                 lora=None) -> jax.Array:
        B, H, W, C = x.shape
        residual = x
        # row-major flatten: a valid spatial prefix of true_rows rows is a
        # valid token prefix of true_rows * W tokens
        true_len = (None if true_rows is None
                    else jnp.minimum(true_rows, H).astype(jnp.int32) * W)
        hn = GroupNorm32(name="norm")(x).reshape(B, H * W, C)
        h = _linear(self.quant_linears, C, dtype=self.dtype,
                    name="proj_in")(hn)
        h = _lora_site(h, hn, lora, "proj_in")
        block = TransformerBlock
        if self.use_remat:
            block = nn.remat(TransformerBlock, static_argnums=())
        for i in range(self.depth):
            h = block(self.num_heads, dtype=self.dtype,
                      attention_impl=self.attention_impl, mesh=self.mesh,
                      quant_linears=self.quant_linears,
                      name=f"block_{i}")(h, context, true_len, ctx_true,
                                         None if lora is None
                                         else lora.get(f"block_{i}"))
        ho = _linear(self.quant_linears, C, dtype=self.dtype,
                     name="proj_out")(h)
        ho = _lora_site(ho, h, lora, "proj_out")
        return residual + ho.reshape(B, H, W, C)


class Downsample(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32
    quant_convs: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return _conv(self.quant_convs, self.channels, strides=(2, 2),
                     padding=1, dtype=self.dtype, name="conv")(x)


class Upsample(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32
    quant_convs: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, H, W, C = x.shape
        x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
        return _conv(self.quant_convs, self.channels, padding=1,
                     dtype=self.dtype, name="conv")(x)


#: Depth at which the step cache splits the UNet: levels < CACHE_SPLIT are
#: "shallow" (recomputed every step), levels >= CACHE_SPLIT plus the mid
#: block are "deep" (computed on refresh steps only, reused in between —
#: DeepCache's observation that deep features vary slowly across adjacent
#: denoise steps). Split 1 maximizes the skipped FLOPs: everything below
#: the top resolution level is cached.
CACHE_SPLIT = 1


def cache_supported(cfg: UNetConfig) -> bool:
    """Deep-feature caching needs at least one level below the split."""
    return len(cfg.block_out_channels) > CACHE_SPLIT


def control_residual_count(cfg: UNetConfig) -> int:
    """Length of the ``control_residuals`` tuple the full forward expects.

    One residual per down-path skip — conv_in, ``layers_per_block`` per
    level, a Downsample for every level but the last — plus one for the
    mid block. The stage-graph executor (parallel/stage_graph.py) computes
    residuals on a separate mesh slice one sigma-step ahead of the UNet
    and feeds them in as stage inputs; it validates the tuple against
    this count on the host before dispatch, mirroring the traced
    ``assert len(control_residuals) == len(skips) + 1`` inside __call__.
    """
    n_levels = len(cfg.block_out_channels)
    skips = 1 + n_levels * cfg.layers_per_block + (n_levels - 1)
    return skips + 1


def deep_cache_shape(cfg: UNetConfig, batch: int, lat_h: int,
                     lat_w: int) -> Tuple[int, int, int, int]:
    """Shape of the cached deep feature: the up-path hidden state right
    after the split level's Upsample — i.e. the value the shallow up path
    starts from on reuse steps. Spatial dims follow the stride-2 conv
    arithmetic (ceil halving per Downsample, doubling at the Upsample)."""
    h, w = lat_h, lat_w
    for _ in range(CACHE_SPLIT):
        h, w = (h + 1) // 2, (w + 1) // 2
    return (batch, 2 * h, 2 * w, cfg.block_out_channels[CACHE_SPLIT])


class UNet(nn.Module):
    """The full conditional denoiser.

    ``__call__(latents, timesteps, context, *, added_cond)``:
      latents (B,H,W,Cin) NHWC; timesteps (B,) f32; context (B,T,Dctx);
      added_cond: SDXL (B, projection_input_dim) vector or None.
    Returns the predicted noise/v, (B,H,W,Cout).

    Step-cache modes (``cache_mode``, a static trace-time choice):
      - ``None``: the ordinary full forward (bit-identical to the
        pre-cache code path — the golden-hash contract).
      - ``"deep"``: run conv_in + full down path + mid + the deep up
        levels (>= CACHE_SPLIT) and return the hidden state right after
        the split level's Upsample — the deep feature the engine carries
        in its denoise scan.
      - ``"reuse"``: ``cache`` required; run only conv_in + the shallow
        down levels (< CACHE_SPLIT) for fresh skips, start the up path
        from ``cache``, finish with norm_out/conv_out. This is the small
        per-step branch on non-refresh steps.
    ControlNet residual injection is full-forward only — the engine
    bypasses the cache for chunks with active CN units.
    """

    cfg: UNetConfig
    dtype: jnp.dtype = jnp.float32
    use_remat: bool = False
    attention_impl: str = "xla"
    mesh: Optional[object] = None
    # experimental dynamic W8A8 for transformer linears (ops/quant.py;
    # SDTPU_UNET_INT8=1) — the int8-MXU lever from PERF.md's roofline
    quant_linears: bool = False
    # ...and for the ResBlock/Down/Up convs (SDTPU_UNET_INT8_CONV=1) —
    # the conv-dominated configs' (#1/#3) half of the same lever;
    # conv_in/conv_out and the time MLP stay in the policy dtype
    quant_convs: bool = False

    def heads_for(self, channels: int) -> int:
        if self.cfg.num_attention_heads is not None:
            return self.cfg.num_attention_heads
        return max(1, channels // 64)

    @nn.compact
    def __call__(
        self,
        latents: jax.Array,
        timesteps: jax.Array,
        context: jax.Array,
        added_cond: Optional[jax.Array] = None,
        control_residuals: Optional[Tuple[jax.Array, ...]] = None,
        cache: Optional[jax.Array] = None,
        cache_mode: Optional[str] = None,
        true_rows: Optional[jax.Array] = None,
        ctx_true: Optional[jax.Array] = None,
        lora=None,
    ) -> jax.Array:
        c = self.cfg
        assert cache_mode in (None, "deep", "reuse"), cache_mode
        if true_rows is not None or ctx_true is not None:
            # ragged dispatch rides the plain full forward only — the
            # engine disables the step cache for ragged chunks
            assert cache_mode is None, "ragged rows exclude the step cache"
        if cache_mode is not None:
            assert cache_supported(c), \
                "step cache needs a level below CACHE_SPLIT"
            assert control_residuals is None, \
                "ControlNet requires the full forward (engine bypasses)"
        if cache_mode == "reuse":
            assert cache is not None, "reuse mode needs the cached feature"
        split = CACHE_SPLIT
        ch0 = c.block_out_channels[0]
        time_dim = 4 * ch0

        # Timestep embedding MLP.
        temb = timestep_embedding(timesteps, ch0)
        temb = nn.Dense(time_dim, dtype=self.dtype, name="time_fc1")(
            temb.astype(self.dtype)
        )
        temb = nn.Dense(time_dim, dtype=self.dtype, name="time_fc2")(nn.silu(temb))

        # SDXL micro-conditioning: pooled text + fourier(time_ids) -> MLP.
        if c.addition_embed_dim:
            assert added_cond is not None, "SDXL family requires added_cond"
            a = nn.Dense(time_dim, dtype=self.dtype, name="add_fc1")(
                added_cond.astype(self.dtype)
            )
            a = nn.Dense(time_dim, dtype=self.dtype, name="add_fc2")(nn.silu(a))
            temb = temb + a

        context = context.astype(self.dtype)
        x = nn.Conv(ch0, (3, 3), padding=1, dtype=self.dtype, name="conv_in")(
            latents.astype(self.dtype)
        )

        # --- down path ---
        # "reuse" runs only the shallow levels (< split): a shallow level's
        # Downsample output feeds the split level's down blocks AND the
        # split level's up blocks (as a skip), both of which live in the
        # cached deep half — so the last shallow Downsample is skipped too.
        n_levels = len(c.block_out_channels)
        down_levels = split if cache_mode == "reuse" else n_levels
        last_ds = split - 1 if cache_mode == "reuse" else n_levels - 1
        # Per-level valid-row counts: each stride-2 Downsample follows the
        # ceil-halving arithmetic, so rows_lvl[level] is the valid spatial
        # prefix at that level's resolution (shared by down, mid, up).
        rows_lvl = None
        if true_rows is not None:
            rows_lvl = [true_rows.astype(jnp.int32)]
            for _ in range(n_levels - 1):
                rows_lvl.append((rows_lvl[-1] + 1) // 2)
        skips = [x]
        for level, (ch, depth) in enumerate(zip(
                c.block_out_channels[:down_levels],
                c.down_blocks[:down_levels])):
            for i in range(c.layers_per_block):
                x = ResBlock(ch, dtype=self.dtype,
                             quant_convs=self.quant_convs,
                             name=f"down_{level}_res_{i}")(x, temb)
                if depth is not None:
                    x = SpatialTransformer(
                        depth, self.heads_for(ch), self.use_remat, self.dtype,
                        self.attention_impl, self.mesh,
                        quant_linears=self.quant_linears,
                        name=f"down_{level}_attn_{i}")(
                        x, context,
                        None if rows_lvl is None else rows_lvl[level],
                        ctx_true,
                        None if lora is None
                        else lora.get(f"down_{level}_attn_{i}"))
                skips.append(x)
            if level < last_ds:
                x = Downsample(ch, dtype=self.dtype,
                               quant_convs=self.quant_convs,
                               name=f"down_{level}_ds")(x)
                skips.append(x)

        if cache_mode != "reuse":
            # --- mid ---
            mid_ch = c.block_out_channels[-1]
            x = ResBlock(mid_ch, dtype=self.dtype,
                         quant_convs=self.quant_convs,
                         name="mid_res_0")(x, temb)
            if c.mid_block_depth is not None:
                x = SpatialTransformer(
                    c.mid_block_depth, self.heads_for(mid_ch), self.use_remat,
                    self.dtype, self.attention_impl, self.mesh,
                    quant_linears=self.quant_linears,
                    name="mid_attn")(
                    x, context,
                    None if rows_lvl is None else rows_lvl[-1], ctx_true,
                    None if lora is None else lora.get("mid_attn"))
            x = ResBlock(mid_ch, dtype=self.dtype,
                         quant_convs=self.quant_convs,
                         name="mid_res_1")(x, temb)

        # ControlNet residual injection: one residual per skip + one for the
        # mid block output (the standard ControlNet contract; the reference
        # only serializes the conditioning payload, control_net.py:20-79 —
        # the math lives here).
        if control_residuals is not None:
            assert len(control_residuals) == len(skips) + 1, (
                f"expected {len(skips) + 1} control residuals, "
                f"got {len(control_residuals)}")
            x = x + control_residuals[-1].astype(x.dtype)
            skips = [s + r.astype(s.dtype)
                     for s, r in zip(skips, control_residuals[:-1])]

        # --- up path (mirror of down, one extra layer per block) ---
        # "deep" stops after the split level's Upsample and returns the
        # hidden state there; "reuse" starts from it.
        up_stop = split if cache_mode == "deep" else 0
        if cache_mode == "reuse":
            x = cache.astype(self.dtype)
        for level in reversed(range(up_stop,
                                    split if cache_mode == "reuse"
                                    else n_levels)):
            ch = c.block_out_channels[level]
            depth = c.down_blocks[level]
            for i in range(c.layers_per_block + 1):
                # channel_concat, not jnp.concatenate: under tensor
                # parallelism the channel dim is tp-sharded and a sharded
                # -dim concatenate mis-partitions on multi-axis meshes
                # (parallel/sharding.py:channel_concat)
                x = channel_concat([x, skips.pop()])
                x = ResBlock(ch, dtype=self.dtype,
                             quant_convs=self.quant_convs,
                             name=f"up_{level}_res_{i}")(x, temb)
                if depth is not None:
                    x = SpatialTransformer(
                        depth, self.heads_for(ch), self.use_remat, self.dtype,
                        self.attention_impl, self.mesh,
                        quant_linears=self.quant_linears,
                        name=f"up_{level}_attn_{i}")(
                        x, context,
                        None if rows_lvl is None else rows_lvl[level],
                        ctx_true,
                        None if lora is None
                        else lora.get(f"up_{level}_attn_{i}"))
            if level > 0:
                x = Upsample(ch, dtype=self.dtype,
                             quant_convs=self.quant_convs,
                             name=f"up_{level}_us")(x)
        if cache_mode == "deep":
            # the shallow skips stay unconsumed by design; the engine's
            # reuse branch recomputes them fresh each step
            return x
        assert not skips, f"{len(skips)} unconsumed skip connections"

        x = nn.silu(GroupNorm32(name="norm_out")(x))
        x = nn.Conv(c.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(x)
        return x.astype(jnp.float32)


def make_added_cond(
    pooled_text: jax.Array,      # (B, addition_embed_dim)
    time_ids: jax.Array,         # (B, 6): orig_h, orig_w, crop_t, crop_l, tgt_h, tgt_w
    addition_time_embed_dim: int,
) -> jax.Array:
    """SDXL micro-conditioning vector: pooled text ++ fourier(time_ids)."""
    B = time_ids.shape[0]
    emb = timestep_embedding(time_ids.reshape(-1), addition_time_embed_dim)
    emb = emb.reshape(B, -1)
    return jnp.concatenate([pooled_text.astype(jnp.float32), emb], axis=-1)

"""webui prompt syntax: attention emphasis + unlimited prompt length.

Every sdwui worker in the reference deployment applies this grammar to the
prompt strings the master ships over HTTP (the reference passes prompts
verbatim, distributed.py:239-265, and relies on each webui to parse them).
This module owns it natively:

- ``(text)`` multiplies attention by 1.1, ``[text]`` divides by 1.1,
  ``(text:1.3)`` sets an explicit weight, ``\\(`` escapes literals —
  webui's ``parse_prompt_attention`` grammar, reimplemented.
- Prompts longer than CLIP's 75-token window are split into 77-token
  chunks (BOS + 75 + EOS each), encoded separately, and concatenated along
  the sequence axis — cross-attention happily consumes the longer context.
- Per-token weights scale the encoded embeddings, then the chunk mean is
  restored (webui's emphasis implementation: scaling must not shift the
  overall magnitude the UNet was trained to expect).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

_ATTENTION_RE = re.compile(r"""
\\\(|\\\)|\\\[|\\]|\\\\|\\|
\(|\[|:\s*([+-]?[.\d]+)\s*\)|\)|]|
[^\\()\[\]:]+|:
""", re.X)

_BREAK_RE = re.compile(r"\s*\bBREAK\b\s*", re.S)


def parse_prompt_attention(text: str) -> List[Tuple[str, float]]:
    """'a (cat:1.3) [dog]' -> [('a ', 1.0), ('cat', 1.3), ('dog', 1/1.1)].

    webui grammar: nested parens multiply, explicit ``:w`` sets the weight
    of the innermost open paren group, backslash escapes literal brackets.
    ``BREAK`` forces a chunk boundary (marked with weight -1 sentinel).
    """
    res: List[List] = []
    round_brackets: List[int] = []
    square_brackets: List[int] = []

    def multiply_range(start: int, multiplier: float):
        for pos in range(start, len(res)):
            res[pos][1] *= multiplier

    for m in _ATTENTION_RE.finditer(text):
        tok = m.group(0)
        weight = m.group(1)
        if tok.startswith("\\"):
            res.append([tok[1:], 1.0])
        elif tok == "(":
            round_brackets.append(len(res))
        elif tok == "[":
            square_brackets.append(len(res))
        elif weight is not None and round_brackets:
            multiply_range(round_brackets.pop(), float(weight))
        elif tok == ")" and round_brackets:
            multiply_range(round_brackets.pop(), 1.1)
        elif tok == "]" and square_brackets:
            multiply_range(square_brackets.pop(), 1.0 / 1.1)
        else:
            parts = _BREAK_RE.split(tok)
            for i, part in enumerate(parts):
                if i > 0:
                    res.append(["BREAK", -1.0])
                if part:
                    res.append([part, 1.0])
    # unclosed brackets behave as if closed at the end (webui semantics)
    for pos in round_brackets:
        multiply_range(pos, 1.1)
    for pos in square_brackets:
        multiply_range(pos, 1.0 / 1.1)
    if not res:
        return [("", 1.0)]
    # merge adjacent segments with equal weight
    merged: List[Tuple[str, float]] = []
    for seg, w in res:
        if merged and merged[-1][1] == w and seg != "BREAK" \
                and merged[-1][0] != "BREAK":
            merged[-1] = (merged[-1][0] + seg, w)
        else:
            merged.append((seg, w))
    return merged


#: Tokens of usable content per 77-token CLIP window (75 + BOS + EOS).
CHUNK_CONTENT = 75


def tokenize_weighted(
    tokenizer, text: str, max_chunks: int = 8
) -> Tuple[np.ndarray, np.ndarray]:
    """Prompt -> (ids (n_chunks, 77), weights (n_chunks, 77)).

    Unlimited-length prompts: content tokens flow into as many 77-token
    windows as needed (capped at ``max_chunks``), each wrapped in BOS/EOS;
    BOS/EOS/padding carry weight 1.0. ``BREAK`` starts a new chunk.
    """
    ids, weights, _ = tokenize_with_embeddings(tokenizer, text, None,
                                               max_chunks)
    return ids, weights


def tokenize_with_embeddings(
    tokenizer,
    text: str,
    embeddings: Optional[Dict[str, int]],
    max_chunks: int = 8,
) -> Tuple[np.ndarray, np.ndarray, List[Tuple[int, int, str, int]]]:
    """``tokenize_weighted`` plus textual-inversion placeholders.

    ``embeddings`` maps lowercase embedding names to their vector counts
    (models/embeddings.py ``EmbeddingStore.vector_counts``). A mention of
    an embedding name (word-boundary, case-insensitive — webui's matching
    rule) emits that many placeholder tokens (id 0; the real vectors are
    injected at the token-embedding layer, models/clip.py) and returns
    their positions as ``(chunk_row, column, name, vector_index)``.
    """
    segments = parse_prompt_attention(text)
    emb_re = None
    if embeddings:
        # longest name first so "style-v2" isn't eaten by "style"
        names = sorted(embeddings, key=len, reverse=True)
        emb_re = re.compile(
            r"(?<![\w-])(" + "|".join(re.escape(n) for n in names)
            + r")(?![\w-])", re.IGNORECASE)

    flat_ids: List[int] = []
    flat_w: List[float] = []
    flat_inj: List[Optional[Tuple[str, int]]] = []
    chunks: List[Tuple[List[int], List[float], List]] = []

    def flush():
        nonlocal flat_ids, flat_w, flat_inj
        chunks.append((flat_ids, flat_w, flat_inj))
        flat_ids, flat_w, flat_inj = [], [], []

    def emit(tid: int, w: float, inj=None):
        if len(flat_ids) >= CHUNK_CONTENT:
            flush()
        flat_ids.append(tid)
        flat_w.append(w)
        flat_inj.append(inj)

    for seg, w in segments:
        if seg == "BREAK" and w == -1.0:
            flush()
            continue
        parts = emb_re.split(seg) if emb_re else [seg]
        for i, part in enumerate(parts):
            if emb_re and i % 2 == 1:  # a matched embedding name
                name = part.lower()
                n_vec = embeddings.get(name, 0)
                if n_vec <= 0:  # unloadable file: keep the literal text
                    for tid in tokenizer.encode(part):
                        emit(tid, w)
                    continue
                # keep the vector run atomic within one chunk (webui's
                # chunking opens a new window when an embedding doesn't
                # fit); runs longer than a whole chunk split unavoidably
                if flat_ids and n_vec <= CHUNK_CONTENT \
                        and len(flat_ids) + n_vec > CHUNK_CONTENT:
                    flush()
                for vec in range(n_vec):
                    emit(0, w, (name, vec))
            elif part:
                for tid in tokenizer.encode(part):
                    emit(tid, w)
    flush()
    chunks = chunks[:max_chunks] or [([], [], [])]

    n = len(chunks)
    bos = getattr(tokenizer, "bos", 49406)
    eos = getattr(tokenizer, "eos", 49407)
    ids = np.full((n, CHUNK_CONTENT + 2), eos, np.int32)
    weights = np.ones((n, CHUNK_CONTENT + 2), np.float32)
    injections: List[Tuple[int, int, str, int]] = []
    for row, (cid, cw, cinj) in enumerate(chunks):
        ids[row, 0] = bos
        ids[row, 1:1 + len(cid)] = cid
        ids[row, 1 + len(cid)] = eos
        weights[row, 1:1 + len(cw)] = cw
        for col, inj in enumerate(cinj):
            if inj is not None:
                injections.append((row, col + 1, inj[0], inj[1]))
    return ids, weights, injections


def true_token_count(ids: np.ndarray, eos: int) -> int:
    """Meaningful tokens in a tokenized (n_chunks, 77) prompt: BOS + content
    + the closing EOS per chunk; the trailing EOS fill is padding. This is
    the numerator of the ``token_padding_ratio`` gauge (denominator: the
    request's padded ``n_chunks * 77``) and the true-cost unit the ragged
    conditioning path stops paying for.
    """
    total = 0
    for row in ids:
        tail = row[1:]          # skip BOS (BOS == EOS id is never emitted)
        eos_at = np.flatnonzero(tail == eos)
        content = int(eos_at[0]) if eos_at.size else CHUNK_CONTENT
        total += 2 + content    # BOS + content + closing EOS
    return total


def pad_chunks(a: np.ndarray, wa: np.ndarray, n: int, eos: int,
               bos: int) -> Tuple[np.ndarray, np.ndarray]:
    """Grow (chunks, 77) ids/weights to ``n`` chunks with empty windows —
    cond and uncond must agree on context length (webui pads the same way).
    """
    have = a.shape[0]
    if have >= n:
        return a, wa
    pad_ids = np.full((n - have, a.shape[1]), eos, np.int32)
    pad_ids[:, 0] = bos
    pad_w = np.ones((n - have, a.shape[1]), np.float32)
    return np.concatenate([a, pad_ids]), np.concatenate([wa, pad_w])

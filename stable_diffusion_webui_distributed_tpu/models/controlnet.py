"""ControlNet: conditioned residual injection for the UNet, in-graph.

The reference only *serializes* ControlNet conditioning for the remote API
(/root/reference/scripts/spartan/control_net.py:20-79: b64-encodes unit
images/masks, both Mikubill and Forge key conventions) — the network itself
runs inside each sdwui worker. Here the network is ours: a Flax copy of the
UNet's down+mid path with a conditioning-hint embedder and zero-convolution
taps, whose outputs are added to the UNet's skip connections
(models/unet.py ``control_residuals``). Params ride as jit arguments, so
enabling/disabling units or swapping ControlNet checkpoints never recompiles
(SURVEY.md §7 hard part #2).

Preprocessors ("modules") are numpy/JAX implementations — no OpenCV in this
image; ``canny`` is a Sobel-magnitude edge detector with double threshold,
close to (not bit-equal with) OpenCV's.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from stable_diffusion_webui_distributed_tpu.models.configs import UNetConfig
from stable_diffusion_webui_distributed_tpu.models.unet import (
    ResBlock,
    SpatialTransformer,
    Downsample,
    timestep_embedding,
)

#: Channel ladder of the conditioning-hint embedder (ldm input_hint_block).
HINT_CHANNELS = (16, 16, 32, 32, 96, 96, 256)


class HintEmbedder(nn.Module):
    """(B, H, W, 3) image-space hint -> (B, H/8, W/8, ch0) latent-space."""

    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hint: jax.Array) -> jax.Array:
        x = hint.astype(self.dtype)
        strides = {2: 2, 4: 2, 6: 2}  # downsample x8 total at convs 2/4/6
        for i, ch in enumerate(HINT_CHANNELS):
            s = strides.get(i, 1)
            x = nn.Conv(ch, (3, 3), strides=(s, s), padding=1,
                        dtype=self.dtype, name=f"conv_{i}")(x)
            x = nn.silu(x)
        # final zero-initialized projection (trained from zero in ControlNet)
        return nn.Conv(self.out_channels, (3, 3), padding=1,
                       kernel_init=nn.initializers.zeros,
                       dtype=self.dtype, name="conv_out")(x)


class ControlNet(nn.Module):
    """Down+mid copy of the UNet emitting one residual per skip + mid."""

    cfg: UNetConfig
    dtype: jnp.dtype = jnp.float32
    # same experimental W8A8 flags as the UNet (runtime/dtypes.py): the
    # CN forward is ~half a UNet, so leaving it bf16 would dilute the
    # int8 cells on ControlNet configs (#3)
    quant_linears: bool = False
    quant_convs: bool = False
    # mirror the UNet's attention configuration: on sp>1 meshes the CN's
    # self-attention must ride the same ring (token-sharded activations),
    # or it all-gathers and materializes the dense score matrix the ring
    # exists to avoid
    use_remat: bool = False
    attention_impl: str = "xla"
    mesh: object = None

    def heads_for(self, channels: int) -> int:
        if self.cfg.num_attention_heads is not None:
            return self.cfg.num_attention_heads
        return max(1, channels // 64)

    @nn.compact
    def __call__(
        self,
        latents: jax.Array,
        timesteps: jax.Array,
        context: jax.Array,
        hint: jax.Array,
        added_cond: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, ...]:
        c = self.cfg
        ch0 = c.block_out_channels[0]
        time_dim = 4 * ch0

        temb = timestep_embedding(timesteps, ch0)
        temb = nn.Dense(time_dim, dtype=self.dtype, name="time_fc1")(
            temb.astype(self.dtype))
        temb = nn.Dense(time_dim, dtype=self.dtype, name="time_fc2")(
            nn.silu(temb))
        if c.addition_embed_dim:
            assert added_cond is not None
            a = nn.Dense(time_dim, dtype=self.dtype, name="add_fc1")(
                added_cond.astype(self.dtype))
            a = nn.Dense(time_dim, dtype=self.dtype, name="add_fc2")(
                nn.silu(a))
            temb = temb + a

        context = context.astype(self.dtype)
        x = nn.Conv(ch0, (3, 3), padding=1, dtype=self.dtype,
                    name="conv_in")(latents.astype(self.dtype))
        x = x + HintEmbedder(ch0, dtype=self.dtype, name="hint")(hint)

        def zero_conv(i, h):
            return nn.Conv(h.shape[-1], (1, 1),
                           kernel_init=nn.initializers.zeros,
                           dtype=self.dtype, name=f"zero_conv_{i}")(h)

        residuals: List[jax.Array] = [zero_conv(0, x)]
        n = 1
        for level, (ch, depth) in enumerate(
                zip(c.block_out_channels, c.down_blocks)):
            for i in range(c.layers_per_block):
                x = ResBlock(ch, dtype=self.dtype,
                             quant_convs=self.quant_convs,
                             name=f"down_{level}_res_{i}")(x, temb)
                if depth is not None:
                    x = SpatialTransformer(
                        depth, self.heads_for(ch), self.use_remat,
                        self.dtype, self.attention_impl, self.mesh,
                        quant_linears=self.quant_linears,
                        name=f"down_{level}_attn_{i}")(x, context)
                residuals.append(zero_conv(n, x))
                n += 1
            if level < len(c.block_out_channels) - 1:
                x = Downsample(ch, dtype=self.dtype,
                               quant_convs=self.quant_convs,
                               name=f"down_{level}_ds")(x)
                residuals.append(zero_conv(n, x))
                n += 1

        mid_ch = c.block_out_channels[-1]
        x = ResBlock(mid_ch, dtype=self.dtype,
                     quant_convs=self.quant_convs, name="mid_res_0")(x, temb)
        if c.mid_block_depth is not None:
            x = SpatialTransformer(
                c.mid_block_depth, self.heads_for(mid_ch), self.use_remat,
                self.dtype, self.attention_impl, self.mesh,
                quant_linears=self.quant_linears, name="mid_attn")(x, context)
        x = ResBlock(mid_ch, dtype=self.dtype,
                     quant_convs=self.quant_convs, name="mid_res_1")(x, temb)
        residuals.append(nn.Conv(mid_ch, (1, 1),
                                 kernel_init=nn.initializers.zeros,
                                 dtype=self.dtype, name="mid_out")(x))
        return tuple(residuals)


# --------------------------------------------------------------------------
# ldm checkpoint conversion (control_model.* layout)
# --------------------------------------------------------------------------

def convert_controlnet(sd: Dict[str, np.ndarray], cfg: UNetConfig,
                       prefix: str = "control_model") -> Dict:
    """ldm ControlNet checkpoint -> :class:`ControlNet` params."""
    from stable_diffusion_webui_distributed_tpu.models.convert import (
        _Puller, _conv, _linear, _res_block, _transformer,
    )

    p = _Puller(sd)
    out: Dict = {
        "time_fc1": _linear(p, f"{prefix}.time_embed.0"),
        "time_fc2": _linear(p, f"{prefix}.time_embed.2"),
        "conv_in": _conv(p, f"{prefix}.input_blocks.0.0"),
        "mid_out": _conv(p, f"{prefix}.middle_block_out.0"),
    }
    if cfg.addition_embed_dim:
        out["add_fc1"] = _linear(p, f"{prefix}.label_emb.0.0")
        out["add_fc2"] = _linear(p, f"{prefix}.label_emb.0.2")

    hint: Dict = {}
    for i in range(len(HINT_CHANNELS)):
        hint[f"conv_{i}"] = _conv(p, f"{prefix}.input_hint_block.{2 * i}")
    hint["conv_out"] = _conv(
        p, f"{prefix}.input_hint_block.{2 * len(HINT_CHANNELS)}")
    out["hint"] = hint

    levels = list(zip(cfg.block_out_channels, cfg.down_blocks))
    out["zero_conv_0"] = _conv(p, f"{prefix}.zero_convs.0.0")
    n = 1
    prev = cfg.block_out_channels[0]
    for level, (ch, depth) in enumerate(levels):
        for i in range(cfg.layers_per_block):
            key = f"{prefix}.input_blocks.{n}"
            out[f"down_{level}_res_{i}"] = _res_block(
                p, f"{key}.0", has_skip=prev != ch)
            if depth is not None:
                out[f"down_{level}_attn_{i}"] = _transformer(
                    p, f"{key}.1", depth)
            out[f"zero_conv_{n}"] = _conv(p, f"{prefix}.zero_convs.{n}.0")
            prev = ch
            n += 1
        if level < len(levels) - 1:
            out[f"down_{level}_ds"] = {
                "conv": _conv(p, f"{prefix}.input_blocks.{n}.0.op")}
            out[f"zero_conv_{n}"] = _conv(p, f"{prefix}.zero_convs.{n}.0")
            n += 1

    out["mid_res_0"] = _res_block(p, f"{prefix}.middle_block.0", False)
    idx = 1
    if cfg.mid_block_depth is not None:
        out["mid_attn"] = _transformer(p, f"{prefix}.middle_block.1",
                                       cfg.mid_block_depth)
        idx = 2
    out["mid_res_1"] = _res_block(p, f"{prefix}.middle_block.{idx}", False)
    p.finish("controlnet")
    return out


# --------------------------------------------------------------------------
# preprocessors ("modules" in the reference's unit payloads)
# --------------------------------------------------------------------------

def preprocess_none(img: np.ndarray) -> np.ndarray:
    """Pass-through: image already IS the control map (e.g. user-drawn)."""
    return img.astype(np.float32) / 255.0 if img.dtype == np.uint8 else img


def preprocess_canny(img: np.ndarray, low: float = 100.0,
                     high: float = 200.0) -> np.ndarray:
    """Sobel-magnitude edge map with double threshold (cv2-free canny
    approximation). Thresholds are on the 0-255 gradient scale like cv2."""
    gray = np.asarray(img, np.float32)
    if gray.ndim == 3:
        gray = gray @ np.array([0.299, 0.587, 0.114], np.float32)
    # 3x3 gaussian-ish blur
    k = np.array([1.0, 2.0, 1.0], np.float32) / 4.0
    gray = np.apply_along_axis(lambda r: np.convolve(r, k, "same"), 1, gray)
    gray = np.apply_along_axis(lambda c: np.convolve(c, k, "same"), 0, gray)
    gx = np.zeros_like(gray)
    gy = np.zeros_like(gray)
    gx[:, 1:-1] = gray[:, 2:] - gray[:, :-2]
    gy[1:-1, :] = gray[2:, :] - gray[:-2, :]
    # x2: central difference is half the Sobel response cv2's thresholds
    # are calibrated against (the [1,2,1] smoothing is already applied)
    mag = 2.0 * np.sqrt(gx**2 + gy**2)
    strong = mag >= high
    weak = (mag >= low) & ~strong
    # weak pixels survive if any 8-neighbour is strong (one-pass hysteresis)
    pad = np.pad(strong, 1)
    neighbour = np.zeros_like(strong)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dy == 0 and dx == 0:
                continue
            neighbour |= pad[1 + dy: pad.shape[0] - 1 + dy,
                             1 + dx: pad.shape[1] - 1 + dx]
    edges = strong | (weak & neighbour)
    out = edges.astype(np.float32)
    return np.repeat(out[:, :, None], 3, axis=2)


def preprocess_inpaint(img: np.ndarray,
                       mask: Optional[np.ndarray] = None) -> np.ndarray:
    """ControlNet v1.1 inpaint convention: the hint is the image with
    masked pixels set to -1.0 (the unit payload's ``image.mask`` channel
    the reference forwards; white mask = repaint)."""
    out = preprocess_none(img).copy()
    if mask is not None:
        m = np.asarray(mask)
        if m.dtype == np.uint8 or m.max() > 1.0:
            m = m.astype(np.float32) / 255.0
        else:
            m = m.astype(np.float32)
        if m.ndim == 3:
            m = m[..., 0]
        out[m > 0.5] = -1.0
    return out


PREPROCESSORS = {
    "none": preprocess_none,
    "canny": preprocess_canny,
    "invert": lambda img: 1.0 - preprocess_none(img),
}


def run_preprocessor(module: str, img: np.ndarray,
                     mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Resolve a webui module name; unknown modules fall back to pass-through
    (same spirit as the reference's sampler fallback, worker.py:457-467).
    ``mask`` feeds mask-aware modules (inpaint family)."""
    name = (module or "none").lower()
    if name.startswith("inpaint"):  # inpaint / inpaint_only / +lama alias
        return preprocess_inpaint(img, mask)
    fn = PREPROCESSORS.get(name)
    if fn is None:
        from stable_diffusion_webui_distributed_tpu.runtime.logging import (
            get_logger,
        )

        get_logger().warning(
            "controlnet preprocessor '%s' unavailable; passing image "
            "through unprocessed", module)
        fn = preprocess_none
    return fn(img)

"""Two-stage device pipeline: base UNet and refiner on DISJOINT meshes.

The measured SDXL base+refiner request (BASELINE config #2) runs its two
models back-to-back on one device group, so the refiner serializes behind
the base for every dispatch group — one of the two hypothesized components
of the north-star gap (VERDICT r3/r4; PERF.md roofline). With two device
groups the stages overlap: while group ``i`` refines on mesh B, group
``i+1``'s base half is already running on mesh A. Dispatch is async, so a
single host thread drives both groups — the engines' ``sync=False``
denoise mode (engine._denoise_range) keeps the host from blocking on
either mesh; latents hop meshes via ``jax.device_put`` (ICI on silicon).

This is pipeline parallelism in the form that fits THIS workload: the
model is small enough to replicate, so stages split by MODEL (base |
refiner), not by layer — no microbatch bubbles beyond the first/last
group, and each mesh can still shard dp/tp internally.

Scope: txt2img, fixed-grid samplers, no hires/inpaint/ControlNet (the
config-#2 shape). Single-chip runs gain nothing (a device executes
serially) — this exists for multi-chip meshes and is validated on the
virtual CPU mesh (tests/test_parallel_pipeline.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.runtime import rng
from stable_diffusion_webui_distributed_tpu.samplers import kdiffusion as kd


def _to_mesh(x, mesh, batch: bool):
    """Commit ``x`` to ``mesh`` (dp-sharded batch dim when it divides,
    replicated otherwise); None mesh = leave placement alone."""
    from stable_diffusion_webui_distributed_tpu.runtime.mesh import (
        batch_sharding, replicated,
    )

    if mesh is None or x is None:
        return x
    dp = mesh.shape.get("dp", 1)
    if batch and dp > 1 and x.shape[0] % dp == 0:
        return jax.device_put(x, batch_sharding(mesh))
    return jax.device_put(x, replicated(mesh))


def pipelined_txt2img(base, refiner, payload, *, group_size: Optional[int] = None):
    """Generate ``payload`` with the base half on ``base``'s mesh and the
    refiner half on ``refiner``'s mesh, pipelined across dispatch groups.

    ``base`` and ``refiner`` are Engines constructed over (ideally
    disjoint) meshes. Returns a GenerationResult identical in content to
    the sequential single-group path — the seed contract keys every draw
    by global image index, so the pipeline layout never changes pixels.
    """
    from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
        GenerationResult, fix_seed,
    )

    if payload.init_images or payload.enable_hr:
        raise ValueError("stage pipeline: txt2img without hires only")
    if kd.resolve_sampler(payload.sampler_name).adaptive:
        raise ValueError("stage pipeline: fixed-grid samplers only "
                         "(DPM adaptive's host loop is inherently serial)")
    if not (0.0 < payload.refiner_switch_at < 1.0):
        raise ValueError("stage pipeline: needs refiner_switch_at in (0,1)")
    if payload.all_prompts:
        raise ValueError("stage pipeline: per-image prompts (prompt "
                         "matrix / scheduler sub-ranges) take the "
                         "sequential path")
    if base._parse_controlnet_units(payload):
        raise ValueError("stage pipeline: ControlNet units take the "
                         "sequential path")
    if base.family.inpaint:
        raise ValueError("stage pipeline: inpainting checkpoints take "
                         "the sequential path")

    payload = payload.model_copy()
    payload.seed = fix_seed(payload.seed)
    payload.subseed = fix_seed(payload.subseed)
    base._adaptive_incomplete = False
    base.state.begin_request()
    base._apply_prompt_loras(payload)   # same engine the sequential path
                                        # applies/deactivates LoRA tags on

    width, height = payload.width, payload.height
    h, w = base._latent_hw(width, height)
    # sampled latent channels — NOT unet.in_channels (engine.py:1132)
    C = base.family.vae.latent_channels
    steps = payload.steps
    # same clamp as _split_denoise (engine.py): switch may be 0, in which
    # case the base range is empty and the refiner runs every step
    switch = max(0, min(steps - 1, int(steps * payload.refiner_switch_at)))

    conds, pooleds = base.encode_prompts(payload)
    ref_conds, ref_pooleds = refiner.encode_prompts(payload)
    rmesh = refiner.mesh
    ref_conds = tuple(_to_mesh(c, rmesh, batch=False) for c in ref_conds)
    ref_pooleds = tuple(_to_mesh(p, rmesh, batch=False)
                        for p in ref_pooleds)

    spec = kd.resolve_sampler(payload.sampler_name)
    sigmas = kd.build_sigmas(spec, base.schedule, steps)

    out = GenerationResult(parameters=payload.model_dump())
    group = max(1, group_size or payload.batch_size)
    total = payload.total_images
    pos = 0
    pending = []   # (decode entries, already queued on base mesh)
    in_flight = []  # (refined latents on refiner mesh, pos, n)

    def flush_one():
        lat_r, p0, n0 = in_flight.pop(0)
        lat_back = _to_mesh(lat_r, base.mesh, batch=True) \
            if base.mesh is not None else jax.device_put(lat_r)
        pending.extend(base._queue_decoded(lat_back, p0, n0,
                                           width, height))

    while pos < total and not base.state.flag.interrupted:
        n = min(group, total - pos)
        noise = rng.batch_noise(
            payload.seed, payload.subseed, payload.subseed_strength,
            pos, n, (h, w, C),
            seed_resize=base._seed_resize_latent(payload),
            pin_index=payload.same_seed)
        x = base._place_batch(noise.astype(jnp.float32) * sigmas[0])
        keys = base._image_keys(payload, pos, n)
        # base half on mesh A — dispatched without host blocking
        lat = base._denoise_range(
            payload, x, keys, conds, pooleds, width, height, 0, steps,
            "txt2img", None, None, (), end_step=switch, sync=False)
        if base.state.flag.interrupted:
            # like _split_denoise: an interrupt during the base half skips
            # the refiner; the partial latents decode as-is. Drain the
            # in-flight (earlier-index) refined groups FIRST so the gallery
            # stays in global-index order — the interrupted group is the
            # newest and must decode last.
            while in_flight:
                flush_one()
            pending.extend(base._queue_decoded(lat, pos, n, width, height))
            break
        # hop to mesh B (async ICI copy; arguments may still be futures)
        lat_b = _to_mesh(lat, rmesh, batch=True)
        keys_b = _to_mesh(keys, rmesh, batch=True)
        refined = refiner._denoise_range(
            payload, lat_b, keys_b, ref_conds, ref_pooleds, width, height,
            switch, steps, "txt2img+refiner", None, None, sync=False)
        in_flight.append((refined, pos, n))
        # decode trails one group behind — the NEWEST group stays in
        # flight so base(g+1) dispatches ahead of decode(g) on the base
        # mesh's in-order stream (draining it here would chain decode(g)
        # behind refine(g) and re-serialize the stages)
        while len(in_flight) > 1:
            flush_one()
        if len(pending) > 1:
            base._flush_decoded(out, payload, pending[:-1])
            pending = pending[-1:]
        pos += n

    while in_flight:
        flush_one()
    base._flush_decoded(out, payload, pending)
    base.state.finish()
    return out

"""Two-stage device pipeline: base UNet and refiner on DISJOINT meshes.

The measured SDXL base+refiner request (BASELINE config #2) runs its two
models back-to-back on one device group, so the refiner serializes behind
the base for every dispatch group — one of the two hypothesized components
of the north-star gap (VERDICT r3/r4; PERF.md roofline). With two device
groups the stages overlap: while group ``i`` refines on mesh B, group
``i+1``'s base half is already running on mesh A. Dispatch is async, so a
single host thread drives both groups — the engines' ``sync=False``
denoise mode (engine._denoise_range) keeps the host from blocking on
either mesh; latents hop meshes via ``jax.device_put`` (ICI on silicon).

This is pipeline parallelism in the form that fits THIS workload: the
model is small enough to replicate, so stages split by MODEL (base |
refiner), not by layer — no microbatch bubbles beyond the first/last
group, and each mesh can still shard dp/tp internally.

The hand-rolled ``in_flight`` list this module shipped with grew into
``parallel/stage_graph.py``'s general N-node executor; each dispatch
group is now an encode → denoise → refine :class:`~.stage_graph.StageGraph`
and the decode-trails-one-group pacing is the
:class:`~.stage_graph.GraphRunner`'s depth window (depth 1 reproduces the
original schedule exactly; ``SDTPU_STAGE_DEPTH`` widens it).

Scope: txt2img, fixed-grid samplers, no hires/inpaint/ControlNet (the
config-#2 shape). Single-chip runs gain nothing (a device executes
serially) — this exists for multi-chip meshes and is validated on the
virtual CPU mesh (tests/test_parallel_pipeline.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.parallel import stage_graph
from stable_diffusion_webui_distributed_tpu.parallel.stage_graph import (
    to_mesh as _to_mesh,  # noqa: F401 — long-standing re-export
)
from stable_diffusion_webui_distributed_tpu.runtime import rng
from stable_diffusion_webui_distributed_tpu.samplers import kdiffusion as kd


def pipelined_txt2img(base, refiner, payload, *, group_size: Optional[int] = None):
    """Generate ``payload`` with the base half on ``base``'s mesh and the
    refiner half on ``refiner``'s mesh, pipelined across dispatch groups.

    ``base`` and ``refiner`` are Engines constructed over (ideally
    disjoint) meshes. Returns a GenerationResult identical in content to
    the sequential single-group path — the seed contract keys every draw
    by global image index, so the pipeline layout never changes pixels.
    """
    from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
        GenerationResult, fix_seed,
    )

    if payload.init_images or payload.enable_hr:
        raise ValueError("stage pipeline: txt2img without hires only")
    if kd.resolve_sampler(payload.sampler_name).adaptive:
        raise ValueError("stage pipeline: fixed-grid samplers only "
                         "(DPM adaptive's host loop is inherently serial)")
    if not (0.0 < payload.refiner_switch_at < 1.0):
        raise ValueError("stage pipeline: needs refiner_switch_at in (0,1)")
    if payload.all_prompts:
        raise ValueError("stage pipeline: per-image prompts (prompt "
                         "matrix / scheduler sub-ranges) take the "
                         "sequential path")
    if base._parse_controlnet_units(payload):
        raise ValueError("stage pipeline: ControlNet units take the "
                         "sequential path")
    if base.family.inpaint:
        raise ValueError("stage pipeline: inpainting checkpoints take "
                         "the sequential path")

    payload = payload.model_copy()
    payload.seed = fix_seed(payload.seed)
    payload.subseed = fix_seed(payload.subseed)
    base._adaptive_incomplete = False
    base.state.begin_request()
    base._apply_prompt_loras(payload)   # same engine the sequential path
                                        # applies/deactivates LoRA tags on

    width, height = payload.width, payload.height
    h, w = base._latent_hw(width, height)
    # sampled latent channels — NOT unet.in_channels (engine.py:1132)
    C = base.family.vae.latent_channels
    steps = payload.steps
    # same clamp as _split_denoise (engine.py): switch may be 0, in which
    # case the base range is empty and the refiner runs every step
    switch = max(0, min(steps - 1, int(steps * payload.refiner_switch_at)))

    conds, pooleds = base.encode_prompts(payload)
    ref_conds, ref_pooleds = refiner.encode_prompts(payload)
    rmesh = refiner.mesh
    ref_conds = tuple(_to_mesh(c, rmesh, batch=False) for c in ref_conds)
    ref_pooleds = tuple(_to_mesh(p, rmesh, batch=False)
                        for p in ref_pooleds)

    spec = kd.resolve_sampler(payload.sampler_name)
    sigmas = kd.build_sigmas(spec, base.schedule, steps)

    out = GenerationResult(parameters=payload.model_dump())
    group = max(1, group_size or payload.batch_size)
    total = payload.total_images
    pos = 0
    pending = []   # decode entries, already queued on base mesh
    # depth 1 = the original schedule: the NEWEST group stays in flight so
    # base(g+1) dispatches ahead of decode(g) on the base mesh's in-order
    # stream (flushing eagerly would chain decode(g) behind refine(g) and
    # re-serialize the stages)
    runner = stage_graph.GraphRunner(depth=stage_graph.depth(),
                                     clock=stage_graph.CLOCK)

    def make_flush(p0, n0):
        def flush(res):
            state, lat = res["refine"]
            if state == "refined":
                lat = _to_mesh(lat, base.mesh, batch=True) \
                    if base.mesh is not None else jax.device_put(lat)
            # "partial": base-half latents already on the base mesh — an
            # interrupt skipped the refiner and they decode as-is
            pending.extend(base._queue_decoded(lat, p0, n0,
                                               width, height))
            if len(pending) > 1:
                base._flush_decoded(out, payload, pending[:-1])
                del pending[:-1]
        return flush

    while pos < total and not base.state.flag.interrupted:
        n = min(group, total - pos)
        graph = stage_graph.StageGraph(
            label=f"base+refine[{pos}:{pos + n}]", group=pos,
            clock=stage_graph.CLOCK)

        def _encode(p0=pos, n0=n):
            noise = rng.batch_noise(
                payload.seed, payload.subseed, payload.subseed_strength,
                p0, n0, (h, w, C),
                seed_resize=base._seed_resize_latent(payload),
                pin_index=payload.same_seed)
            x = base._place_batch(noise.astype(jnp.float32) * sigmas[0])
            return x, base._image_keys(payload, p0, n0)

        def _denoise(enc):
            x, keys = enc
            # base half on mesh A — dispatched without host blocking
            lat = base._denoise_range(
                payload, x, keys, conds, pooleds, width, height, 0, steps,
                "txt2img", None, None, (), end_step=switch, sync=False)
            return lat, keys

        def _refine(den):
            lat, keys = den
            if base.state.flag.interrupted:
                # like _split_denoise: an interrupt during the base half
                # skips the refiner; the partial latents decode as-is
                return ("partial", lat)
            # hop to mesh B (async ICI copy; args may still be futures)
            lat_b = _to_mesh(lat, rmesh, batch=True)
            keys_b = _to_mesh(keys, rmesh, batch=True)
            refined = refiner._denoise_range(
                payload, lat_b, keys_b, ref_conds, ref_pooleds, width,
                height, switch, steps, "txt2img+refiner", None, None,
                sync=False)
            return ("refined", refined)

        graph.add("encode", _encode, kind="stage")
        graph.add("denoise", _denoise, deps=("encode",), kind="denoise")
        graph.add("refine", _refine, deps=("denoise",), kind="stage")
        runner.submit(graph, make_flush(pos, n))
        pos += n
        if graph.node("refine").result[0] == "partial":
            # drain in submit order so the gallery stays in global-index
            # order — the interrupted group is the newest and decodes last
            break

    runner.drain()
    base._flush_decoded(out, payload, pending)
    base.state.finish()
    return out

"""N-node stage-graph executor: async Encode / Denoise / ControlNet /
Decode overlap on the host timeline.

``parallel/stage_pipeline.py`` proved the two-stage form of this design:
drive several device groups from ONE host thread by dispatching every
stage async (the engines' ``sync=False`` denoise mode) and hopping
latents between meshes with ``jax.device_put``. This module generalizes
that hand-rolled ``in_flight`` list into an explicit dependency graph:

- :class:`StageGraph` — one dispatch group's stages as named nodes with
  data-dependency edges. Nodes run in topological order on the
  dispatching thread; the device work INSIDE a node is dispatched
  without blocking, so the host races ahead and group *i*'s VAE decode
  or group *i+1*'s CLIP encode overlaps group *i+1*'s denoise.
- :class:`GraphRunner` — the depth-limited FIFO in-flight window across
  groups. ``submit`` dispatches a graph now and defers its ``flush``
  (host materialization of the decode) until more than ``depth`` groups
  are in flight; ``drain`` flushes everything in order, which is also
  the interrupt/preempt seam (gallery order is global-image-index
  order, so the OLDEST group must always materialize first).
- :class:`OverlapClock` — host-timeline accounting: encode/decode/merge
  intervals are scored against OTHER groups' open or closed denoise
  windows, producing the ``stage_overlap_ratio`` the perf ledger and
  ``bench.py --stages`` report. Overlap is measured, never asserted.

Byte-identity contract: the graph never changes WHAT is computed — the
seed contract keys every noise draw by global image index and
``sync=False`` only changes host pacing — so gate-on images are
byte-identical to the serial path (tests/test_stagegraph.py pins both
directions). Gate: ``SDTPU_STAGE_GRAPH`` (default OFF; the off path
never imports this module on a hot path). ``SDTPU_STAGE_DEPTH`` sizes
the in-flight window; ``SDTPU_STAGE_CN_DEVICES`` carves the last N
visible devices into a mesh slice for the stage-ahead ControlNet tower
(pipeline/engine.py:_denoise_range_staged_cn).

This module stays importable without JAX on purpose (jax only inside
:func:`to_mesh`): the schedule-explorer harness
(sim/harnesses.py:stage_graph_harness) races real StageGraph/GraphRunner
objects under the cooperative scheduler, where device work is stubbed.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from stable_diffusion_webui_distributed_tpu.runtime.config import (
    env_flag,
    env_int,
)

__all__ = [
    "CLOCK",
    "GraphRunner",
    "OverlapClock",
    "StageGraph",
    "StageNode",
    "cn_slice_devices",
    "depth",
    "enabled",
    "to_mesh",
]

#: Fixed trace lanes (/internal/trace.json tid field) so every stage kind
#: renders on its own swimlane instead of the dispatching thread's —
#: overlapped stages from different groups would otherwise collapse into
#: one visually-serial row.
LANES = {
    "encode": -101,
    "controlnet": -102,
    "denoise": -103,
    "decode": -104,
    "merge": -105,
    "refine": -106,
}


def enabled() -> bool:
    """SDTPU_STAGE_GRAPH: route txt2img (engine) and grouped dispatch
    (serving dispatcher) through the stage-graph executor."""
    return env_flag("SDTPU_STAGE_GRAPH", False)


def depth() -> int:
    """SDTPU_STAGE_DEPTH: in-flight group window (>=1). Depth 1 matches
    the serial path's decode-trails-one-group pipelining."""
    return max(1, env_int("SDTPU_STAGE_DEPTH", 1))


def cn_slice_devices() -> int:
    """SDTPU_STAGE_CN_DEVICES: devices carved off for the ControlNet
    stage's own mesh slice (0 = evaluate on the UNet's devices)."""
    return max(0, env_int("SDTPU_STAGE_CN_DEVICES", 0))


def to_mesh(x, mesh, batch: bool):
    """Commit ``x`` to ``mesh`` (dp-sharded batch dim when it divides,
    replicated otherwise); None mesh = leave placement alone. Moved from
    stage_pipeline (which re-exports it) so the ControlNet slice hop and
    the base/refiner hop share one implementation."""
    import jax

    from stable_diffusion_webui_distributed_tpu.runtime.mesh import (
        batch_sharding,
        replicated,
    )

    if mesh is None or x is None:
        return x
    dp = mesh.shape.get("dp", 1)
    if batch and dp > 1 and x.shape[0] % dp == 0:
        return jax.device_put(x, batch_sharding(mesh))
    return jax.device_put(x, replicated(mesh))


class OverlapClock:
    """Host-timeline overlap accounting across dispatch groups.

    Denoise windows open when a group's denoise stage starts dispatching
    and close when the group's flush materializes (async engine path) or
    when the blocking denoise returns (sync dispatcher path). A stage
    interval (encode / decode dispatch / merge fetch) scores the seconds
    it spent inside ANY other group's denoise window — its own group is
    excluded so a stage can never overlap the denoise it feeds. Open
    windows clamp to "now", which is what makes eager scoring correct:
    by the time group *i*'s merge interval ends, group *i+1*'s denoise
    window has already opened even though it hasn't closed.
    """

    _KEEP = 512  # windows retained; bench runs stay far under this

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: _lock (every field below)
        self._open: List[List[Any]] = []     # [t0, group], still running
        self._closed: List[Tuple[float, float, Any]] = []
        self._stage_s = 0.0
        self._overlap_s = 0.0
        self._events = 0

    def begin_denoise(self, group: Any, t0: Optional[float] = None) -> None:
        with self._lock:
            self._open.append([time.perf_counter() if t0 is None else t0,
                               group])

    def end_denoise(self, group: Any, t1: Optional[float] = None) -> None:
        t1 = time.perf_counter() if t1 is None else t1
        with self._lock:
            for idx, (t0, grp) in enumerate(self._open):
                if grp == group:
                    self._open.pop(idx)
                    self._closed.append((t0, t1, grp))
                    if len(self._closed) > self._KEEP:
                        del self._closed[:-self._KEEP]
                    return

    def note_stage(self, t0: float, t1: float, group: Any) -> float:
        """Record one encode/decode/merge host interval; returns (and
        accumulates) the seconds of it overlapped with other groups'
        denoise windows."""
        ov = self.overlap_of(t0, t1, exclude_group=group)
        with self._lock:
            self._stage_s += max(0.0, t1 - t0)
            self._overlap_s += ov
            self._events += 1
        return ov

    def overlap_of(self, t0: float, t1: float,
                   exclude_group: Any = None) -> float:
        """Seconds of [t0, t1] covered by the union of denoise windows
        belonging to other groups (open windows clamp to now)."""
        now = time.perf_counter()
        with self._lock:
            wins = [(a, b) for a, b, grp in self._closed
                    if grp != exclude_group and b > t0 and a < t1]
            wins += [(a, now) for a, grp in self._open
                     if grp != exclude_group and now > t0 and a < t1]
        if not wins or t1 <= t0:
            return 0.0
        wins.sort()
        total = 0.0
        cur_a, cur_b = wins[0]
        for a, b in wins[1:]:
            if a > cur_b:
                total += max(0.0, min(cur_b, t1) - max(cur_a, t0))
                cur_a, cur_b = a, b
            else:
                cur_b = max(cur_b, b)
        total += max(0.0, min(cur_b, t1) - max(cur_a, t0))
        return total

    def summary(self) -> Dict[str, float]:
        with self._lock:
            ratio = (self._overlap_s / self._stage_s) if self._stage_s \
                else 0.0
            return {"stage_s": self._stage_s,
                    "overlap_s": self._overlap_s,
                    "events": float(self._events),
                    "stage_overlap_ratio": ratio}

    def reset(self) -> None:
        with self._lock:
            self._open.clear()
            self._closed.clear()
            self._stage_s = 0.0
            self._overlap_s = 0.0
            self._events = 0


#: Process-wide clock the engine and dispatcher feed; bench.py --stages
#: reads/resets it. Module-level singleton — explorer harnesses construct
#: fresh OverlapClock instances instead (its lock was born raw at import,
#: sim/harnesses.py ground rules).
CLOCK = OverlapClock()


class StageNode:
    """One stage of a dispatch group: name, callable, dependency names,
    and the host-timeline record of its execution."""

    __slots__ = ("name", "fn", "deps", "kind", "result", "t0", "t1",
                 "overlap", "ran")

    def __init__(self, name: str, fn: Callable[..., Any],
                 deps: Tuple[str, ...], kind: Optional[str]) -> None:
        self.name = name
        self.fn = fn
        self.deps = deps
        self.kind = kind
        self.result: Any = None
        self.t0 = 0.0
        self.t1 = 0.0
        self.overlap = 0.0
        self.ran = False

    def seconds(self) -> float:
        return max(0.0, self.t1 - self.t0)


class StageGraph:
    """Stages of ONE dispatch group as an explicit dependency graph.

    ``add`` requires every dependency to already exist, so insertion
    order is a topological order and cycles are impossible by
    construction. ``run(until=...)`` executes the not-yet-run prefix on
    the calling thread — the serving dispatcher uses the split point to
    run encode/denoise/decode under the device lock and the merge node
    after releasing it.

    Node ``kind`` routes host-interval accounting:

    - ``"stage"``   — scored against other groups' denoise windows
      (:meth:`OverlapClock.note_stage`).
    - ``"denoise"`` — opens a denoise window at node start; the
      GraphRunner closes it when the group's flush materializes (the
      async engine path, where the node's host return means "dispatched",
      not "done").
    - ``"denoise_sync"`` — opens and closes the window around the node
      (the dispatcher path, whose denoise blocks).
    - ``None``      — no clock accounting.

    ``on_stage(name, seconds)`` fires after every node — the per-stage
    completion callback surface (serving/dispatcher.py Ticket.on_stage).
    ``obs=False`` skips prometheus/span emission entirely (explorer
    harnesses run without the obs singletons).
    """

    def __init__(self, label: str = "", group: Any = None,
                 clock: Optional[OverlapClock] = None,
                 on_stage: Optional[Callable[[str, float], None]] = None,
                 obs: bool = True) -> None:
        self.label = label
        self.group = group
        self.clock = clock
        self.on_stage = on_stage
        self.obs = obs
        self.open_denoise = False  # async window awaiting runner close
        self._nodes: "Dict[str, StageNode]" = {}  # insertion = topo order

    def add(self, name: str, fn: Callable[..., Any],
            deps: Sequence[str] = (), kind: Optional[str] = "stage") -> None:
        if name in self._nodes:
            raise ValueError(f"stage graph: duplicate node {name!r}")
        for d in deps:
            if d not in self._nodes:
                raise ValueError(
                    f"stage graph: node {name!r} depends on undefined "
                    f"{d!r} (dependencies must be added first)")
        self._nodes[name] = StageNode(name, fn, tuple(deps), kind)

    def node(self, name: str) -> StageNode:
        return self._nodes[name]

    def results(self) -> Dict[str, Any]:
        return {n.name: n.result for n in self._nodes.values() if n.ran}

    def stage_seconds(self) -> float:
        """Host seconds of every completed ``"stage"``-kind node."""
        return sum(n.seconds() for n in self._nodes.values()
                   if n.ran and n.kind == "stage")

    def stage_overlap(self) -> float:
        return sum(n.overlap for n in self._nodes.values()
                   if n.ran and n.kind == "stage")

    def run(self, until: Optional[str] = None) -> Dict[str, Any]:
        """Execute not-yet-run nodes in insertion (= topological) order,
        stopping AFTER ``until`` when given; returns name -> result for
        everything completed so far."""
        for node in self._nodes.values():
            if node.ran:
                if node.name == until:
                    break
                continue
            node.t0 = time.perf_counter()
            if node.kind in ("denoise", "denoise_sync") \
                    and self.clock is not None:
                self.clock.begin_denoise(self.group, node.t0)
                self.open_denoise = True
            node.result = node.fn(
                *(self._nodes[d].result for d in node.deps))
            node.t1 = time.perf_counter()
            node.ran = True
            if self.clock is not None:
                if node.kind == "denoise_sync":
                    self.clock.end_denoise(self.group, node.t1)
                    self.open_denoise = False
                elif node.kind == "stage":
                    node.overlap = self.clock.note_stage(
                        node.t0, node.t1, self.group)
            self._observe(node)
            if node.name == until:
                break
        return self.results()

    def close_denoise(self, t1: Optional[float] = None) -> None:
        """Close this group's async denoise window (GraphRunner calls
        this when the group's flush has materialized)."""
        if self.open_denoise and self.clock is not None:
            self.clock.end_denoise(self.group, t1)
            self.open_denoise = False

    def _observe(self, node: StageNode) -> None:
        secs = node.seconds()
        if self.obs:
            try:
                from stable_diffusion_webui_distributed_tpu.obs import (
                    prometheus as obs_prom,
                )
                from stable_diffusion_webui_distributed_tpu.obs import (
                    spans as obs_spans,
                )

                obs_prom.observe_stage_graph(node.name, secs)
                obs_spans.add_span(
                    obs_spans.current(), f"stage.{node.name}", node.t0,
                    secs, attrs={"group": str(self.group),
                                 "graph": self.label},
                    lane=LANES.get(node.name))
            except Exception:  # noqa: BLE001 — obs stays best-effort
                pass
        if self.on_stage is not None:
            try:
                self.on_stage(node.name, secs)
            except Exception:  # noqa: BLE001 — callbacks stay best-effort
                pass


class GraphRunner:
    """Depth-limited FIFO in-flight window of per-group StageGraphs.

    ``submit`` runs the graph's nodes NOW (device work inside them
    dispatches async) and queues its ``flush`` — the host
    materialization step — until more than ``depth`` groups are in
    flight, so the newest group's device work always dispatches ahead of
    an older group's blocking fetch (the same decode-trails-one-group
    rule the serial loop and stage_pipeline use). ``drain`` flushes
    everything in order: the interrupt/preempt seam.

    Thread-safety: submit/drain may race (the engine's preempt protocol
    drains from the dispatching thread while a cancel drains elsewhere);
    flushes execute UNDER the runner lock so a racing drain can never
    reorder or double-run a flush — gallery order is the invariant the
    explorer harness checks.
    """

    def __init__(self, depth: int = 1,
                 clock: Optional[OverlapClock] = None) -> None:
        self._lock = threading.Lock()
        # guarded-by: _lock (_in_flight, flushed; flushes run under it)
        self._in_flight: List[Tuple[StageGraph, Callable[[Dict[str, Any]],
                                                         None]]] = []
        self._depth = max(1, int(depth))
        self._clock = clock
        self.flushed = 0

    def submit(self, graph: StageGraph,
               flush: Callable[[Dict[str, Any]], None]) -> None:
        graph.run()
        with self._lock:
            self._in_flight.append((graph, flush))
            excess = len(self._in_flight) - self._depth
        self._flush_n(excess)

    def drain(self) -> None:
        self._flush_n(None)

    def in_flight(self) -> int:
        with self._lock:
            return len(self._in_flight)

    def _flush_n(self, k: Optional[int]) -> None:
        """Flush up to ``k`` oldest graphs (None = everything). Each
        pop+flush pair runs under the lock, so racing drains serialize
        per item and can never reorder or double-run a flush; a
        competitor that already emptied the window just ends this loop
        early."""
        done = 0
        while k is None or done < k:
            with self._lock:
                if not self._in_flight:
                    return
                graph, flush = self._in_flight.pop(0)
                t0 = time.perf_counter()
                try:
                    flush(graph.results())
                finally:
                    t1 = time.perf_counter()
                    # the fetch returning is the proof the group's device
                    # work is done — close its denoise window here, then
                    # score the fetch interval against the OTHER open ones
                    graph.close_denoise(t1)
                    if self._clock is not None:
                        self._clock.note_stage(t0, t1, graph.group)
                    self.flushed += 1
            done += 1

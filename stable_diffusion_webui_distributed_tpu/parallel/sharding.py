"""Sharding placement: batch over ``dp``, Megatron-pattern weights over ``tp``.

Design (scaling-book recipe): pick a mesh, annotate input shardings, let
XLA's SPMD partitioner insert the collectives. The UNet/CLIP modules stay
sharding-agnostic; placement happens on the param pytree and the batch
inputs, so the same compiled code serves 1 chip or a v5e-16 slice.

TP rules (applied by param-path pattern, the Megatron split):
- fused QKV / q / kv / fc1 / geglu proj / time+add MLP fc1: split the
  *output* features over ``tp`` (column parallel);
- out_proj / fc2 / ff_out / MLP fc2: split the *input* features over ``tp``
  (row parallel; XLA inserts the psum);
- convs: split output channels (last dim of HWIO) over ``tp``;
- norms, biases of row-parallel layers, embeddings: replicated.
"""

from __future__ import annotations


import jax


_COLUMN_ENDINGS = ("qkv", "q", "kv", "fc1", "proj", "time_fc1", "add_fc1",
                   "time_proj", "proj_in")
_ROW_ENDINGS = ("out_proj", "fc2", "ff_out", "time_fc2", "add_fc2",
                "proj_out")


def keystr_path(keypath, separator: str = "/") -> str:
    """Version-compat ``jax.tree_util.keystr`` in "simple" form.

    ``keystr(..., simple=True, separator=...)`` only exists from jax 0.4.35
    behind a changing signature (0.4.37 still raises TypeError on the
    kwargs). Every keystr call site in the repo goes through this shim:
    try the modern call, fall back to joining the key entries by hand —
    DictKey('a')/GetAttrKey('a') -> "a", SequenceKey(0) -> "0" — which is
    exactly what ``simple=True`` produces."""
    try:
        return jax.tree_util.keystr(keypath, simple=True,
                                    separator=separator)
    except TypeError:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):       # DictKey / FlattenedIndexKey
                parts.append(str(k.key))
            elif hasattr(k, "name"):    # GetAttrKey
                parts.append(str(k.name))
            elif hasattr(k, "idx"):     # SequenceKey
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        return separator.join(parts)


def tp_spec_for(path: str, ndim: int):
    """PartitionSpec for one param, from its tree path (joined with '/')."""
    from jax.sharding import PartitionSpec as P

    parts = path.strip("/").split("/")
    leaf = parts[-1]              # kernel | bias | scale | embedding
    module = parts[-2] if len(parts) > 1 else ""

    if leaf == "kernel":
        if module in _ROW_ENDINGS:
            # row-parallel: contract dim sharded
            return P(*([None] * (ndim - 2) + ["tp", None]))
        if module in _COLUMN_ENDINGS or module == "conv":
            return P(*([None] * (ndim - 1) + ["tp"]))
        if ndim >= 2:
            # default: treat as column-parallel (safe — no correctness risk,
            # XLA all-gathers where needed)
            return P(*([None] * (ndim - 1) + ["tp"]))
    if leaf == "bias" and module in _COLUMN_ENDINGS:
        return P("tp")
    # norms, embeddings, row-parallel biases: replicated
    return P()


def shard_params(params, mesh, use_tp: bool = True):
    """Place a param pytree on ``mesh``: TP rules if the mesh has tp>1,
    otherwise fully replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if mesh is None:
        return params
    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves, treedef = flat
    placed = []
    for keypath, leaf in leaves:
        if tp > 1 and use_tp and hasattr(leaf, "ndim"):
            path = keystr_path(keypath, separator="/")
            spec = tp_spec_for(path, leaf.ndim)
            # only shard dims that divide evenly; else replicate
            ok = True
            for dim, axis in enumerate(spec):
                if axis == "tp" and leaf.shape[dim] % tp != 0:
                    ok = False
            sharding = NamedSharding(mesh, spec if ok else P())
        else:
            sharding = NamedSharding(mesh, P())
        placed.append(jax.device_put(leaf, sharding))
    return jax.tree_util.tree_unflatten(treedef, placed)


def batch_concat(parts):
    """Concatenate equal-shaped blocks along axis 0 (the CFG [uncond; cond]
    doubling and its conditioning rows) without ``jnp.concatenate``.

    jax 0.4.x's SPMD partitioner mis-compiles a concatenate whose concat
    dimension is sharded when the mesh carries a second axis the operands
    do not use: each replica along that axis contributes a partial
    concatenate that gets summed, scaling values by the axis size.
    Minimal repro — place x with P('dp') on a ('dp','tp') mesh and
    ``jnp.concatenate([x, x], axis=0)`` returns rows of 2*x. stack+reshape
    expresses the identical layout through a reshape, which partitions
    correctly on the same meshes (eager and jitted), so every batch-axis
    concat reachable with a dp-sharded operand routes through here."""
    import jax.numpy as jnp

    parts = list(parts)
    if len(parts) == 1:
        return parts[0]
    first = parts[0]
    stacked = jnp.stack(parts, axis=0)
    return stacked.reshape((len(parts) * first.shape[0],)
                           + tuple(first.shape[1:]))


def channel_concat(parts):
    """Concatenate along the last (feature/channel) dimension without
    ``jnp.concatenate`` — the same partitioner mis-lowering as
    ``batch_concat`` hits here when the channel dim is tp-sharded (the
    UNet decoder's skip concat, the SDXL dual-text-encoder context).
    Parts may have different channel widths, so instead of stack+reshape
    each part is zero-padded to the full output width at its own offset
    and the padded blocks are summed; pad and add both partition
    correctly on multi-axis meshes."""
    import jax.numpy as jnp

    parts = list(parts)
    if len(parts) == 1:
        return parts[0]
    total = sum(p.shape[-1] for p in parts)
    out = None
    off = 0
    for p in parts:
        widths = [(0, 0)] * (p.ndim - 1) + [(off, total - off - p.shape[-1])]
        padded = jnp.pad(p, widths)
        out = padded if out is None else out + padded
        off += p.shape[-1]
    return out


def place_batch(x, mesh):
    """Put a batch-major array on the mesh, axis 0 split over ``dp``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        return x
    spec = P(*(["dp"] + [None] * (x.ndim - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        return x
    return jax.device_put(x, NamedSharding(mesh, P()))

"""Parallelism: batch-DP sharding, Megatron-style TP rules, multi-host init.

The reference's single strategy — split the image batch across workers
proportional to speed (/root/reference/scripts/spartan/world.py:111-115,
418-601) — maps here to sharding the batch axis of every tensor over the
mesh's ``dp`` axis and letting XLA emit ICI collectives. Tensor parallelism
(``tp``) is an addition the reference has no counterpart for.
"""

from stable_diffusion_webui_distributed_tpu.parallel.sharding import (  # noqa: F401
    shard_params,
    place_batch,
    tp_spec_for,
)

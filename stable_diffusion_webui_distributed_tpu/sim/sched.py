"""Deterministic schedule explorer over locksan yield points.

The runtime lockset sanitizer (runtime/locksan.py) wraps every
``threading`` lock and condition created after ``install()``. This
module exploits that seam: an :class:`Explorer` registers itself via
``locksan.set_scheduler`` and every lock acquire/release, condition
wait/notify, thread start and thread join performed by a *managed*
thread becomes a cooperative yield point. Exactly one managed thread
runs at a time (a token handed around with raw, unwrapped locks), so a
run's interleaving is a pure function of the seed — no wall-clock, no
OS scheduler, no flakes.

Scheduling is PCT-style (probabilistic concurrency testing): each task
draws a random priority at registration, the highest-priority runnable
task runs until its next yield point, and at a few seeded
priority-change steps the running task is demoted — shallow-depth bug
interleavings (the common kind) get hit with high probability across a
modest seed sweep. ≥64 seeds per harness is the repo's floor
(tests/test_sched.py, ``bench.py --ledger``).

Verdicts, per run:

- **deadlock** — unfinished tasks remain and none is runnable: every
  one is blocked on a lock whose owner cannot run, waiting on a
  condition nobody can notify, or joining a thread that cannot finish.
  The detail names each task's blocker — that plus the trace is the
  repro.
- **livelock** — the step budget ran out (tasks kept yielding without
  finishing); harnesses treat it as a failure too.
- **completed** — every task ran to the end of its body; the harness
  then checks its own invariants over the shared state.

Scope and honest limits: only threads spawned through
:meth:`Explorer.spawn` (or started by managed code while the explorer
is active — ``Thread.start`` is adopted) are serialized. Locks created
*before* ``locksan.install()`` are raw and invisible — a managed thread
hard-blocking on one would hang the explorer, so harnesses construct
fresh objects after install and never touch module-level locks born at
import time. Timed waits don't model real time: a timeout burns a fixed
number of yields (``timeout_yields``) and then gives up, which keeps
runs finite and deterministic but means "waited 0.25 s" and "waited
60 s" explore identically.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.runtime import locksan

__all__ = ["Explorer", "ExploreResult"]


@dataclass
class ExploreResult:
    seed: int
    steps: int = 0
    trace: List[str] = field(default_factory=list)
    deadlocked: bool = False
    deadlock: Optional[str] = None
    livelock: bool = False
    completed: bool = False
    errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.completed and not self.deadlocked \
            and not self.livelock and not self.errors

    def digest(self) -> str:
        """Stable fingerprint of the interleaving (determinism tests
        compare digests across repeated same-seed runs)."""
        import hashlib
        return hashlib.sha256("\n".join(self.trace).encode()).hexdigest()


class _Task:
    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.gate = locksan._real_lock()
        self.gate.acquire()  # starts closed; a grant opens it
        self.prio = 0.0
        self.started = False
        self.finished = False
        self.blocked_on: Optional[int] = None  # id(raw lock)
        self.blocked_name = ""
        self.wait_cell: Optional[List[bool]] = None  # untimed cond wait
        self.join_target: Optional["_Task"] = None
        self.error: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


class Explorer:
    """One seeded exploration run. Usage::

        ex = Explorer(seed)
        ex.spawn(body_a, "a")
        ex.spawn(body_b, "b")
        result = ex.run()

    ``run()`` requires ``locksan.install()`` to be active (the harness
    fixtures handle it) and must not be nested.
    """

    def __init__(self, seed: int, max_steps: int = 4000,
                 timeout_yields: int = 3, change_points: int = 6,
                 change_horizon: int = 64, eps: float = 0.25):
        self.seed = seed
        self.max_steps = max_steps
        #: scheduling grants a timed wait/join burns before timing out
        self.timeout_yields = timeout_yields
        #: probability a grant ignores priorities and picks uniformly —
        #: pure PCT underexplores the tiny harnesses (a high-priority
        #: task runs to completion through its own yield points)
        self.eps = eps
        self._rng = random.Random(seed)
        self._tasks: List[_Task] = []
        self._tls = threading.local()
        self._control = locksan._real_lock()
        #: id(raw lock) -> [task, recursion count]
        self._owners: Dict[int, List] = {}
        self._result = ExploreResult(seed=seed)
        self._step = 0
        # PCT priority-change points, drawn over the realistic run
        # horizon (harness runs are tens of steps; drawing over
        # max_steps would mean the change almost never lands mid-run)
        self._change_steps = {
            self._rng.randrange(change_horizon)
            for _ in range(change_points)}
        self._anon: Dict[int, str] = {}  # id(raw) -> stable per-run name
        self._orig_start = None
        self._orig_join = None
        self._orig_alive = None
        self._ran = False

    # -- registration --------------------------------------------------------

    def spawn(self, body: Callable[[], object], name: str) -> None:
        """Register a managed task; its thread starts at ``run()``."""
        task = self._register(name)
        task.thread = threading.Thread(
            target=self._task_body, args=(task, body),
            name=name, daemon=True)

    def _register(self, name: str) -> _Task:
        task = _Task(len(self._tasks), name)
        task.prio = self._rng.random()
        self._tasks.append(task)
        return task

    def _task_body(self, task: _Task, body: Callable[[], object]) -> None:
        self._tls.task = task
        task.gate.acquire()  # wait for the first grant
        try:
            body()
        except BaseException as e:  # noqa: BLE001 — recorded, not raised
            task.error = e
        finally:
            task.finished = True
            self._trace(task, "finish")
            self._control.release()  # hand the token home for good

    # -- thread adoption (code under test spawning its own threads) ----------

    class _StartedGate:
        """Stand-in for ``Thread._started`` on an adopted thread.

        ``Thread.start`` blocks on ``_started.wait()`` until the child's
        bootstrap calls ``_started.set()`` — but the bootstrap runs on
        the raw OS thread BEFORE the adoption wrapper parks it on its
        grant gate, so the set() lands at wall-clock time, not at a
        schedule point. A managed parent would then sometimes fast-path
        the wait and sometimes cooperatively block, splitting the trace
        on OS timing. The gate makes the parent's wait a deterministic
        no-op: the explorer's own grant gate is what actually sequences
        the child, so waiting for the bootstrap buys nothing.
        """

        def __init__(self, real) -> None:
            self._real = real

        def is_set(self):
            return self._real.is_set()

        def set(self):
            self._real.set()

        def wait(self, timeout=None):
            return True

    def _install_thread_patches(self) -> None:
        self._orig_start = threading.Thread.start
        self._orig_join = threading.Thread.join
        self._orig_alive = threading.Thread.is_alive
        explorer = self

        def start(th):
            if explorer._current() is None:
                return explorer._orig_start(th)
            task = explorer._register(th.name)
            task.thread = th
            task.started = True  # grantable as soon as the OS thread parks
            th._started = Explorer._StartedGate(th._started)
            orig_run = th.run

            def run():
                explorer._tls.task = task
                task.gate.acquire()
                try:
                    orig_run()
                except BaseException as e:  # noqa: BLE001
                    task.error = e
                finally:
                    task.finished = True
                    explorer._trace(task, "finish")
                    explorer._control.release()

            th.run = run
            explorer._trace(explorer._current(), f"spawn:{th.name}")
            return explorer._orig_start(th)

        def is_alive(th):
            # A finished task's OS thread tears down at wall-clock time
            # (tstate release), so the real is_alive() read is racy even
            # under a serialized schedule. For managed threads, liveness
            # is the task state the scheduler already sequences.
            cur = explorer._current()
            target = next((t for t in explorer._tasks
                           if t.thread is th), None)
            if cur is None or target is None:
                return explorer._orig_alive(th)
            return target.started and not target.finished

        def join(th, timeout=None):
            cur = explorer._current()
            target = next((t for t in explorer._tasks
                           if t.thread is th), None)
            if cur is None or target is None:
                return explorer._orig_join(th, timeout)
            if timeout is None:
                cur.join_target = target
                explorer._yield(cur, f"join:{target.name}")
                cur.join_target = None
                return
            for _ in range(explorer.timeout_yields):
                if target.finished:
                    return
                explorer._yield(cur, f"join:{target.name}")
            return

        threading.Thread.start = start
        threading.Thread.join = join
        threading.Thread.is_alive = is_alive

    def _remove_thread_patches(self) -> None:
        if self._orig_start is not None:
            threading.Thread.start = self._orig_start
            threading.Thread.join = self._orig_join
            threading.Thread.is_alive = self._orig_alive
            self._orig_start = self._orig_join = None

    # -- locksan scheduler protocol ------------------------------------------

    def managed(self) -> bool:
        return getattr(self._tls, "task", None) is not None

    def _current(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    def _lock_name(self, lock) -> str:
        """Trace-stable lock label: the locksan name, or a per-run
        first-sight sequence number (never ``id()`` — traces must be
        byte-identical across same-seed runs)."""
        if lock._san_name is not None:
            return lock._san_name
        key = id(lock._raw)
        if key not in self._anon:
            self._anon[key] = f"anon{len(self._anon)}"
        return self._anon[key]

    def lock_acquire(self, lock, blocking=True, timeout=-1) -> bool:
        task = self._current()
        raw = lock._raw
        name = self._lock_name(lock)
        budget = self.timeout_yields if (timeout is not None
                                         and timeout >= 0) else None
        # the pre-acquire scheduling point: without it, consecutive
        # acquires by one task are atomic and no inversion can interleave
        self._yield(task, f"pre:{name}")
        while True:
            if raw.acquire(False):
                owner = self._owners.get(id(raw))
                if owner is not None and owner[0] is task:
                    owner[1] += 1  # RLock recursion
                else:
                    self._owners[id(raw)] = [task, 1]
                self._trace(task, f"acquire:{name}")
                return True
            if not blocking:
                self._trace(task, f"tryfail:{name}")
                return False
            if budget is not None:
                if budget <= 0:
                    self._trace(task, f"timeout:{name}")
                    return False
                budget -= 1
                self._yield(task, f"blocked:{name}")
                continue
            task.blocked_on = id(raw)
            task.blocked_name = name
            self._yield(task, f"blocked:{name}")
            task.blocked_on = None
            task.blocked_name = ""

    def lock_release(self, lock) -> None:
        task = self._current()
        raw = lock._raw
        name = self._lock_name(lock)
        owner = self._owners.get(id(raw))
        if owner is not None and owner[0] is task:
            owner[1] -= 1
            if owner[1] <= 0:
                del self._owners[id(raw)]
        raw.release()
        self._trace(task, f"release:{name}")
        self._yield(task, f"released:{name}")

    def cond_wait(self, cond, timeout) -> bool:
        task = self._current()
        cell = [False]
        cond._coop_waiters.append(cell)
        lock = cond._san_lock
        lock.release()  # routes back through lock_release (yields)
        woken = False
        if timeout is None:
            task.wait_cell = cell
            self._yield(task, "cond_wait")
            task.wait_cell = None
            woken = cell[0]
        else:
            for _ in range(self.timeout_yields):
                self._yield(task, "cond_wait")
                if cell[0]:
                    woken = True
                    break
        if not woken and cell in cond._coop_waiters:
            cond._coop_waiters.remove(cell)
        lock.acquire()
        return woken

    # -- the scheduling loop -------------------------------------------------

    def run(self) -> ExploreResult:
        if self._ran:
            raise RuntimeError("Explorer instances are single-use")
        self._ran = True
        if not locksan.installed():
            raise RuntimeError("schedule exploration requires "
                               "locksan.install() (see the sched fixtures)")
        prior = locksan.scheduler()
        locksan.set_scheduler(self)
        self._install_thread_patches()
        self._control.acquire()  # token starts with the scheduler
        try:
            for task in self._tasks:
                task.started = True
                task.thread.start()
            self._loop()
        finally:
            self._remove_thread_patches()
            locksan.set_scheduler(prior)
            # reap: every finished task's thread exits on its own; give
            # stragglers (deadlocked tasks still parked on their gates)
            # nothing — they are daemon threads and the result records
            # them. Releasing their gates here would run them unmanaged.
        res = self._result
        res.steps = self._step
        res.completed = all(t.finished for t in self._tasks)
        res.errors = [f"{t.name}: {t.error!r}" for t in self._tasks
                      if t.error is not None]
        if res.completed:
            for t in self._tasks:  # patches removed above: plain joins
                t.thread.join(timeout=5.0)
        return res

    def _runnable(self, task: _Task) -> bool:
        if task.finished or not task.started:
            return False
        if task.blocked_on is not None and task.blocked_on in self._owners:
            return False
        if task.wait_cell is not None and not task.wait_cell[0]:
            return False
        if task.join_target is not None and not task.join_target.finished:
            return False
        return True

    def _loop(self) -> None:
        while True:
            live = [t for t in self._tasks if t.started and not t.finished]
            if not live:
                return
            runnable = [t for t in live if self._runnable(t)]
            if not runnable:
                self._result.deadlocked = True
                self._result.deadlock = "; ".join(
                    f"{t.name} {self._blocker(t)}" for t in live)
                return
            if self._step >= self.max_steps:
                self._result.livelock = True
                return
            if self._step in self._change_steps and len(runnable) > 1:
                top = max(runnable, key=lambda t: (t.prio, -t.tid))
                top.prio -= 1.0 + self._rng.random()
            if len(runnable) > 1 and self._rng.random() < self.eps:
                task = runnable[self._rng.randrange(len(runnable))]
            else:
                task = max(runnable, key=lambda t: (t.prio, -t.tid))
            self._step += 1
            task.gate.release()  # grant
            self._control.acquire()  # until it yields or finishes

    def _blocker(self, t: _Task) -> str:
        if t.blocked_on is not None:
            owner = self._owners.get(t.blocked_on)
            who = owner[0].name if owner else "?"
            return f"blocked on {t.blocked_name} held by {who}"
        if t.wait_cell is not None:
            return "in cond.wait with nobody left to notify"
        if t.join_target is not None:
            return f"joining {t.join_target.name}"
        return "not runnable"

    def _yield(self, task: _Task, why: str) -> None:
        self._trace(task, f"yield:{why}")
        self._control.release()
        task.gate.acquire()

    def _trace(self, task: Optional[_Task], event: str) -> None:
        name = task.name if task is not None else "<sched>"
        self._result.trace.append(f"{len(self._result.trace)}:{name}:{event}")


def explore(build: Callable[["Explorer"], Optional[Callable[[], List[str]]]],
            seeds: range) -> List[ExploreResult]:
    """Run one harness across a seed range. ``build`` receives a fresh
    Explorer, spawns its tasks, and may return an invariant checker
    (zero-arg callable returning a list of violation strings, called
    after a completed run). Results carry any violations as errors."""
    results = []
    for seed in seeds:
        ex = Explorer(seed)
        check = build(ex)
        res = ex.run()
        if res.ok and check is not None:
            res.errors.extend(check())
        results.append(res)
    return results

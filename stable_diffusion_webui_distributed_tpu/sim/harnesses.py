"""Schedule-explorer harnesses for the package's real lock protocols.

Each harness is a ``build(ex)`` callable for :func:`sim.sched.explore`:
it constructs a real subsystem object (FleetGate, ServingDispatcher,
Notifier, StoppableDaemon), spawns the threads that race over it, and
returns an invariant checker run after every completed interleaving.
The explorer then drives the harness across a seed range of PCT-style
priority schedules; a deadlock, livelock, task exception, or checker
violation fails that seed.

Ground rules (these are load-bearing — see sim/sched.py):

- ``locksan.install()`` must be active BEFORE a builder runs: locks and
  events the subsystem creates in its constructor must be the sanitized
  wrappers, or a managed thread hard-blocks the whole explorer on a raw
  primitive. The ``explore`` entry asserts install; builders construct
  all objects fresh rather than touching module-level singletons (whose
  locks were born raw at import time).
- Blocking that a harness thread performs must route through wrapped
  primitives (Lock/Condition/Event built post-install). Timed waits are
  fine — they burn ``timeout_yields`` grants and give up, which is how
  the 0.25 s cv-wait in FleetGate.acquire stays live under the
  scheduler.
- Network and env are off-limits: delivery callables are stubbed per
  instance, and the notifier harness uses ``notify_transition``'s
  ``force=True`` seam instead of setting ``SDTPU_NOTIFY_URL`` (EV001).

The harnesses cover the lock protocols the static tier reasons about:
condition-variable handoff (FleetGate), two-lock leader/follower
coalescing with cancellation (dispatcher), multi-channel
producer/drain-daemon shutdown (notifier), daemon stop/restart
(StoppableDaemon), the push-plane delta subscriber's cursor-resume
fetch/apply cycle racing reconnect and stop (DeltaSubscriber), and the
stage-graph runner's submit/drain FIFO with per-stage completion
callbacks racing cancel and preempt (GraphRunner).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

# Imported eagerly on purpose: FleetGate.yield_device and the notifier's
# outcome counters lazy-import these inside the code under test. A first
# run would then execute the import (creating module-level locks mid-run
# on a managed thread) while every later run skips it — splitting the
# trace and breaking same-seed determinism. Warm them before any
# explorer exists so every run sees identical global state.
from ..obs import journal as _journal  # noqa: F401
from ..obs import prometheus as _prometheus  # noqa: F401
from . import sched

__all__ = [
    "HARNESSES",
    "daemon_restart_harness",
    "delta_subscriber_harness",
    "dispatcher_coalesce_harness",
    "fleet_gate_harness",
    "notifier_drain_harness",
    "run_harness",
    "stage_graph_harness",
    "warm_pool_harness",
]


# -- FleetGate: acquire / should_yield / yield_device ------------------------

def fleet_gate_harness(ex: "sched.Explorer") -> Callable[[], List[str]]:
    """A preemptible batch runner and an interactive runner race over one
    FleetGate. The batch runner polls ``should_yield`` at its chunk
    boundaries and yields the device; the interactive waiter must get
    in, and at most one runner may ever hold the device."""
    from ..fleet import policy as fleet_policy

    # Deterministic stepping clock: quantum 0 makes should_yield purely
    # queue-driven, huge aging keeps the WFQ selection order fixed.
    ticks = [0.0]

    def clock() -> float:
        ticks[0] += 1.0
        return ticks[0]

    pol = fleet_policy.FleetPolicy(aging_s=1000.0, quantum_s=0.0)
    gate = fleet_policy.FleetGate(pol, clock=clock)
    active = [0]
    violations: List[str] = []

    def enter(who: str) -> None:
        active[0] += 1
        if active[0] > 1:
            violations.append(
                f"mutual exclusion broken: {who} entered with "
                f"{active[0] - 1} other holder(s)")

    def leave() -> None:
        active[0] -= 1

    def batch_runner() -> None:
        entry = fleet_policy.GateEntry(
            pol.resolve("batch"), tenant="t-batch", cost=2.0,
            request_id="rq-batch")
        gate.acquire(entry)
        enter("batch")
        for _ in range(2):  # two chunk boundaries
            if gate.should_yield(entry):
                leave()
                gate.yield_device(entry)
                enter("batch")
        leave()
        gate.release(entry)

    def interactive_runner() -> None:
        entry = fleet_policy.GateEntry(
            pol.resolve("interactive"), tenant="t-int", cost=1.0,
            request_id="rq-int")
        gate.acquire(entry)
        enter("interactive")
        leave()
        gate.release(entry)

    ex.spawn(batch_runner, "batch")
    ex.spawn(interactive_runner, "interactive")

    def check() -> List[str]:
        out = list(violations)
        if gate.summary()["running_class"] is not None:
            out.append("gate still owned after both runners returned")
        if gate.queue.depth() != 0:
            out.append(f"gate queue leaked {gate.queue.depth()} entries")
        return out

    return check


# -- ServingDispatcher: coalesce + cancel ------------------------------------

def dispatcher_coalesce_harness(ex: "sched.Explorer") \
        -> Callable[[], List[str]]:
    """Three submitters race through ``_run_grouped`` (leader election,
    follower wait, group close under the exec lock) while a fourth
    thread cancels one of them. Every ticket must complete, no group or
    ticket-table entry may leak, and a finished ticket is either
    cancelled or carries a result."""
    from ..serving import dispatcher as disp_mod

    disp = disp_mod.ServingDispatcher(engine=None, window=0.0)

    class _Run:
        total_images = 1

    run = _Run()
    # One bucket for everyone (forces coalescing pressure); the key only
    # needs the [-3]/[-2]/[-1] slots _run_grouped reads.
    disp._group_key = lambda r: ("harness", 0, 0, "bf16")
    disp._dispatch_eta = lambda r, images: None

    def execute_group(g) -> None:
        for t in g.tickets:
            if not t.cancelled.is_set():
                t.result = f"img-{t.request_id}"

    disp._execute_group = execute_group
    tickets: List["disp_mod.Ticket"] = []

    def submitter(rid: str) -> Callable[[], None]:
        def body() -> None:
            t = disp_mod.Ticket(run, run, "txt2img", False, rid)
            tickets.append(t)
            with disp._lock:
                disp._tickets[rid] = t
            try:
                disp._run_grouped(t)
            finally:
                with disp._lock:
                    disp._tickets.pop(rid, None)
        return body

    def canceller() -> None:
        disp.cancel("r2")

    for rid in ("r1", "r2", "r3"):
        ex.spawn(submitter(rid), f"submit-{rid}")
    ex.spawn(canceller, "cancel-r2")

    def check() -> List[str]:
        out: List[str] = []
        for t in tickets:
            if not t.done.is_set():
                out.append(f"ticket {t.request_id} never completed")
            if t.error is not None:
                out.append(f"ticket {t.request_id} errored: {t.error!r}")
            if t.result is None and not t.cancelled.is_set():
                out.append(f"ticket {t.request_id} lost its result")
        with disp._lock:
            leaked_groups = len(disp._groups)
            leaked_tickets = sorted(disp._tickets)
        if leaked_groups:
            out.append(f"group table leaked {leaked_groups} groups")
        if leaked_tickets:
            out.append(f"ticket table leaked {leaked_tickets}")
        return out

    return check


# -- Notifier: producer enqueue vs drain daemon vs stop ----------------------

def notifier_drain_harness(ex: "sched.Explorer") -> Callable[[], List[str]]:
    """Two producers enqueue transitions onto two *different* severity
    channels (forced no-route transitions land on a channel named by
    their severity) while a stopper shuts the notifier down as soon as
    both have finished. Delivery is stubbed. The per-channel queue
    accounting must balance: ``pending`` mirrors the union of the
    channel queues, every accepted item is sent, failed, or still
    pending on its own channel — never dropped on the floor — and no
    item crosses channels."""
    from ..obs import notify as notify_mod

    n = notify_mod.Notifier()
    n._deliver = lambda item: (True, 1)  # no network from the harness
    accepted = [0]
    produced = threading.Event()  # post-install: cooperative wait
    remaining = [2]
    severities = ("page", "warn")

    def producer(idx: int) -> Callable[[], None]:
        def body() -> None:
            for j in range(2):
                # distinct rules: the dedup window must not eat any
                if n.notify_transition(f"rule-{idx}-{j}", "firing", j,
                                       "harness",
                                       severity=severities[idx],
                                       force=True):
                    with n._lock:
                        accepted[0] += 1
            remaining[0] -= 1
            if remaining[0] == 0:
                produced.set()
        return body

    def stopper() -> None:
        produced.wait()
        n.stop()

    ex.spawn(producer(0), "produce-0")
    ex.spawn(producer(1), "produce-1")
    ex.spawn(stopper, "stopper")

    def check() -> List[str]:
        out: List[str] = []
        with n._lock:
            pending = n._pending
            queued = sum(len(q) for q in n._queues.values())
            channels = set(n._queues) | set(n._counts)
            totals: Dict[str, int] = {}
            for per in n._counts.values():
                for outcome, count in per.items():
                    totals[outcome] = totals.get(outcome, 0) + count
        sent = totals.get("sent", 0)
        failed = totals.get("failed", 0)
        deduped = totals.get("deduped", 0)
        dropped = totals.get("dropped", 0)
        if pending != queued:
            out.append(f"pending {pending} != queued {queued}")
        if sent + failed + pending != accepted[0]:
            out.append(
                f"accounting leak: sent {sent} + failed {failed} + "
                f"pending {pending} != accepted {accepted[0]}")
        if deduped or dropped:
            out.append(f"unexpected rejects: deduped={deduped} "
                       f"dropped={dropped}")
        if not channels <= set(severities):
            out.append(f"items crossed channels: {sorted(channels)}")
        return out

    return check


# -- DeltaSubscriber: delta stream reconnect vs stop -------------------------

def delta_subscriber_harness(ex: "sched.Explorer") \
        -> Callable[[], List[str]]:
    """Two threads each run a start()/poll_once()/stop() cycle against
    one DeltaSubscriber (the push-plane daemon lifecycle under a
    reset() racing a start) while a producer publishes entries into the
    worker-side buffer and the in-process fetch seam injects one
    disconnect. Whatever the interleaving: cursor resume keeps the
    stream lossless (``applied == cursor`` — every cursor up to the
    high-water mark applied exactly once, redeliveries deduped, nothing
    reported lost) and the final stop wins (no daemon thread
    survives)."""
    from ..obs import push as push_mod
    from ..obs import tsdb as tsdb_mod

    buf = push_mod.DeltaBuffer(capacity=64)
    calls = [0]

    class _Backend:
        """In-process fetch seam; call #2 raises (a mid-stream
        disconnect the subscriber must resume across)."""

        @staticmethod
        def push_fetch(cursor: int):
            calls[0] += 1
            if calls[0] == 2:
                raise OSError("simulated disconnect")
            return buf.collect(cursor, hold_s=0.0)

    store = tsdb_mod.SeriesStore()
    sub = push_mod.DeltaSubscriber("w0", _Backend(), store=store)
    produced = threading.Event()  # post-install: cooperative wait

    def producer() -> None:
        for i in range(4):
            buf.publish("sample", {"name": "queue_wait_p95_s",
                                   "t": float(i), "v": float(i)})
        produced.set()

    def cycle() -> None:
        sub.start()
        produced.wait()
        sub.poll_once()
        sub.stop(timeout_s=0.1)

    ex.spawn(producer, "producer")
    ex.spawn(cycle, "cycle-a")
    ex.spawn(cycle, "cycle-b")

    def check() -> List[str]:
        out: List[str] = []
        with sub._lock:
            applied = sub._applied
            lost = sub._lost
            cursor = sub.cursor
        if lost:
            out.append(f"subscriber reported {lost} lost entries")
        if applied != cursor:
            out.append(f"applied {applied} != cursor {cursor} "
                       "(an entry double-applied or skipped)")
        if sub.alive():
            out.append("subscriber daemon survived both stop() calls")
        if not sub._daemon.stopped():
            out.append("halt flag clear after both stop() calls")
        return out

    return check


# -- StoppableDaemon: concurrent stop / restart ------------------------------

def daemon_restart_harness(ex: "sched.Explorer") -> Callable[[], List[str]]:
    """Two threads each run a start()/stop() cycle against one
    StoppableDaemon (the TSDB sampler lifecycle under a reset() racing a
    start_daemon()). Whatever the interleaving, the final stop must win:
    no loop thread survives and the halt flag is set."""
    from ..runtime.daemon import StoppableDaemon

    ticked = [0]

    def tick() -> None:
        ticked[0] += 1

    d = StoppableDaemon("harness-sampler", tick, 0.01)

    def cycle() -> None:
        d.start()
        d.stop(timeout_s=0.1)

    ex.spawn(cycle, "cycle-a")
    ex.spawn(cycle, "cycle-b")

    def check() -> List[str]:
        out: List[str] = []
        if not d.stopped():
            out.append("halt flag clear after both stop() calls")
        if d.alive():
            out.append("daemon thread survived both stop() calls")
        return out

    return check


# -- StageGraph/GraphRunner: submit vs preempt-drain vs cancel ---------------

def stage_graph_harness(ex: "sched.Explorer") -> Callable[[], List[str]]:
    """A producer submits three encode→denoise→decode StageGraphs through
    one GraphRunner while a preemptor drains mid-stream (the engine's
    chunk-boundary yield runs drain() from a racing thread) and a
    canceller stops the producer between submissions (the interrupt
    seam). Whatever the interleaving: each submitted group's per-stage
    completion callbacks fire in dependency order, every submitted group
    flushes exactly once in submission (FIFO = gallery) order, nothing
    stays in flight, and every denoise window is closed."""
    from ..parallel import stage_graph

    # fresh objects: the module-level CLOCK's lock was born raw at import
    clock = stage_graph.OverlapClock()
    runner = stage_graph.GraphRunner(depth=1, clock=clock)
    stages: List[tuple] = []   # (group, stage) completion log
    flushes: List[int] = []    # group ids in flush order
    submitted: List[int] = []
    cancel = threading.Event()  # post-install: cooperative wait

    def make_graph(gid: int):
        g = stage_graph.StageGraph(
            label=f"g{gid}", group=gid, clock=clock,
            on_stage=lambda name, secs, gid=gid: stages.append((gid, name)),
            obs=False)
        g.add("encode", lambda gid=gid: f"enc{gid}", kind="stage")
        g.add("denoise", lambda e, gid=gid: f"lat{gid}",
              deps=("encode",), kind="denoise")
        g.add("decode", lambda e, lat, gid=gid: f"img{gid}",
              deps=("encode", "denoise"), kind="stage")
        return g

    def producer() -> None:
        for gid in range(3):
            if cancel.is_set():
                break
            submitted.append(gid)
            runner.submit(make_graph(gid),
                          lambda res, gid=gid: flushes.append(gid))
        runner.drain()

    def preemptor() -> None:
        runner.drain()

    def canceller() -> None:
        cancel.set()

    ex.spawn(producer, "producer")
    ex.spawn(preemptor, "preempt-drain")
    ex.spawn(canceller, "cancel")

    def check() -> List[str]:
        out: List[str] = []
        for gid in submitted:
            order = [s for g, s in stages if g == gid]
            if order != ["encode", "denoise", "decode"]:
                out.append(f"group {gid} stage callbacks out of order: "
                           f"{order}")
        if flushes != submitted:
            out.append(f"flush order {flushes} != submit order {submitted}")
        if runner.in_flight():
            out.append(f"{runner.in_flight()} graphs left in flight")
        if runner.flushed != len(submitted):
            out.append(f"flushed {runner.flushed} != "
                       f"submitted {len(submitted)}")
        with clock._lock:
            left_open = len(clock._open)
        if left_open:
            out.append(f"{left_open} denoise windows left open")
        return out

    return check


# -- WarmPool: checkout vs chaos-kill vs heal vs retire ----------------------

def warm_pool_harness(ex: "sched.Explorer") -> Callable[[], List[str]]:
    """Two borrowers check residents in and out of one WarmPool while a
    chaos thread kills resident-1 then heals back to target size and a
    fourth thread retires one resident (the autoscale down path).
    Whatever the interleaving: every checkout gets an engine and is
    balanced by a release, a retired resident never lingers once
    drained, inflight counts return to zero, and the pool never drains
    below one ready resident (retire_one refuses the last; kill is
    followed by a heal)."""
    from ..fleet import pool as fleet_pool

    spawned = [0]

    def factory(name: str) -> object:
        spawned[0] += 1
        return object()  # the protocol under test is bookkeeping-only

    pool = fleet_pool.WarmPool(factory, size=2)
    violations: List[str] = []

    def borrower(tag: str) -> Callable[[], None]:
        def body() -> None:
            for _ in range(2):
                res = pool.acquire()
                if res.engine is None:
                    violations.append(f"{tag} checked out a bare resident")
                if res.inflight < 1:
                    violations.append(
                        f"{tag} acquired {res.name} with inflight "
                        f"{res.inflight}")
                pool.release(res)
        return body

    def chaos() -> None:
        pool.kill("resident-1")
        pool.heal()

    def retirer() -> None:
        pool.retire_one()

    ex.spawn(borrower("borrower-a"), "borrower-a")
    ex.spawn(borrower("borrower-b"), "borrower-b")
    ex.spawn(chaos, "chaos-kill-heal")
    ex.spawn(retirer, "retire")

    def check() -> List[str]:
        out = list(violations)
        with pool._lock:
            residents = list(pool._residents.values())
        ready = 0
        for r in residents:
            if r.inflight != 0:
                out.append(f"{r.name} left inflight={r.inflight}")
            if r.state == "retired":
                out.append(f"retired {r.name} leaked (drained but still "
                           f"in the table)")
            if r.state == "ready":
                ready += 1
        if ready < 1:
            out.append("pool drained below one ready resident")
        if spawned[0] != pool.summary()["spawns_total"]:
            out.append(f"factory ran {spawned[0]} times but pool counted "
                       f"{pool.summary()['spawns_total']} spawns")
        return out

    return check


HARNESSES: Dict[str, Callable[["sched.Explorer"],
                              Optional[Callable[[], List[str]]]]] = {
    "fleet_gate": fleet_gate_harness,
    "dispatcher_coalesce": dispatcher_coalesce_harness,
    "notifier_drain": notifier_drain_harness,
    "daemon_restart": daemon_restart_harness,
    "delta_subscriber": delta_subscriber_harness,
    "stage_graph": stage_graph_harness,
    "warm_pool": warm_pool_harness,
}


def run_harness(name: str, seeds: range) -> List["sched.ExploreResult"]:
    """Explore one named harness across ``seeds`` (locksan must already
    be installed — tests do this via the session fixture)."""
    return sched.explore(HARNESSES[name], seeds)

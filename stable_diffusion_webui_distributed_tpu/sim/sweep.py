"""Capacity sweeper: one replayed mix, N configs, one ranked answer.

The Gemma-on-TPU comparison (PAPERS.md) makes the case that capacity and
topology choices only become defensible when swept against a *fixed*
workload. :func:`run_sweep` drives the caller's runner — which applies
one config (bucket ladder, cadence policy, coalesce window, worker
count), replays the same plan, and returns a :mod:`sim.score` scorecard
— once per candidate, then :func:`rank` orders the results:

1. highest worst-class SLO attainment (requests meeting their deadline
   dominate everything else),
2. lowest worst-class p95 latency,
3. fewest compiles (executable-budget pressure as the tiebreak).

The ranked table plus the winner lands in ``BENCH_scenarios.json``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List


def _rank_key(score: Dict[str, Any]):
    attain = [row["slo_attainment"] for row in score["classes"].values()
              if row.get("slo_attainment") is not None]
    p95s = [row["p95_s"] for row in score["classes"].values()
            if row.get("p95_s") is not None]
    worst_attain = min(attain) if attain else 1.0
    worst_p95 = max(p95s) if p95s else float("inf")
    return (-worst_attain, worst_p95, score.get("compiles", 0))


def rank(scored: List[Dict[str, Any]]) -> Dict[str, Any]:
    """``scored``: [{"name": ..., "config": ..., "score": ...}] → ranked
    table + recommendation. Pure; unit-testable."""
    ordered = sorted(scored, key=lambda row: _rank_key(row["score"]))
    table = []
    for pos, row in enumerate(ordered):
        key = _rank_key(row["score"])
        table.append({
            "rank": pos + 1,
            "name": row["name"],
            "config": row.get("config", {}),
            "worst_slo_attainment": -key[0],
            "worst_p95_s": None if key[1] == float("inf") else key[1],
            "compiles": key[2],
        })
    return {
        "ranked": table,
        "recommendation": table[0]["name"] if table else None,
    }


def run_sweep(configs: Dict[str, Dict[str, Any]],
              runner: Callable[[str, Dict[str, Any]], Dict[str, Any]],
              ) -> Dict[str, Any]:
    """Run ``runner(name, config) -> scorecard`` per candidate and rank.
    Configs are env-knob dicts (the bench applies them via _EnvPatch);
    candidates run sequentially so they never contend for the device."""
    scored = []
    for name in sorted(configs):
        score = runner(name, configs[name])
        scored.append({"name": name, "config": configs[name],
                       "score": score})
    out = rank(scored)
    out["runs"] = {row["name"]: row["score"] for row in scored}
    return out

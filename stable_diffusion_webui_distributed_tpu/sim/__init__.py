"""sim/ — scenario engine: journal-driven traffic simulation, chaos
injection, and SLO-scored capacity regression.

The observability plane made every request's lifecycle a replayable
artifact (obs/journal.py + tools/replay.py) and MFU/SLO a live ledger
(obs/perf.py); this package closes the observe→replay→perturb→score
loop on top of them:

- **workload** (:mod:`sim.workload`) — load a recorded journal (live
  snapshot, snapshot file, or ``SDTPU_JOURNAL_SINK`` JSONL spill) or a
  synthetic spec, and re-emit its request mix through the real
  dispatcher/fleet path open-loop, with deterministic seeded transforms:
  rate scaling, diurnal curves, flash bursts, shape/precision/tenant
  diversity. A 200-request recording can drive a 5,000-request run.
- **chaos** (:mod:`sim.chaos`) — a seeded, scenario-scripted fault plan
  (worker kill, stall, slow response, transient HTTP error at request N)
  delivered through the sanctioned ``CHAOS_HOOK`` seams in
  ``scheduler/worker.py`` / ``scheduler/world.py`` /
  ``serving/dispatcher.py``. Every delivered fault is journaled
  (``fault_injected`` / ``fault_cleared``) and counted in
  ``sdtpu_sim_faults_total{kind}``, so recovery is auditable.
- **score** (:mod:`sim.score`) — score a run from the open-loop records
  + journal + perf ledger: per-class p50/p95 and SLO attainment, requeue
  recovery rate, double-merge audit, fault census, SLO burn, compile
  census, padding ratios.
- **sweep** (:mod:`sim.sweep`) — run the same replayed mix under
  competing configs (bucket ladders, cadence policies, worker counts)
  and emit a ranked recommendation.

Everything rides on ``SDTPU_SIM`` (default OFF): chaos hooks refuse to
arm without it and the default path is byte-identical (hash-pinned).
``bench.py --scenarios`` runs the steady / flash-burst / chaos-kill
matrix and commits ``BENCH_scenarios.json`` + per-scenario ledger rows
gated by ``tools/bench_compare.py``. Live state at ``/internal/sim``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from stable_diffusion_webui_distributed_tpu.runtime.config import env_flag


def enabled() -> bool:
    """Scenario-engine gate — re-read per call so tests can flip it."""
    return env_flag("SDTPU_SIM", False)


_LOCK = threading.Lock()
#: name + score of the most recently scored scenario run (sim/score.py
#: records it); surfaced via /internal/sim.
_LAST_RUN: Optional[Dict[str, Any]] = None  # guarded-by: _LOCK


def record_last_run(name: str, score: Dict[str, Any]) -> None:
    global _LAST_RUN
    with _LOCK:
        _LAST_RUN = {"name": str(name), "score": dict(score)}


def last_run() -> Optional[Dict[str, Any]]:
    with _LOCK:
        return None if _LAST_RUN is None else dict(_LAST_RUN)


def clear_last_run() -> None:
    global _LAST_RUN
    with _LOCK:
        _LAST_RUN = None


def summary() -> Dict[str, Any]:
    """The ``/internal/sim`` document (schema pinned by tests)."""
    from stable_diffusion_webui_distributed_tpu.obs.journal import JOURNAL
    from stable_diffusion_webui_distributed_tpu.sim import chaos

    return {
        "enabled": enabled(),
        "sink": JOURNAL.sink_status(),
        "chaos": chaos.status(),
        "last_run": last_run(),
    }

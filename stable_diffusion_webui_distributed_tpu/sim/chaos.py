"""Chaos injector: seeded, scenario-scripted fault plans.

The watchdog bench has always staged its stall ad hoc (a StubBehavior
with a long ``seconds_per_image``); this module generalizes that into a
declarative, auditable fault plan delivered through the sanctioned
``CHAOS_HOOK`` seams:

- ``scheduler/world.py`` / ``serving/dispatcher.py`` consult the hook
  once per request entering the system — that is where a plan's request
  counter advances, making "at request N" deterministic;
- ``scheduler/worker.py`` consults it inside :meth:`WorkerNode.request`'s
  try-block just before ``backend.generate`` — a raised fault lands in
  the *existing* failure path (health demerit, UNAVAILABLE demotion,
  World requeue to survivors), and a sleep is seen by the hang watchdog
  exactly like a genuinely wedged remote.

Fault kinds: ``kill`` (hard backend failure), ``stall`` (sleep long
enough for the watchdog to latch), ``slow`` (degraded but completing),
``http_error`` (transient failure that clears after ``count`` hits).
Every delivered fault is journaled (``fault_injected`` /
``fault_cleared``) and counted in ``sdtpu_sim_faults_total{kind}``.

:func:`arm` refuses to install hooks unless ``SDTPU_SIM=1`` — the
default path never sees a non-None hook.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.obs import (
    journal as obs_journal,
    prometheus as obs_prom,
)

KINDS = ("kill", "stall", "slow", "http_error")


@dataclasses.dataclass
class Fault:
    """One scripted fault.

    ``worker`` targets a label exactly; ``""``/``"any"`` matches the
    first worker consulted after activation. ``at_request`` arms the
    fault once the Nth request (1-based) has entered the system;
    ``count`` is how many generate calls it hits before clearing.
    ``duration_s`` is the sleep for stall/slow kinds."""

    kind: str
    worker: str = ""
    at_request: int = 1
    count: int = 1
    duration_s: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")


class ChaosPlan:
    """A fault script + its delivery state; ``consult`` is the hook."""

    def __init__(self, faults: List[Fault], seed: int = 0) -> None:
        self.faults = list(faults)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._step = 0  # guarded-by: _lock — requests entered so far
        # per-fault delivery state                       guarded-by: _lock
        self._state = [{"remaining": f.count, "injected": 0,
                        "cleared": False} for f in self.faults]

    def consult(self, site: str, **ctx: Any) -> None:
        """The CHAOS_HOOK entry point. Never holds ``_lock`` while
        sleeping or raising — actions are decided under the lock and
        delivered outside it."""
        if site in ("world.execute", "dispatcher.submit"):
            with self._lock:
                self._step += 1
            return
        if site != "worker.generate":
            return
        worker = str(ctx.get("worker", ""))
        deliver = []
        with self._lock:
            step = self._step
            for i, f in enumerate(self.faults):
                st = self._state[i]
                if st["remaining"] <= 0 or step < f.at_request:
                    continue
                if f.worker not in ("", "any") and f.worker != worker:
                    continue
                st["remaining"] -= 1
                st["injected"] += 1
                cleared = st["remaining"] == 0
                if cleared:
                    st["cleared"] = True
                deliver.append((i, f, cleared))
        for i, f, cleared in deliver:
            self._journal("fault_injected", i, f, worker, step)
            obs_prom.sim_fault_count(f.kind)
            if cleared:
                self._journal("fault_cleared", i, f, worker, step)
            if f.kind in ("stall", "slow"):
                time.sleep(max(0.0, f.duration_s))
            elif f.kind == "kill":
                raise ConnectionError(
                    f"chaos: injected kill on worker '{worker}'")
            elif f.kind == "http_error":
                raise ConnectionError(
                    f"chaos: injected transient http error on "
                    f"worker '{worker}'")

    def _journal(self, event: str, index: int, fault: Fault,
                 worker: str, step: int) -> None:
        if obs_journal.enabled():
            obs_journal.emit(event, f"chaos-{self.seed}-{index}",
                             kind=fault.kind, worker=worker, step=step,
                             at_request=fault.at_request)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "step": self._step,
                "faults": [
                    {"kind": f.kind, "worker": f.worker,
                     "at_request": f.at_request,
                     "injected": st["injected"],
                     "remaining": st["remaining"],
                     "cleared": st["cleared"]}
                    for f, st in zip(self.faults, self._state)
                ],
            }


_ARM_LOCK = threading.Lock()
_ARMED: Optional[ChaosPlan] = None  # guarded-by: _ARM_LOCK


def arm(plan: ChaosPlan) -> ChaosPlan:
    """Install ``plan.consult`` into every CHAOS_HOOK seam. Refuses
    unless the scenario engine is enabled (SDTPU_SIM=1) — the default
    path keeps its hooks None."""
    from stable_diffusion_webui_distributed_tpu import sim
    from stable_diffusion_webui_distributed_tpu.scheduler import (
        worker as worker_mod,
        world as world_mod,
    )
    from stable_diffusion_webui_distributed_tpu.serving import (
        dispatcher as dispatcher_mod,
    )

    if not sim.enabled():
        raise RuntimeError("SDTPU_SIM is off; refusing to arm chaos hooks")
    global _ARMED
    with _ARM_LOCK:
        worker_mod.CHAOS_HOOK = plan.consult
        world_mod.CHAOS_HOOK = plan.consult
        dispatcher_mod.CHAOS_HOOK = plan.consult
        _ARMED = plan
    return plan


def disarm() -> None:
    """Reset every CHAOS_HOOK seam to None (idempotent)."""
    from stable_diffusion_webui_distributed_tpu.scheduler import (
        worker as worker_mod,
        world as world_mod,
    )
    from stable_diffusion_webui_distributed_tpu.serving import (
        dispatcher as dispatcher_mod,
    )

    global _ARMED
    with _ARM_LOCK:
        worker_mod.CHAOS_HOOK = None
        world_mod.CHAOS_HOOK = None
        dispatcher_mod.CHAOS_HOOK = None
        _ARMED = None


def status() -> Dict[str, Any]:
    """Armed-plan state for /internal/sim (``armed: false`` when idle)."""
    with _ARM_LOCK:
        plan = _ARMED
    if plan is None:
        return {"armed": False, "plan": None}
    return {"armed": True, "plan": plan.status()}

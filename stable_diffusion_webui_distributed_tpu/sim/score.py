"""SLO scorer: one scenario run → one structured scorecard.

Inputs are the three artifacts a run leaves behind:

- **records** — per-request open-loop records from
  :func:`sim.workload.emit_open_loop` (class, tenant, status, latency,
  expected vs delivered image counts);
- **events** — the journal slice for the run (fault census, requeue and
  job-failure counts from the closed event vocabulary);
- **ledger** — ``obs.perf.LEDGER.summary()`` (per-tenant/class SLO
  attainment + burn, compile census, padding ratios) when the run was
  recorded under ``SDTPU_PERF=1``.

The scorecard is pure arithmetic over those inputs (unit-testable
against hand-built journals); :func:`ledger_metrics` flattens the gated
subset into a ``BENCH_LEDGER.jsonl`` metrics dict for
``tools/bench_compare.py``, and the worst observed SLO burn is pushed to
the ``sdtpu_sim_slo_burn`` gauge.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.obs import (
    prometheus as obs_prom,
)


def _percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (same convention as bench.py)."""
    if not samples:
        return None
    xs = sorted(samples)
    idx = max(0, min(len(xs) - 1, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def score_run(records: List[Dict[str, Any]],
              events: Optional[List[Dict[str, Any]]] = None,
              ledger: Optional[Dict[str, Any]] = None,
              slo_s_by_class: Optional[Dict[str, float]] = None,
              ) -> Dict[str, Any]:
    """Build the scorecard; every key is always present (None/empty when
    its input artifact is missing) so downstream schemas stay stable."""
    events = events or []
    slo_s_by_class = slo_s_by_class or {}

    classes: Dict[str, Dict[str, Any]] = {}
    expected_images = 0
    delivered_images = 0
    double_merged = 0
    for rec in records:
        cls = str(rec.get("class") or "interactive")
        row = classes.setdefault(cls, {
            "requests": 0, "completed": 0, "failed": 0, "throttled": 0,
            "latencies": [],
        })
        row["requests"] += 1
        status = rec.get("status", "")
        if status == "completed":
            row["completed"] += 1
            row["latencies"].append(float(rec.get("latency_s", 0.0)))
        elif status == "failed":
            row["failed"] += 1
        else:
            row["throttled"] += 1
        exp = int(rec.get("expected", 0))
        got = int(rec.get("images", 0))
        expected_images += exp
        delivered_images += min(got, exp)
        double_merged += max(0, got - exp)

    class_rows: Dict[str, Dict[str, Any]] = {}
    for cls, row in sorted(classes.items()):
        lats = row.pop("latencies")
        out = dict(row)
        out["p50_s"] = _percentile(lats, 0.50)
        out["p95_s"] = _percentile(lats, 0.95)
        slo = slo_s_by_class.get(cls)
        if slo is not None and lats:
            out["slo_attainment"] = (
                sum(1 for x in lats if x <= slo) / len(lats))
        else:
            out["slo_attainment"] = None
        class_rows[cls] = out

    faults: Dict[str, int] = {}
    requeues = 0
    job_failures = 0
    for ev in events:
        name = ev.get("event", "")
        if name == "fault_injected":
            kind = str((ev.get("attrs") or {}).get("kind", ""))
            faults[kind] = faults.get(kind, 0) + 1
        elif name == "requeued":
            requeues += 1
        elif name == "job_failed":
            job_failures += 1

    recovery = (delivered_images / expected_images
                if expected_images else 1.0)

    slo_rows: List[Dict[str, Any]] = []
    worst_burn: Optional[float] = None
    compiles = 0
    padding: Optional[float] = None
    if ledger:
        for row in ledger.get("slo", []):
            slo_rows.append({k: row.get(k) for k in
                             ("tenant", "class", "slo_s", "total", "met",
                              "attainment", "burn_rate")})
            burn = row.get("burn_rate")
            if burn is not None and (worst_burn is None
                                     or burn > worst_burn):
                worst_burn = float(burn)
        compiles = sum(int(c.get("count", 0))
                       for c in ledger.get("compiles", {}).values())
        groups = ledger.get("groups", [])
        disp = sum(int(g.get("dispatches", 0)) for g in groups)
        if disp:
            padding = sum(float(g.get("padding_ratio", 1.0))
                          * int(g.get("dispatches", 0))
                          for g in groups) / disp
    if worst_burn is not None:
        obs_prom.set_sim_slo_burn(worst_burn)

    return {
        "requests": len(records),
        "classes": class_rows,
        "faults": faults,
        "requeues": requeues,
        "job_failures": job_failures,
        "expected_images": expected_images,
        "delivered_images": delivered_images,
        "double_merged_images": double_merged,
        "requeue_recovery_rate": round(recovery, 6),
        "slo": slo_rows,
        "worst_slo_burn": worst_burn,
        "compiles": compiles,
        "avg_padding_ratio": padding,
    }


def alert_validation(phases: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Score detector behavior against labeled ground truth (the
    ``bench.py --alerts`` gate). Each phase is
    ``{"name", "expected": [rule, ...], "fired": [rule, ...]}``: a phase
    with no expected rules is steady traffic — every firing there is a
    false positive — and a phase with expected rules is an injected
    fault window, detected when any expected rule fired. Pure
    arithmetic, unit-testable against hand-built phase lists."""
    false_positives = 0
    fp_rules: List[str] = []
    fault_count = 0
    detected = 0
    rows: List[Dict[str, Any]] = []
    for ph in phases:
        fired = sorted(set(ph.get("fired") or []))
        expected = sorted(set(ph.get("expected") or []))
        row: Dict[str, Any] = {"name": str(ph.get("name", "")),
                               "expected": expected, "fired": fired}
        if not expected:
            row["false_positives"] = len(fired)
            false_positives += len(fired)
            fp_rules.extend(fired)
        else:
            fault_count += 1
            hit = bool(set(fired) & set(expected))
            row["detected"] = hit
            detected += 1 if hit else 0
        rows.append(row)
    return {
        "phases": rows,
        "alert_false_positives": false_positives,
        "false_positive_rules": sorted(set(fp_rules)),
        "faults": fault_count,
        "detected": detected,
        "alert_recall": (detected / fault_count) if fault_count else None,
    }


def ledger_metrics(score: Dict[str, Any]) -> Dict[str, Any]:
    """The bench_compare-gated flat view of a scorecard."""
    p95s = [row["p95_s"] for row in score["classes"].values()
            if row.get("p95_s") is not None]
    attain = [row["slo_attainment"] for row in score["classes"].values()
              if row.get("slo_attainment") is not None]
    metrics: Dict[str, Any] = {
        "requests": score["requests"],
        "requeue_recovery_rate": score["requeue_recovery_rate"],
        "double_merged_images": score["double_merged_images"],
        "faults_injected": sum(score["faults"].values()),
        "requeues": score["requeues"],
    }
    if p95s:
        metrics["scenario_p95_s"] = max(p95s)
    if attain:
        metrics["slo_attainment"] = min(attain)
    if score.get("worst_slo_burn") is not None:
        metrics["slo_burn"] = score["worst_slo_burn"]
    if score.get("avg_padding_ratio") is not None:
        metrics["avg_padding_ratio"] = score["avg_padding_ratio"]
    if score.get("compiles"):
        metrics["compiles"] = score["compiles"]
    return metrics

"""Workload generator: replay a recorded journal mix, scaled and shaped.

A journal recording (live snapshot dict, ``/internal/journal`` JSON file,
or ``SDTPU_JOURNAL_SINK`` JSONL spill) carries every request's
post-``fix_seed`` payload dump on its ``received``/``planned`` event —
enough to re-emit the *mix* at any rate. :func:`generate_plan` resamples
that mix into ``spec.count`` requests with deterministic seeded
transforms (same seed → byte-identical plan):

- **rate_scale** — compress/stretch the recorded arrival process;
- **diurnal** — sinusoidal arrival-rate modulation (amplitude, period);
- **flash burst** — ``burst_size`` simultaneous arrivals at the
  ``burst_at`` fraction of the timeline;
- **diversity knobs** — optional shape / precision / tenant / class
  pools sampled per request, stressing bucketing and fleet scheduling.

:func:`emit_open_loop` then fires the plan open-loop (arrival-clocked
threads, like real traffic: late responses do not slow down future
arrivals) against any ``submit(payload)`` callable — normally
``ServingDispatcher.submit`` — and returns one record per request for
:mod:`sim.score`.
"""

from __future__ import annotations

import dataclasses
import json
import math
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)

Source = Union[str, Dict[str, Any], List[Dict[str, Any]]]


def load_events(source: Source) -> List[Dict[str, Any]]:
    """Journal events from a snapshot dict, snapshot JSON file, or JSONL
    sink file, sorted by seq (sink spills can land out of order)."""
    if isinstance(source, dict):
        events = list(source.get("events", []))
    elif isinstance(source, list):
        events = list(source)
    else:
        with open(source, "r", encoding="utf-8") as fh:
            text = fh.read()
        try:
            doc = json.loads(text)
        except ValueError:
            doc = None
        if isinstance(doc, dict):
            events = list(doc.get("events", []))
        elif isinstance(doc, list):
            events = list(doc)
        else:
            # JSONL sink: one event object per line
            events = [json.loads(line) for line in text.splitlines()
                      if line.strip()]
    return sorted(events, key=lambda e: e.get("seq", 0))


def base_mix(events: List[Dict[str, Any]]) -> List[Tuple[Dict[str, Any],
                                                         float]]:
    """(payload dump, relative arrival seconds) per recorded request, in
    arrival order. Requests whose payload-bearing event fell out of the
    ring (and off the sink) are skipped."""
    first_payload: Dict[str, Dict[str, Any]] = {}
    first_t: Dict[str, float] = {}
    order: List[str] = []
    for ev in events:
        rid = ev.get("request_id", "")
        if rid not in first_t:
            first_t[rid] = float(ev.get("t_mono", 0.0))
            order.append(rid)
        if rid not in first_payload \
                and ev.get("event") in ("received", "planned"):
            payload = (ev.get("attrs") or {}).get("payload")
            if isinstance(payload, dict):
                first_payload[rid] = payload
    mix = [(first_payload[rid], first_t[rid])
           for rid in order if rid in first_payload]
    if not mix:
        return []
    t0 = min(t for _, t in mix)
    return [(p, t - t0) for p, t in mix]


def synthetic_mix(n: int = 8, size: int = 64, steps: int = 4,
                  seed: int = 0) -> List[Tuple[Dict[str, Any], float]]:
    """A recorded-mix stand-in when no journal is available: ``n``
    prompts arriving one second apart."""
    rng = random.Random(seed)
    mix = []
    for i in range(n):
        mix.append(({
            "prompt": f"synthetic scene {i}, variant {rng.randrange(100)}",
            "seed": 1000 + i,
            "steps": steps,
            "width": size,
            "height": size,
            "batch_size": 1,
        }, float(i)))
    return mix


@dataclasses.dataclass
class WorkloadSpec:
    """Deterministic transform knobs; same (mix, spec) → same plan."""

    seed: int = 0
    count: int = 0              # 0 = one pass over the mix, unscaled
    rate_scale: float = 1.0     # >1 = compress arrivals (more rps)
    diurnal_amplitude: float = 0.0   # 0..1 sinusoidal rate modulation
    diurnal_period_s: float = 60.0
    burst_size: int = 0         # simultaneous arrivals injected...
    burst_at: float = 0.5       # ...at this fraction of the timeline
    shapes: Optional[List[Tuple[int, int]]] = None   # (w, h) pool
    precisions: Optional[List[str]] = None
    tenants: Optional[List[str]] = None
    classes: Optional[List[str]] = None


@dataclasses.dataclass
class SimRequest:
    """One planned request: arrival offset + ready-to-submit payload."""

    index: int
    request_id: str
    arrival_s: float
    payload: GenerationPayload

    def dump(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "request_id": self.request_id,
            "arrival_s": round(self.arrival_s, 6),
            "payload": self.payload.model_dump(),
        }


def generate_plan(mix: List[Tuple[Dict[str, Any], float]],
                  spec: WorkloadSpec) -> List[SimRequest]:
    """Resample ``mix`` into a deterministic request plan.

    The recorded mean inter-arrival sets the base rate; each generated
    gap is an exponential draw at that rate × ``rate_scale`` × the
    diurnal factor at the current point of the timeline. Payloads are
    sampled from the mix with replacement (first pass keeps recorded
    order so ``count <= len(mix)`` replays a prefix verbatim)."""
    if not mix:
        raise ValueError("empty workload mix")
    rng = random.Random(spec.seed)
    count = spec.count or len(mix)
    arrivals = sorted(t for _, t in mix)
    if len(arrivals) > 1 and arrivals[-1] > arrivals[0]:
        mean_gap = (arrivals[-1] - arrivals[0]) / (len(arrivals) - 1)
    else:
        mean_gap = 1.0
    mean_gap /= max(1e-9, spec.rate_scale)

    plan: List[SimRequest] = []
    t = 0.0
    for i in range(count):
        if i < len(mix):
            base = mix[i][0]
        else:
            base = mix[rng.randrange(len(mix))][0]
        dump = dict(base)
        if spec.shapes:
            w, h = spec.shapes[rng.randrange(len(spec.shapes))]
            dump["width"], dump["height"] = int(w), int(h)
        if spec.precisions:
            dump["precision"] = spec.precisions[
                rng.randrange(len(spec.precisions))]
        if spec.tenants:
            dump["tenant"] = spec.tenants[rng.randrange(len(spec.tenants))]
        if spec.classes:
            dump["priority_class"] = spec.classes[
                rng.randrange(len(spec.classes))]
        rid = f"sim-{spec.seed}-{i:05d}"
        dump["request_id"] = rid
        plan.append(SimRequest(
            index=i, request_id=rid, arrival_s=t,
            payload=GenerationPayload(**dump)))
        # diurnal factor for the NEXT gap, evaluated at the current point
        factor = 1.0
        if spec.diurnal_amplitude > 0.0:
            factor += spec.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / max(1e-9, spec.diurnal_period_s))
        rate = max(1e-9, factor) / max(1e-9, mean_gap)
        t += rng.expovariate(rate)

    if spec.burst_size > 0 and plan:
        span = plan[-1].arrival_s
        burst_t = span * min(1.0, max(0.0, spec.burst_at))
        base_i = rng.randrange(len(mix))
        n0 = len(plan)
        for j in range(spec.burst_size):
            dump = dict(mix[(base_i + j) % len(mix)][0])
            rid = f"sim-{spec.seed}-{n0 + j:05d}"
            dump["request_id"] = rid
            plan.append(SimRequest(
                index=n0 + j, request_id=rid, arrival_s=burst_t,
                payload=GenerationPayload(**dump)))
        plan.sort(key=lambda r: (r.arrival_s, r.index))
    return plan


def plan_fingerprint(plan: List[SimRequest]) -> str:
    """Stable hash of a plan — the determinism assertion in tests."""
    from stable_diffusion_webui_distributed_tpu.obs.journal import (
        fingerprint,
    )

    return fingerprint([r.dump() for r in plan])


def emit_open_loop(plan: List[SimRequest],
                   submit: Callable[[GenerationPayload], Any],
                   time_scale: float = 1.0,
                   job: str = "txt2img") -> List[Dict[str, Any]]:
    """Fire the plan open-loop and return one score record per request.

    Each request fires on its own thread at ``arrival_s * time_scale``
    regardless of how earlier requests are faring (open-loop: overload
    shows up as latency/throttling, not as a slower generator)."""
    from stable_diffusion_webui_distributed_tpu.fleet.admission import (
        FleetRejected,
    )

    records: List[Optional[Dict[str, Any]]] = [None] * len(plan)
    t0 = time.monotonic()

    def fire(i: int, req: SimRequest) -> None:
        delay = req.arrival_s * time_scale - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        rec: Dict[str, Any] = {
            "request_id": req.request_id,
            "class": req.payload.priority_class or "interactive",
            "tenant": req.payload.tenant,
            "expected": req.payload.total_images,
            "images": 0,
        }
        started = time.monotonic()
        try:
            result = submit(req.payload)
            rec["status"] = "completed"
            rec["images"] = len(getattr(result, "images", []) or [])
        except FleetRejected as e:
            rec["status"] = getattr(e, "reason", "rejected") or "rejected"
        except Exception as e:  # noqa: BLE001 — scored, not raised
            rec["status"] = "failed"
            rec["error"] = str(e)
        rec["latency_s"] = time.monotonic() - started
        records[i] = rec

    threads = [threading.Thread(target=fire, args=(i, req), daemon=True)
               for i, req in enumerate(plan)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return [r for r in records if r is not None]

"""Pallas ragged attention: per-row true lengths over bucket-padded tokens.

Ragged dispatch ("Ragged Paged Attention", PAPERS.md arxiv 2604.15464) lets
heterogeneous requests share ONE bucket-shaped executable: every batch row
carries its true token count as traced data, and the attention kernel masks
the padded tail in-block instead of the bucketer rounding every request up
the shape ladder. Spatial rows are padded at the BOTTOM (row-major flatten),
so the valid tokens of each batch row form a prefix — the mask is a single
``position < true_len`` compare per tile, and k-tiles that start past the
longest-needed position are skipped outright (no tail FLOPs on TPU).

Two entry points:

- ``ragged_attention_reference`` — dense XLA masked attention. This is BOTH
  the CPU/tier-1 execution path (bit-exact by construction: the fallback IS
  the reference) and the oracle the pallas kernel is tested against.
- ``ragged_attention`` — the pallas kernel, same online-softmax blockwise
  form as ``ops/flash_attention.py`` (grid ``(B*H, T/block_q, S/block_k)``,
  VMEM (m, l, acc) scratch), extended with a scalar-prefetched per-(b·h)
  ``true_len`` vector, ``pl.when``-skipped fully-masked k-tiles, and a
  finalize that zeroes query rows at or past ``true_len``.

Masked scores use a large-negative constant (not ``-inf``): ``exp(-1e30 - m)``
underflows to exactly ``0.0`` in f32, while ``-inf`` arithmetic can surface
NaN through ``inf - inf`` when a whole tile is masked.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: additive mask for padded key positions — exp() underflows to exact 0.0
#: in f32 without the NaN hazards of -inf
MASK_VALUE = -1e30


def ragged_attention_reference(
    q: jax.Array,              # (B, T, H, D)
    k: jax.Array,              # (B, S, H, D)
    v: jax.Array,              # (B, S, H, D)
    true_len: jax.Array,       # (B,) int32 — valid KEY prefix per row
    scale: float | None = None,
    q_true_len: jax.Array | None = None,   # (B,) valid QUERY prefix; None=all
) -> jax.Array:
    """Dense XLA masked attention — the oracle and the CPU execution path.

    Keys/values at positions ``>= true_len[b]`` are excluded from the
    softmax; query rows at positions ``>= q_true_len[b]`` (when given) are
    zeroed — their content is bucket padding and downstream consumers mask
    them anyway, but pinning them to 0 keeps padded tails from drifting
    through residual streams. Rows must have ``true_len >= 1``.
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum(
        "bthd,bshd->bhts",
        q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    kmask = jnp.arange(s, dtype=jnp.int32)[None, :] < true_len[:, None]
    scores = jnp.where(kmask[:, None, None, :], scores, MASK_VALUE)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    if q_true_len is not None:
        qmask = (jnp.arange(t, dtype=jnp.int32)[None, :]
                 < q_true_len[:, None])
        out = jnp.where(qmask[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)


def _ragged_kernel(tl_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, *, scale: float, block_q: int, block_k: int):
    """One (batch*head, q-tile, k-tile) step of the ragged online softmax."""
    bh = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    tl = tl_ref[bh]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Fold only k-tiles that overlap the valid prefix: a tile starting at or
    # past true_len is entirely padding and contributes nothing — skipping it
    # is where the ragged FLOP savings come from.
    @pl.when(j * block_k < tl)
    def _fold():
        q = q_ref[0].astype(jnp.float32) * scale        # (block_q, D)
        k_blk = k_ref[0].astype(jnp.float32)            # (block_k, D)
        v_blk = v_ref[0].astype(jnp.float32)

        s = q @ k_blk.T                                 # (block_q, block_k)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(kpos < tl, s, MASK_VALUE)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + p @ v_blk

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        l = l_ref[:]
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        valid = (qpos < tl) & (l > 0.0)
        o_ref[0] = jnp.where(
            valid, acc_ref[:] / jnp.where(l > 0.0, l, 1.0),
            0.0).astype(o_ref.dtype)


def _ragged_bhtd(q, k, v, tl_bh, scale, block_q, block_k, interpret):
    """(BH, T, D) x (BH, S, D) with per-BH true_len -> (BH, T, D)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    s_len = k.shape[1]
    kernel = functools.partial(_ragged_kernel, scale=scale,
                               block_q=block_q, block_k=block_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, t // block_q, s_len // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, i, j, *_: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),   # unnormalized acc
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tl_bh, q, k, v)


def ragged_attention(
    q: jax.Array,              # (B, T, H, D)
    k: jax.Array,              # (B, S, H, D)
    v: jax.Array,              # (B, S, H, D)
    true_len: jax.Array,       # (B,) int32 — valid prefix per batch row
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Self-attention over bucket-padded tokens with per-row true lengths.

    Dispatches to the pallas kernel on TPU (or under ``interpret=True`` for
    tests); everywhere else — and whenever the sequence doesn't tile — runs
    the dense masked reference, so the CPU tier-1 path is the oracle itself.
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    true_len = true_len.astype(jnp.int32)
    on_tpu = jax.default_backend() == "tpu"
    # Off-TPU the default is the dense reference (bit-exact tier-1 path);
    # interpret=True opts into the emulated pallas kernel for kernel tests.
    use_pallas = on_tpu or interpret is True
    if interpret is None:
        interpret = not on_tpu
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k or not use_pallas:
        return ragged_attention_reference(q, k, v, true_len, scale,
                                          q_true_len=true_len)

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    tl_bh = jnp.repeat(true_len, h)                     # (B*H,)
    out = _ragged_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), tl_bh, scale,
                       block_q, block_k, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)

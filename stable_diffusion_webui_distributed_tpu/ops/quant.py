"""Dynamic W8A8 int8 linears for the UNet's transformer blocks.

The v5e MXU multiplies s8 x s8 -> s32 at double the bf16 rate
(394 vs 197 TOP/s), and PERF.md's round-5 roofline analysis shows the
SDXL north-star target sits ABOVE the bf16 roofline — int8 is the only
single-chip lever that clears it (0.96 img/s/chip ceiling vs the 0.5
target). This module provides the minimal, checkpoint-compatible form:

- ``QuantDense`` stores exactly the same ``kernel``/``bias`` parameters
  as ``flax.linen.Dense`` (same names, same shapes, same initializers),
  so converted checkpoints, LoRA merges, and the param cache all work
  unchanged — quantization happens at CALL time, not load time.
- Quantization is dynamic and symmetric: per-token activation scales
  (max-abs over the feature axis) and per-output-channel weight scales,
  int32 accumulation, rescale to the layer dtype. No calibration pass,
  no stored scales.

Scope and honesty: only the transformer-block linears (qkv/out_proj,
GEGLU, ff_out, proj_in/out) quantize — convs, time embeddings, and
norms stay in the bf16/f32 policy. Dynamic W8A8 on diffusion UNets is
known to cost some image fidelity; this stays OFF unless
``SDTPU_UNET_INT8=1`` (Policy.unet_int8), and its quality must be
eyeballed with real weights before any default flip (README
"numerical-parity status"). Throughput is measured by sweep cells
``c2-int8`` / ``c4-int8``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def int8_dot(x: jax.Array, kernel: jax.Array, eps: float = 1e-8):
    """Dynamic symmetric W8A8 matmul: ``x @ kernel`` with int8 operands and
    int32 accumulation.

    x: (..., in_features) any float dtype; kernel: (in, out).
    Per-token activation scales, per-output-channel weight scales.
    Returns f32 of shape (..., out_features).
    """
    xf = x.astype(jnp.float32)
    kf = kernel.astype(jnp.float32)
    s_x = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0 + eps
    s_w = jnp.max(jnp.abs(kf), axis=0, keepdims=True) / 127.0 + eps
    xq = jnp.round(xf / s_x).astype(jnp.int8)
    wq = jnp.round(kf / s_w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * s_x * s_w


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense`` with the int8 dynamic-quant forward.

    Parameter tree is IDENTICAL to ``nn.Dense`` (kernel (in, out) via
    lecun_normal, optional bias zeros), so a module can switch between
    the two purely by construction flag with no checkpoint migration.
    """

    features: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features))
        out = int8_dot(x, kernel)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            out = out + bias.astype(jnp.float32)
        return out.astype(self.dtype)


def linear(quant: bool, features: int, *, use_bias: bool = True,
           dtype=jnp.float32, name: str):
    """The transformer-linear factory: ``nn.Dense`` or ``QuantDense``
    under the same parameter names."""
    cls = QuantDense if quant else nn.Dense
    return cls(features, use_bias=use_bias, dtype=dtype, name=name)


def int8_conv(x: jax.Array, kernel: jax.Array, *, strides=(1, 1),
              padding, eps: float = 1e-8):
    """Dynamic symmetric W8A8 NHWC conv with int32 accumulation.

    x: (B, H, W, Cin) float; kernel: (kh, kw, Cin, Cout) — flax layout.
    Per-IMAGE activation scales (max-abs over H, W, C — spatial weight
    sharing means one scale per image, not per pixel) and per-output-
    channel weight scales. Symmetric quant maps 0 -> 0, so zero padding
    is exact. Returns f32 (B, H', W', Cout).
    """
    xf = x.astype(jnp.float32)
    kf = kernel.astype(jnp.float32)
    s_x = jnp.max(jnp.abs(xf), axis=(1, 2, 3), keepdims=True) / 127.0 + eps
    s_w = jnp.max(jnp.abs(kf), axis=(0, 1, 2), keepdims=True) / 127.0 + eps
    xq = jnp.round(xf / s_x).astype(jnp.int8)
    wq = jnp.round(kf / s_w).astype(jnp.int8)
    acc = jax.lax.conv_general_dilated(
        xq, wq, window_strides=strides, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * s_x * s_w.reshape(1, 1, 1, -1)


class QuantConv(nn.Module):
    """Drop-in for ``nn.Conv`` (NHWC, HWIO) with the int8 forward.

    Parameter tree matches ``nn.Conv`` (kernel (kh, kw, in, out) via
    lecun_normal, bias zeros) so checkpoints swap freely. Supports the
    subset the UNet/VAE use: 2-D kernels, strides, int or explicit-pair
    padding; no dilation/groups/masking.
    """

    features: int
    kernel_size: tuple
    strides: tuple = (1, 1)
    padding: object = 0
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, x.shape[-1], self.features))
        pad = self.padding
        if isinstance(pad, int):
            pad = [(pad, pad), (pad, pad)]
        elif isinstance(pad, (tuple, list)) and pad and \
                not isinstance(pad[0], (tuple, list)):
            pad = [tuple(p) if isinstance(p, (tuple, list)) else (p, p)
                   for p in pad]
        out = int8_conv(x, kernel, strides=tuple(self.strides), padding=pad)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            out = out + bias.astype(jnp.float32)
        return out.astype(self.dtype)


def conv(quant: bool, features: int, kernel_size=(3, 3), *, strides=(1, 1),
         padding=1, dtype=jnp.float32, name: str):
    """ResBlock/Down/Up conv factory: ``nn.Conv`` or ``QuantConv`` under
    the same parameter names."""
    if quant:
        return QuantConv(features, tuple(kernel_size),
                         strides=tuple(strides), padding=padding,
                         dtype=dtype, name=name)
    return nn.Conv(features, tuple(kernel_size), strides=tuple(strides),
                   padding=padding, dtype=dtype, name=name)

"""Dynamic W8A8 int8 linears for the UNet's transformer blocks.

The v5e MXU multiplies s8 x s8 -> s32 at double the bf16 rate
(394 vs 197 TOP/s), and PERF.md's round-5 roofline analysis shows the
SDXL north-star target sits ABOVE the bf16 roofline — int8 is the only
single-chip lever that clears it (0.96 img/s/chip ceiling vs the 0.5
target). This module provides the minimal, checkpoint-compatible form:

- ``QuantDense`` stores exactly the same ``kernel``/``bias`` parameters
  as ``flax.linen.Dense`` (same names, same shapes, same initializers),
  so converted checkpoints, LoRA merges, and the param cache all work
  unchanged — quantization happens at CALL time, not load time.
- Quantization is dynamic and symmetric: per-token activation scales
  (max-abs over the feature axis) and per-output-channel weight scales,
  int32 accumulation, rescale to the layer dtype. No calibration pass,
  no stored scales.

Scope and honesty: only the transformer-block linears (qkv/out_proj,
GEGLU, ff_out, proj_in/out) quantize — convs, time embeddings, and
norms stay in the bf16/f32 policy. Dynamic W8A8 on diffusion UNets is
known to cost some image fidelity; this stays OFF unless
``SDTPU_UNET_INT8=1`` (Policy.unet_int8), and its quality must be
eyeballed with real weights before any default flip (README
"numerical-parity status"). Throughput is measured by sweep cells
``c2-int8`` / ``c4-int8``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def int8_dot(x: jax.Array, kernel: jax.Array, eps: float = 1e-8):
    """Dynamic symmetric W8A8 matmul: ``x @ kernel`` with int8 operands and
    int32 accumulation.

    x: (..., in_features) any float dtype; kernel: (in, out).
    Per-token activation scales, per-output-channel weight scales.
    Returns f32 of shape (..., out_features).
    """
    xf = x.astype(jnp.float32)
    kf = kernel.astype(jnp.float32)
    s_x = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0 + eps
    s_w = jnp.max(jnp.abs(kf), axis=0, keepdims=True) / 127.0 + eps
    xq = jnp.round(xf / s_x).astype(jnp.int8)
    wq = jnp.round(kf / s_w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * s_x * s_w


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense`` with the int8 dynamic-quant forward.

    Parameter tree is IDENTICAL to ``nn.Dense`` (kernel (in, out) via
    lecun_normal, optional bias zeros), so a module can switch between
    the two purely by construction flag with no checkpoint migration.
    """

    features: int
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features))
        out = int8_dot(x, kernel)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            out = out + bias.astype(jnp.float32)
        return out.astype(self.dtype)


def linear(quant: bool, features: int, *, use_bias: bool = True,
           dtype=jnp.float32, name: str):
    """The transformer-linear factory: ``nn.Dense`` or ``QuantDense``
    under the same parameter names."""
    cls = QuantDense if quant else nn.Dense
    return cls(features, use_bias=use_bias, dtype=dtype, name=name)

"""Pallas flash attention for the UNet's latent-token self-attention.

Online-softmax blockwise attention in the canonical TPU form: the grid is
``(batch*heads, T/block_q, S/block_k)`` with the key dimension innermost,
K/V arrive as ``block_k`` tiles through the pallas pipeline (double-buffered
DMA, never whole-sequence resident in VMEM), and the running softmax state
(m, l, acc) lives in VMEM scratch that persists across the sequential grid
steps of one query tile. The (T x S) score matrix never materializes in
HBM — the standard memory-bound win at SDXL resolutions (T = 4096 latent
tokens at 1024²) and the only viable form at the hires second pass
(T = 65536 at 2048², where even one (T x S) bf16 score matrix would be
8 GB). Whole-K-in-VMEM variants stop fitting around S≈16k at f32; tile
streaming has no such ceiling.

Falls back to ``jax.nn.dot_product_attention`` when shapes don't tile
(cross-attention's 77-token context) or when running on CPU test platforms
without ``interpret=True``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                 scale: float):
    """One (batch*head, q-tile, k-tile) step: fold one K/V tile into the
    running online-softmax state; finalize on the last k-tile."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * scale            # (block_q, D)
    k_blk = k_ref[0].astype(jnp.float32)                # (block_k, D)
    v_blk = v_ref[0].astype(jnp.float32)

    s = q @ k_blk.T                                     # (block_q, block_k)
    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    m_ref[:] = m_new
    l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + p @ v_blk

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def _flash_bhtd(q, k, v, scale, block_q, block_k, interpret):
    """(BH, T, D) x (BH, S, D) -> (BH, T, D)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    s_len = k.shape[1]
    kernel = functools.partial(_attn_kernel, scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, t // block_q, s_len // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),   # unnormalized acc
        ],
        interpret=interpret,
    )(q, k, v)


def flash_attention(
    q: jax.Array,      # (B, T, H, D)
    k: jax.Array,      # (B, S, H, D)
    v: jax.Array,      # (B, S, H, D)
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in for ``jax.nn.dot_product_attention`` (no mask/bias path).

    Tiles shrink to fit short sequences; if the sequence still doesn't tile
    evenly, falls back to the XLA path (correctness first — the reference's
    degraded-capability spirit, worker.py:457-467).
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k:
        return jax.nn.dot_product_attention(q, k, v, scale=scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), scale,
                      block_q, block_k, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)

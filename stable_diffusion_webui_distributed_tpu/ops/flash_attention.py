"""Pallas flash attention for the UNet's latent-token self-attention.

Online-softmax blockwise attention: K/V stream through VMEM in
``block_k``-sized tiles per ``block_q`` query tile, so the (T x S) score
matrix never materializes in HBM — the standard memory-bound win at SDXL
resolutions (T = 4096 latent tokens at 1024², 16384 at 2048² hires).

Falls back to ``jax.nn.dot_product_attention`` when shapes don't tile
(cross-attention's 77-token context) or when running on CPU test platforms
without ``interpret=True``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int):
    """One (batch*head, q-tile) program: stream K/V tiles, online softmax."""
    q = q_ref[0].astype(jnp.float32) * scale           # (block_q, D)
    block_q, d = q.shape
    s_len = k_ref.shape[1]

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T                                 # (block_q, block_k)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + p @ v_blk
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, s_len // block_k, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_bhtd(q, k, v, scale, block_q, block_k, interpret):
    """(BH, T, D) x (BH, S, D) -> (BH, T, D)."""
    bh, t, d = q.shape
    kernel = functools.partial(_attn_kernel, scale=scale, block_k=block_k)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, k.shape[1], d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, k.shape[1], d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


def flash_attention(
    q: jax.Array,      # (B, T, H, D)
    k: jax.Array,      # (B, S, H, D)
    v: jax.Array,      # (B, S, H, D)
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in for ``jax.nn.dot_product_attention`` (no mask/bias path).

    Tiles shrink to fit short sequences; if the sequence still doesn't tile
    evenly, falls back to the XLA path (correctness first — the reference's
    degraded-capability spirit, worker.py:457-467).
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k:
        return jax.nn.dot_product_attention(q, k, v, scale=scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), scale,
                      block_q, block_k, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)

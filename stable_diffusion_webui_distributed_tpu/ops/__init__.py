"""Hand-written TPU kernels (Pallas) + sequence-parallel collectives.

The reference has no kernels at all — its FLOPs live in remote CUDA
processes. Here the UNet's self-attention over latent tokens (4096 tokens at
SDXL 1024², 16k+ at hires resolutions) is the MXU hot spot, served by a
Pallas flash-attention kernel; beyond single-chip VMEM limits, ring
attention shards the token axis over the mesh's ``sp`` axis and rotates K/V
blocks over ICI (the long-context strategy the task brief makes
first-class).
"""

from stable_diffusion_webui_distributed_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
)
from stable_diffusion_webui_distributed_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
)

"""Ring attention: sequence parallelism over the mesh's ``sp`` axis.

For resolutions whose latent-token count outgrows one chip (hires 2048²+ =
65k tokens), Q/K/V are sharded over tokens on the ``sp`` axis; each device
computes attention of its local query shard against K/V blocks that rotate
around the ring via ``lax.ppermute`` over ICI, accumulated with the online
softmax (permutation-invariant, so ring order never changes the result).
This is the blockwise/ring-attention recipe the task brief makes
first-class; the reference has no counterpart (its long-sequence axis is
pixels, handled by per-worker caps — SURVEY.md §5 long-context).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


_RING_CHUNK_DEFAULT = 1024


def _ring_chunk() -> int:
    """Upper bound on the key-block chunk folded per inner step
    (SDTPU_RING_CHUNK, default 1024): the per-device score buffer is
    (b, h, t_loc, chunk) instead of (b, h, t_loc, t_loc) — at the hires
    65k-token scale a full local score matrix would be GBs of HBM per
    ring step; chunked folding keeps it flat."""
    from stable_diffusion_webui_distributed_tpu.runtime.config import env_int

    return max(128, env_int("SDTPU_RING_CHUNK", _RING_CHUNK_DEFAULT))


def _ring_body(q, k, v, axis_name: str, scale: float, vary_axes=None):
    """Per-device computation: local Q against the rotating K/V ring.

    Each ring step folds its K/V block into the running online softmax in
    bounded key-chunks (an inner ``lax.scan``) — the same associative
    (m, l, acc) update at two granularities, so the result is identical
    to the dense fold up to float summation order."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    b, t_loc, h, d = q.shape
    qf = q.astype(jnp.float32) * scale

    # fresh accumulators must be marked device-varying over every mesh axis
    # the inputs vary over (the ring axis, plus dp on combined dp+sp
    # meshes) or the fori_loop carry types disagree under shard_map; older
    # jax has no varying-mesh-axes type system, so pcast degrades to identity
    _pcast = getattr(lax, "pcast", None)

    def varying(x):
        if _pcast is None:
            return x
        return _pcast(x, vary_axes or axis_name, to="varying")

    m0 = varying(jnp.full((b, h, t_loc, 1), -jnp.inf, jnp.float32))
    l0 = varying(jnp.zeros((b, h, t_loc, 1), jnp.float32))
    acc0 = varying(jnp.zeros((b, h, t_loc, d), jnp.float32))

    s_loc = k.shape[1]
    chunk = min(_ring_chunk(), s_loc)
    # non-divisor request: pad the local K/V block up to the next chunk
    # multiple and mask the tail (scores -> -inf, so exp -> 0 and the
    # padded keys contribute nothing to l or acc). This keeps the HBM
    # bound of the chunked fold at every resolution without degrading the
    # chunk size toward 1 when s_loc is near-prime.
    n_chunks = -(-s_loc // chunk)
    pad = n_chunks * chunk - s_loc
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # (n_chunks, chunk) validity mask for key positions; only the final
    # chunk can contain padding, but carrying it through the scan keeps
    # the fold uniform
    key_valid = (jnp.arange(n_chunks * chunk) < s_loc).reshape(
        n_chunks, chunk)

    def fold(carry, kv):
        m, l, acc = carry
        k_c, v_c, valid_c = kv                      # (b, chunk, h, d)
        s = jnp.einsum("bthd,bshd->bhts", qf, k_c.astype(jnp.float32))
        if pad:
            s = jnp.where(valid_c[None, None, None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhts,bshd->bhtd", p, v_c.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    def step(_, carry):
        m, l, acc, k_blk, v_blk = carry
        if n_chunks == 1:
            (m, l, acc), _ = fold((m, l, acc), (k_blk, v_blk, key_valid[0]))
        else:
            kc = k_blk.reshape(b, n_chunks, chunk, h, d).transpose(
                1, 0, 2, 3, 4)
            vc = v_blk.reshape(b, n_chunks, chunk, h, d).transpose(
                1, 0, 2, 3, 4)
            (m, l, acc), _ = lax.scan(fold, (m, l, acc), (kc, vc, key_valid))
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return m, l, acc, k_next, v_next

    m, l, acc, _, _ = lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    out = acc / l                                  # (b, h, t_loc, d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,      # (B, T, H, D), T sharded over `axis_name`
    k: jax.Array,
    v: jax.Array,
    mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh``'s ``axis_name`` ring.

    Inputs/outputs are global arrays; sharding is applied here via
    ``shard_map`` (batch replicated or dp-sharded upstream; tokens split
    over the ring axis).
    """
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _shard_map  # jax >= 0.6 name

        shard_map = _shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # carry the dp axis on the batch dim when the mesh has one — otherwise
    # shard_map would declare the batch replicated and XLA would all-gather
    # activations across dp at every layer
    dp = "dp" if mesh.shape.get("dp", 1) > 1 else None
    spec = P(dp, axis_name, None, None)
    vary_axes = (axis_name, dp) if dp else (axis_name,)

    def body(q_l, k_l, v_l):
        return _ring_body(q_l, k_l, v_l, axis_name, scale, vary_axes)

    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)

"""Warm engine pool: pre-initialized residents the autoscaler can act on.

The autoscaler (``fleet/slices.py``) has emitted scale decisions into an
audit ring since ISSUE 6d — but every entry read ``no_executor`` because
acting on a decision meant eating a cold-start compile storm. With AOT
artifacts (``serving/aot.py``) a fresh engine hydrates in seconds, so
this module closes the loop: a :class:`WarmPool` of in-process engine
*residents*, each built by a caller-supplied factory and warmed through
the artifact store, with

- **checkout routing** — the dispatcher borrows the least-loaded healthy
  resident per execution (``Dispatcher(pool=...)``), so admitted
  requests spread across residents the way the source paper's World/Job
  ipm optimization spreads jobs across a heterogeneous worker pool;
- **real executors** — :meth:`attach_autoscale` registers a hook that
  turns ``up`` decisions into spawns and ``down`` decisions into
  retirements, then upgrades the audit entry to ``executed`` / ``failed``
  via ``AutoscaleEngine.record_execution``;
- **healing** — a resident killed by a chaos fault (``sim/``) stops
  taking checkouts immediately (requests already inflight on it finish
  or fail on their own engine — never double-merge onto a replacement),
  and :meth:`heal` spawns back to target size, timing the heal through
  the ``sdtpu_cold_start_seconds`` histogram.

Everything is in-process and synchronous — no daemon threads, no device
assumptions — so the schedule explorer can drive spawn/teardown
interleavings deterministically. Gated ``SDTPU_POOL`` (default off);
knobs: ``SDTPU_POOL_SIZE`` (target residents, default 2),
``SDTPU_POOL_COOLDOWN_S`` (min seconds between autoscale-driven
spawn/retire executions, default 0).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.runtime.config import (
    env_flag, env_float, env_int,
)

DEFAULT_POOL_SIZE = 2


def enabled() -> bool:
    """Pool gate — re-read per call so tests/bench phases can flip it."""
    return env_flag("SDTPU_POOL", False)


class EngineResident:
    """One pooled engine and its serving state.

    States: ``ready`` (takes checkouts), ``dead`` (chaos-killed — takes
    no new checkouts; its inflight work belongs to it alone), ``retired``
    (scale-down — drains and drops). State flips are O(1) under the pool
    lock; the engine itself is built and warmed outside it."""

    def __init__(self, name: str, engine: Any, spawn_s: float) -> None:
        self.name = name
        self.engine = engine
        self.spawn_s = spawn_s
        self.state = "ready"
        self.inflight = 0
        self.checkouts_total = 0
        self.spawned_at = time.time()


class WarmPool:
    """A fixed-target pool of engine residents with least-loaded checkout.

    ``factory(name) -> engine`` builds one resident's engine; ``warm``
    (optional, ``warm(engine)``) runs after construction — typically
    ``serving.warmup.warmup_engine`` so the resident hydrates every
    manifest cell before it ever sees traffic. Both run OUTSIDE the pool
    lock; only the bookkeeping is serialized."""

    def __init__(self, factory: Callable[[str], Any],
                 size: Optional[int] = None,
                 warm: Optional[Callable[[Any], Any]] = None,
                 clock=time.monotonic) -> None:
        self.factory = factory
        self.warm = warm
        self.size = max(1, env_int("SDTPU_POOL_SIZE", DEFAULT_POOL_SIZE)
                        if size is None else int(size))
        self.cooldown_s = env_float("SDTPU_POOL_COOLDOWN_S", 0.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._residents: Dict[str, EngineResident] = {}  # guarded-by: _lock
        self._spawn_seq = 0  # guarded-by: _lock
        self._last_exec = -1e18  # guarded-by: _lock (autoscale cooldown)
        self._spawns_total = 0  # guarded-by: _lock
        self._retires_total = 0  # guarded-by: _lock
        self._kills_total = 0  # guarded-by: _lock

    # -- lifecycle --------------------------------------------------------

    def _next_name(self) -> str:
        with self._lock:
            self._spawn_seq += 1
            return f"resident-{self._spawn_seq}"

    def spawn(self, name: Optional[str] = None) -> EngineResident:
        """Build + warm one resident (outside the lock) and register it.
        The build-to-ready wall time is the pool's cold start — it lands
        in ``sdtpu_cold_start_seconds``, which is what the AOT bench
        squeezes."""
        name = name or self._next_name()
        t0 = self._clock()
        engine = self.factory(name)
        if self.warm is not None:
            self.warm(engine)
        spawn_s = max(0.0, self._clock() - t0)
        res = EngineResident(name, engine, spawn_s)
        with self._lock:
            self._residents[name] = res
            self._spawns_total += 1
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
            prometheus as obs_prom,
        )

        obs_prom.observe_cold_start(spawn_s)
        if obs_journal.enabled():
            obs_journal.emit("pool_spawned", f"pool-{name}",
                             spawn_s=round(spawn_s, 4))
        return res

    def kill(self, name: str) -> bool:
        """Chaos entry point (``sim/``): the resident stops taking new
        checkouts NOW. Work already inflight on it keeps its engine —
        a request never re-runs on a replacement, so a heal can never
        double-merge images."""
        with self._lock:
            res = self._residents.get(name)
            if res is None or res.state != "ready":
                return False
            res.state = "dead"
            self._kills_total += 1
        return True

    def retire_one(self) -> Optional[str]:
        """Scale-down: mark the least-loaded ready resident retired (it
        drains naturally; a retired resident with zero inflight is
        dropped from the table). Refuses to retire the last ready one."""
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
        )

        with self._lock:
            ready = [r for r in self._residents.values()
                     if r.state == "ready"]
            if len(ready) <= 1:
                return None
            res = min(ready, key=lambda r: (r.inflight, r.name))
            res.state = "retired"
            self._retires_total += 1
            if res.inflight == 0:
                self._residents.pop(res.name, None)
            name = res.name
        if obs_journal.enabled():
            obs_journal.emit("pool_retired", f"pool-{name}")
        return name

    def heal(self) -> List[str]:
        """Spawn residents until the ready count reaches the target size
        (the chaos scenario times this). Spawns run outside the lock,
        one at a time — deterministic under the schedule explorer."""
        spawned: List[str] = []
        while True:
            with self._lock:
                ready = sum(1 for r in self._residents.values()
                            if r.state == "ready")
            if ready >= self.size:
                return spawned
            spawned.append(self.spawn().name)

    # -- checkout routing -------------------------------------------------

    def acquire(self) -> EngineResident:
        """Least-loaded ready resident (ties break by name for
        determinism); spawns synchronously when the pool is empty."""
        while True:
            with self._lock:
                ready = [r for r in self._residents.values()
                         if r.state == "ready"]
                if ready:
                    res = min(ready, key=lambda r: (r.inflight, r.name))
                    res.inflight += 1
                    res.checkouts_total += 1
                    return res
            # empty pool: build one (outside the lock), then retry the
            # selection — a racing acquire may win it, which is fine
            self.spawn()

    def release(self, res: EngineResident) -> None:
        with self._lock:
            res.inflight = max(0, res.inflight - 1)
            if res.state == "retired" and res.inflight == 0:
                self._residents.pop(res.name, None)

    # -- autoscale executor -----------------------------------------------

    def attach_autoscale(self, autoscale) -> None:
        """Wire an ``AutoscaleEngine``'s decisions to real capacity: up
        spawns a resident, down retires one, and the decision's audit
        entry is upgraded from ``no_executor`` to ``executed`` /
        ``failed`` (detail says why — cooldown, last resident, error)."""

        def execute(decision) -> None:
            now = self._clock()
            with self._lock:
                if now - self._last_exec < self.cooldown_s:
                    in_cooldown = True
                else:
                    in_cooldown = False
                    self._last_exec = now
            if in_cooldown:
                autoscale.record_execution(decision, "failed", "cooldown")
                return
            try:
                if decision.direction == "up":
                    name = self.spawn().name
                    autoscale.record_execution(
                        decision, "executed", f"spawned {name}")
                else:
                    name = self.retire_one()
                    if name is None:
                        autoscale.record_execution(
                            decision, "failed", "last ready resident")
                    else:
                        autoscale.record_execution(
                            decision, "executed", f"retired {name}")
            except Exception as exc:  # noqa: BLE001 — audit, don't raise
                autoscale.record_execution(
                    decision, "failed", f"{type(exc).__name__}: {exc}")

        autoscale.add_hook(execute)

    # -- introspection ----------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """The ``/internal/status`` pool block."""
        with self._lock:
            residents = [
                {"name": r.name, "state": r.state, "inflight": r.inflight,
                 "checkouts_total": r.checkouts_total,
                 "spawn_s": round(r.spawn_s, 4)}
                for r in sorted(self._residents.values(),
                                key=lambda r: r.name)
            ]
            return {
                "enabled": enabled(),
                "size": self.size,
                "ready": sum(1 for r in self._residents.values()
                             if r.state == "ready"),
                "residents": residents,
                "spawns_total": self._spawns_total,
                "retires_total": self._retires_total,
                "kills_total": self._kills_total,
                "cooldown_s": self.cooldown_s,
            }


# -- module-level active pool (server/api.py reads it) -----------------------

_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[WarmPool] = None  # guarded-by: _ACTIVE_LOCK


def set_pool(pool: Optional[WarmPool]) -> None:
    """Install ``pool`` as the process-wide warm pool (last one wins);
    the deployment that builds the pool calls this so
    ``/internal/status`` can report it."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = pool


def get_pool() -> Optional[WarmPool]:
    with _ACTIVE_LOCK:
        return _ACTIVE

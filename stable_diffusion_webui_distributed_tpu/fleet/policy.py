"""Priority classes and the weighted-fair device gate.

The fleet tier (this package) turns the dispatcher's single FIFO device
lock into a scheduled resource: requests carry a tenant id and a priority
class (``interactive`` / ``batch`` / ``best_effort``), waiters are ordered
by weighted-fair queueing with starvation-free aging, and long preemptible
jobs yield the device to interactive traffic at chunk-scan boundaries
(the engine's existing interrupt-poll points, pipeline/engine.py).

Everything here is host-side policy — no JAX, no device work — so the
whole module is unit-testable with a fake clock (tests/test_fleet.py).

Knobs (runtime/config.py helpers; documented in the config knob block):

- ``SDTPU_FLEET`` — master switch; 0 (default) keeps the dispatcher's
  plain exec-lock path byte-identical to the pre-fleet build.
- ``SDTPU_FLEET_CLASSES`` — ``name:weight`` list overriding class weights,
  e.g. ``interactive:8,batch:2,best_effort:1``.
- ``SDTPU_SLO_INTERACTIVE_S`` — interactive completion SLO (seconds) the
  admission controller enforces (fleet/admission.py).
- ``SDTPU_FLEET_AGING_S`` — waiters older than this are served oldest
  first regardless of fair-queue tags (starvation bound).
- ``SDTPU_FLEET_QUANTUM_S`` — minimum device tenure before a preemptible
  job may be asked to yield (anti-thrash).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

INTERACTIVE = "interactive"
BATCH = "batch"
BEST_EFFORT = "best_effort"

#: default WFQ weights per class (SDTPU_FLEET_CLASSES overrides)
DEFAULT_WEIGHTS = {INTERACTIVE: 8.0, BATCH: 2.0, BEST_EFFORT: 1.0}
DEFAULT_SLO_INTERACTIVE_S = 30.0
DEFAULT_AGING_S = 10.0
DEFAULT_QUANTUM_S = 0.25


@dataclasses.dataclass(frozen=True)
class ClassPolicy:
    """One priority class: fair-share weight, optional completion SLO, and
    the preemption relation (who this class may displace)."""

    name: str
    weight: float
    slo_s: Optional[float] = None  # None = no completion SLO
    preemptible: bool = False      # may be asked to yield mid-denoise
    preempts: Tuple[str, ...] = ()  # classes a waiter of this class bumps


def _parse_class_weights(raw: str) -> Dict[str, float]:
    """``interactive:8,batch:2`` -> {..}; malformed entries are skipped via
    env_parsed's warn-and-default contract (the caller wraps us)."""
    out: Dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        w = float(weight)  # ValueError propagates to env_parsed
        if w <= 0:
            raise ValueError(f"weight for {name!r} must be > 0")
        out[name.strip()] = w
    return out


class FleetPolicy:
    """Resolved class table + scheduler constants (immutable after init)."""

    def __init__(self,
                 weights: Optional[Dict[str, float]] = None,
                 slo_interactive_s: Optional[float] = None,
                 aging_s: Optional[float] = None,
                 quantum_s: Optional[float] = None) -> None:
        w = dict(DEFAULT_WEIGHTS)
        w.update(weights or {})
        slo = DEFAULT_SLO_INTERACTIVE_S if slo_interactive_s is None \
            else slo_interactive_s
        self.classes: Dict[str, ClassPolicy] = {
            INTERACTIVE: ClassPolicy(
                INTERACTIVE, w[INTERACTIVE],
                slo_s=(slo if slo > 0 else None),
                preemptible=False, preempts=(BATCH, BEST_EFFORT)),
            BATCH: ClassPolicy(BATCH, w[BATCH], preemptible=True),
            BEST_EFFORT: ClassPolicy(
                BEST_EFFORT, w[BEST_EFFORT], preemptible=True),
        }
        # custom classes from SDTPU_FLEET_CLASSES: scheduled like batch
        for name, weight in w.items():
            if name not in self.classes:
                self.classes[name] = ClassPolicy(name, weight,
                                                 preemptible=True)
        self.aging_s = DEFAULT_AGING_S if aging_s is None else aging_s
        self.quantum_s = DEFAULT_QUANTUM_S if quantum_s is None \
            else quantum_s

    def resolve(self, name: Optional[str]) -> ClassPolicy:
        """Class lookup: unset -> interactive (the pre-fleet behavior for
        every request), unknown -> best_effort (never let a typo grab the
        high-priority lane)."""
        if not name:
            return self.classes[INTERACTIVE]
        return self.classes.get(str(name), self.classes[BEST_EFFORT])

    @classmethod
    def from_env(cls) -> "FleetPolicy":
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            env_float, env_parsed,
        )

        weights = env_parsed("SDTPU_FLEET_CLASSES", _parse_class_weights,
                             {}, "class:weight list")
        return cls(
            weights=weights,
            slo_interactive_s=env_float("SDTPU_SLO_INTERACTIVE_S",
                                        DEFAULT_SLO_INTERACTIVE_S),
            aging_s=env_float("SDTPU_FLEET_AGING_S", DEFAULT_AGING_S),
            quantum_s=env_float("SDTPU_FLEET_QUANTUM_S", DEFAULT_QUANTUM_S))


def fleet_enabled(config=None) -> bool:
    """Master switch. Env SDTPU_FLEET wins; otherwise the config model's
    ``fleet_enabled`` field; default off (pre-fleet byte-identity)."""
    from stable_diffusion_webui_distributed_tpu.runtime.config import (
        env_flag, env_str,
    )

    if env_str("SDTPU_FLEET"):
        return env_flag("SDTPU_FLEET", False)
    if config is not None:
        val = getattr(config, "fleet_enabled", None)
        if val is not None:
            return bool(val)
    return False


class GateEntry:
    """One waiter at the device gate (a request or a coalesced group)."""

    _seq = itertools.count()

    def __init__(self, policy: ClassPolicy, tenant: str = "default",
                 cost: float = 1.0, request_id: str = "") -> None:
        self.policy = policy
        self.tenant = tenant
        self.cost = max(0.0, float(cost))  # images — the WFQ work unit
        self.request_id = request_id
        self.seq = next(GateEntry._seq)
        self.enqueued: Optional[float] = None  # stamped by the queue
        self.tag: float = 0.0                  # WFQ virtual finish time

    @property
    def flow(self) -> Tuple[str, str]:
        return (self.tenant, self.policy.name)


class WeightedFairQueue:
    """Virtual-time weighted-fair queue over (tenant, class) flows with an
    aging override: any waiter older than ``aging_s`` is served oldest
    first, bounding starvation no matter how the weights are set.

    Thread-safe on its own lock so it can also be inspected (depth, peek)
    outside the gate's condition variable.
    """

    def __init__(self, aging_s: float = DEFAULT_AGING_S,
                 clock=time.monotonic) -> None:
        self.aging_s = aging_s
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: List[GateEntry] = []  # guarded-by: _lock
        self._flow_tag: Dict[Tuple[str, str], float] = {}  # guarded-by: _lock
        self._vt = 0.0  # guarded-by: _lock — virtual time floor

    def push(self, entry: GateEntry, recost: bool = True) -> None:
        """Enqueue. ``recost=False`` re-admits a preempted runner without
        charging its cost again — it keeps its original finish tag, so a
        yielded batch job resumes after the interactive waiters that bumped
        it but ahead of work that arrived later."""
        with self._lock:
            if entry.enqueued is None:
                entry.enqueued = self._clock()
            prev = self._flow_tag.get(entry.flow, 0.0)
            if recost:
                entry.tag = max(self._vt, prev) \
                    + entry.cost / max(entry.policy.weight, 1e-9)
                self._flow_tag[entry.flow] = entry.tag
            else:
                entry.tag = max(prev, entry.tag)
            self._entries.append(entry)

    def select(self) -> Optional[GateEntry]:
        """The waiter that should run next (non-destructive)."""
        with self._lock:
            if not self._entries:
                return None
            now = self._clock()
            aged = [e for e in self._entries
                    if e.enqueued is not None
                    and now - e.enqueued >= self.aging_s]
            if aged:
                return min(aged, key=lambda e: (e.enqueued, e.seq))
            # the preemption relation outranks fair-queue tags: a waiter
            # whose class has an entitled preemptor queued must not win
            # the gate ahead of it. Without this, a yielded batch runner
            # (re-queued with its KEPT tag, which predates the virtual
            # time its own admission advanced) selects itself straight
            # back and the yield livelocks. Aging above still bounds
            # starvation of the preempted class.
            bumped = set()
            for e in self._entries:
                bumped.update(e.policy.preempts)
            pool = [e for e in self._entries
                    if e.policy.name not in bumped] or self._entries
            return min(pool, key=lambda e: (e.tag, e.seq))

    def remove(self, entry: GateEntry) -> None:
        with self._lock:
            if entry in self._entries:
                self._entries.remove(entry)
                self._vt = max(self._vt, entry.tag)

    def has_preemptor_for(self, policy: ClassPolicy) -> bool:
        """Is any waiter entitled to bump a runner of class ``policy``?"""
        with self._lock:
            return any(policy.name in e.policy.preempts
                       for e in self._entries)

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def depth_by_class(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for e in self._entries:
                out[e.policy.name] = out.get(e.policy.name, 0) + 1
            return out


class FleetGate:
    """Policy-ordered replacement for the dispatcher's bare exec lock.

    ``acquire``/``release`` bracket one device execution exactly like the
    lock did, but the next runner is chosen by the weighted-fair queue,
    and a preemptible runner polls :meth:`should_yield` at chunk
    boundaries (via the engine preempt hook) — ``yield_device`` then
    releases the device, lets the preemptor run, and blocks until the
    queue selects this entry again. All denoise-loop state lives in the
    yielding thread's frame, so resumption is byte-identical and hits the
    same compiled executables (zero new compiles).
    """

    def __init__(self, policy: Optional[FleetPolicy] = None,
                 clock=time.monotonic) -> None:
        self.policy = policy or FleetPolicy()
        self._clock = clock
        self._cv = threading.Condition()
        self.queue = WeightedFairQueue(self.policy.aging_s, clock)
        self._running: Optional[GateEntry] = None  # guarded-by: _cv
        self._run_started = 0.0  # guarded-by: _cv
        self._preemptions = 0  # guarded-by: _cv

    # -- lock-like protocol -------------------------------------------------

    def acquire(self, entry: GateEntry, recost: bool = True) -> None:
        self.queue.push(entry, recost=recost)
        try:
            with self._cv:
                while self._running is not None \
                        or self.queue.select() is not entry:
                    # timeout: aging promotions change the selection
                    # without a release event; a bounded wait keeps the
                    # bound live
                    self._cv.wait(0.25)
                self.queue.remove(entry)
                self._running = entry
                self._run_started = self._clock()
        except BaseException:
            # a dying waiter (e.g. KeyboardInterrupt inside cv.wait) must
            # not leave its entry queued: select() would keep returning
            # the orphan — oldest entry wins the aging branch — and every
            # other waiter would deadlock permanently
            self.queue.remove(entry)
            with self._cv:
                if self._running is entry:
                    self._running = None
                self._cv.notify_all()
            raise

    def release(self, entry: GateEntry) -> None:
        with self._cv:
            if self._running is entry:
                self._running = None
            self._cv.notify_all()

    # -- preemption ---------------------------------------------------------

    def should_yield(self, entry: GateEntry) -> bool:
        """Poll: does a queued waiter outrank this (running) entry?  Cheap
        — called between denoise chunk dispatches."""
        with self._cv:
            if self._running is not entry or not entry.policy.preemptible:
                return False
            if self._clock() - self._run_started < self.policy.quantum_s:
                return False
        return self.queue.has_preemptor_for(entry.policy)

    def yield_device(self, entry: GateEntry) -> None:
        """Give the device up and re-queue without re-charging cost; the
        call returns when the queue hands the device back."""
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
        )
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        with self._cv:
            self._preemptions += 1
            if self._running is entry:
                self._running = None
            self._cv.notify_all()
        obs_prom.fleet_count("preemptions", **{"class": entry.policy.name})
        if obs_journal.enabled() and entry.request_id:
            obs_journal.emit("preempted", entry.request_id,
                             **{"class": entry.policy.name})
        self.acquire(entry, recost=False)
        if obs_journal.enabled() and entry.request_id:
            obs_journal.emit("resumed", entry.request_id,
                             **{"class": entry.policy.name})

    # -- introspection ------------------------------------------------------

    def preemption_count(self) -> int:
        with self._cv:
            return self._preemptions

    def summary(self) -> Dict[str, object]:
        with self._cv:
            running = self._running
            preemptions = self._preemptions
        return {
            "queue_depth": self.queue.depth(),
            "queue_by_class": self.queue.depth_by_class(),
            "running_class": running.policy.name if running else None,
            "preemptions": preemptions,
            "classes": {name: {"weight": c.weight, "slo_s": c.slo_s,
                               "preemptible": c.preemptible}
                        for name, c in self.policy.classes.items()},
        }


class EnginePreemptHook:
    """The object installed as ``engine.preempt_hook`` for one preemptible
    execution. Thread-filtered: coalesced/interactive work running *during*
    a yield sees the same engine attribute, so every method no-ops unless
    called from the owning thread."""

    def __init__(self, gate: FleetGate, entry: GateEntry) -> None:
        self._gate = gate
        self._entry = entry
        self._owner = threading.get_ident()

    def should_yield(self) -> bool:
        return threading.get_ident() == self._owner \
            and self._gate.should_yield(self._entry)

    def yield_device(self) -> None:
        if threading.get_ident() == self._owner:
            self._gate.yield_device(self._entry)

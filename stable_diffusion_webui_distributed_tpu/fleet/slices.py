"""Slice registry + autoscale signals.

A *slice* is a logical mesh partition serving one group of traffic (a
model family at a precision, e.g. ``sdxl/bf16``). This registry is the
fleet's placement table — which serving groups live on which slices and
how many replicas each has — and the decision engine that turns the
Prometheus queue-wait evidence into scale-up/scale-down signals.

Scope (ISSUE 6d): decisions + hooks land now; *acting* on a decision
(instantiating another engine over a disjoint device set) rides the
existing stage-pipeline disjoint-mesh machinery and is wired by the
deployment via :meth:`AutoscaleEngine.add_hook`. The decision engine
therefore never touches a device — it reads histogram quantiles and
emits :class:`ScaleDecision` records, which also makes it fully
CPU-testable.

Signal: per-class fleet queue-wait p95 (``sdtpu_fleet_queue_wait_seconds``
in obs/prometheus.py). Sustained p95 above ``SDTPU_AUTOSCALE_UP_S``
asks for a replica; p95 below ``SDTPU_AUTOSCALE_DOWN_S`` with more than
``min_replicas`` releases one. A cooldown stops flapping.

A second scale-up signal rides beside the point read: alert rules
marked ``scale_up`` in obs/alerts.py (SLO burn, queue-wait anomaly)
trigger a scale-up while firing even when the instantaneous p95 sits
below the threshold — the windowed detectors see a trend the point
read misses. Scale-down keeps its worker-health veto unchanged.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

DEFAULT_UP_P95_S = 5.0
DEFAULT_DOWN_P95_S = 0.5
DEFAULT_COOLDOWN_S = 60.0
#: audit-ring capacity default (SDTPU_AUTOSCALE_AUDIT)
DEFAULT_AUDIT_CAP = 256


@dataclasses.dataclass
class SliceInfo:
    """One logical mesh slice and the serving group pinned to it."""

    name: str
    group: str = ""                 # serving group key, e.g. "sdxl/bf16"
    mesh_axes: Dict[str, int] = dataclasses.field(default_factory=dict)
    replicas: int = 1
    min_replicas: int = 1
    max_replicas: int = 4


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    slice_name: str
    direction: str                  # "up" | "down"
    reason: str
    p95_s: float
    replicas: int                   # replica count AFTER the decision


class SliceRegistry:
    """Thread-safe name -> :class:`SliceInfo` table."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slices: Dict[str, SliceInfo] = {}  # guarded-by: _lock

    def register(self, info: SliceInfo) -> None:
        with self._lock:
            self._slices[info.name] = info

    def get(self, name: str) -> Optional[SliceInfo]:
        with self._lock:
            return self._slices.get(name)

    def for_group(self, group: str) -> List[SliceInfo]:
        with self._lock:
            return [s for s in self._slices.values() if s.group == group]

    def set_replicas(self, name: str, replicas: int) -> None:
        with self._lock:
            s = self._slices.get(name)
            if s is not None:
                s.replicas = max(s.min_replicas,
                                 min(s.max_replicas, int(replicas)))

    def summary(self) -> Dict[str, Dict]:
        with self._lock:
            return {name: dataclasses.asdict(s)
                    for name, s in self._slices.items()}


class AutoscaleEngine:
    """Queue-wait-driven scale decisions over a :class:`SliceRegistry`.

    ``quantile_source`` abstracts the Prometheus read — production passes
    :func:`obs.prometheus.fleet_queue_wait_p95`, tests pass a lambda.
    Hooks receive every emitted :class:`ScaleDecision`; the registry's
    replica count is updated first, so a hook reads the post-decision
    state.
    """

    def __init__(self, registry: SliceRegistry,
                 quantile_source: Optional[Callable[[], float]] = None,
                 up_p95_s: Optional[float] = None,
                 down_p95_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 clock=time.monotonic,
                 health_source: Optional[Callable[[], Dict[str, Dict]]]
                 = None,
                 alert_source: Optional[Callable[[], List[str]]]
                 = None) -> None:
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            env_float, env_int,
        )

        self.registry = registry
        self.quantile_source = quantile_source \
            or _default_quantile_source
        self.up_p95_s = env_float("SDTPU_AUTOSCALE_UP_S", DEFAULT_UP_P95_S) \
            if up_p95_s is None else up_p95_s
        self.down_p95_s = env_float("SDTPU_AUTOSCALE_DOWN_S",
                                    DEFAULT_DOWN_P95_S) \
            if down_p95_s is None else down_p95_s
        self.cooldown_s = env_float("SDTPU_AUTOSCALE_COOLDOWN_S",
                                    DEFAULT_COOLDOWN_S) \
            if cooldown_s is None else cooldown_s
        self._clock = clock
        #: optional worker-health feed (World.health_summary) — scale-down
        #: is vetoed while any worker looks unhealthy, since the apparent
        #: headroom may just be capacity the fleet already lost
        self.health_source = health_source
        #: alert feed (obs.alerts.scale_up_firing unless overridden):
        #: firing scale_up-marked rules trigger a scale-up beside the
        #: queue-wait point read; [] with SDTPU_ALERTS off
        self.alert_source = alert_source or _default_alert_source
        self._lock = threading.Lock()
        self._hooks: List[Callable[[ScaleDecision], None]] = []  # guarded-by: _lock
        self._last_decision: Dict[str, float] = {}  # guarded-by: _lock
        #: bounded decision audit ring (ISSUE 8: /internal/autoscale) —
        #: each entry is asdict(decision) + a wall-clock decided_at so an
        #: operator can line decisions up against external monitoring
        self._audit_cap = max(1, env_int("SDTPU_AUTOSCALE_AUDIT",
                                         DEFAULT_AUDIT_CAP))
        # guarded-by: _lock
        self._decisions: Deque[ScaleDecision] = \
            collections.deque(maxlen=self._audit_cap)
        # guarded-by: _lock
        self._audit: Deque[Dict[str, object]] = \
            collections.deque(maxlen=self._audit_cap)
        self._audit_total = 0  # guarded-by: _lock
        set_autoscale(self)  # last engine created serves /internal/autoscale

    def add_hook(self, hook: Callable[[ScaleDecision], None]) -> None:
        with self._lock:
            self._hooks.append(hook)

    def unhealthy_workers(self) -> List[str]:
        """Labels the health feed currently considers unhealthy (3+
        consecutive failures, >=50% rolling error rate, or UNAVAILABLE);
        empty when no ``health_source`` is attached."""
        if self.health_source is None:
            return []
        try:
            summaries = self.health_source() or {}
        except Exception:  # noqa: BLE001 — advisory feed, never fatal
            return []
        bad = []
        for label, s in summaries.items():
            if int(s.get("consecutive_failures", 0)) >= 3 \
                    or float(s.get("error_rate", 0.0)) >= 0.5 \
                    or s.get("state") == "UNAVAILABLE":
                bad.append(label)
        return sorted(bad)

    def firing_alerts(self) -> List[str]:
        """Firing scale_up-marked alert rules (the alert feed); empty
        when the feed fails or the alert engine is gated off."""
        try:
            return sorted(self.alert_source() or [])
        except Exception:  # noqa: BLE001 — advisory feed, never fatal
            return []

    def decide(self) -> List[ScaleDecision]:
        """One evaluation pass over every registered slice; returns (and
        dispatches to hooks) the decisions made this pass."""
        p95 = float(self.quantile_source())
        now = self._clock()
        unhealthy = self.unhealthy_workers()
        alerts = self.firing_alerts()
        out: List[ScaleDecision] = []
        for name, info in self.registry.summary().items():
            with self._lock:
                last = self._last_decision.get(name, -1e18)
                in_cooldown = now - last < self.cooldown_s
            if in_cooldown:
                continue
            replicas = info["replicas"]
            decision = None
            if (p95 >= self.up_p95_s or alerts) \
                    and replicas < info["max_replicas"]:
                if p95 >= self.up_p95_s:
                    reason = (f"queue-wait p95 {p95:.2f}s "
                              f">= {self.up_p95_s:.2f}s")
                else:
                    reason = (f"alert {','.join(alerts)} firing "
                              f"(scale-up signal)")
                decision = ScaleDecision(
                    name, "up", reason, p95, replicas + 1)
            elif p95 <= self.down_p95_s and replicas > info["min_replicas"]:
                if unhealthy:
                    # low queue wait with sick workers is not surplus
                    # capacity — hold replicas until the fleet heals
                    continue
                decision = ScaleDecision(
                    name, "down",
                    f"queue-wait p95 {p95:.2f}s <= {self.down_p95_s:.2f}s",
                    p95, replicas - 1)
            if decision is None:
                continue
            self.registry.set_replicas(name, decision.replicas)
            with self._lock:
                self._last_decision[name] = now
                self._decisions.append(decision)
                entry = dict(dataclasses.asdict(decision))
                entry["decided_at"] = time.time()  # audit-log wall clock
                # execution outcome: seeded "no_executor"; an attached
                # executor (fleet/pool.py attach_autoscale) upgrades it
                # to executed/failed via record_execution
                entry["execution"] = {"outcome": "no_executor"}
                self._audit.append(entry)
                self._audit_total += 1
                hooks = list(self._hooks)
            for hook in hooks:  # outside the lock: hooks may re-enter
                hook(decision)
            out.append(decision)
        return out

    def record_execution(self, decision: ScaleDecision, outcome: str,
                         detail: str = "") -> bool:
        """Upgrade a decision's audit entry with its execution outcome
        (``executed`` / ``failed``) once an attached executor (the warm
        pool) has actually spawned or retired capacity. Matches the most
        recent still-``no_executor`` entry for this decision; returns
        False if the ring has already evicted it."""
        want = dataclasses.asdict(decision)
        with self._lock:
            for entry in reversed(self._audit):
                if entry.get("execution", {}).get("outcome") \
                        != "no_executor":
                    continue
                if all(entry.get(k) == v for k, v in want.items()):
                    entry["execution"] = {
                        "outcome": str(outcome),
                        "detail": str(detail),
                        "executed_at": time.time(),
                    }
                    return True
        return False

    def history(self) -> List[ScaleDecision]:
        with self._lock:
            return list(self._decisions)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            decisions = list(self._decisions)
        return {
            "slices": self.registry.summary(),
            "thresholds": {"up_p95_s": self.up_p95_s,
                           "down_p95_s": self.down_p95_s,
                           "cooldown_s": self.cooldown_s},
            "decisions": [dataclasses.asdict(d)
                          for d in list(decisions)[-16:]],
        }

    def audit(self) -> Dict[str, object]:
        """Full bounded audit ring for ``/internal/autoscale`` — every
        retained decision with its wall-clock timestamp, plus how many
        were made overall so a reader can tell when the ring wrapped."""
        with self._lock:
            entries = list(self._audit)
            total = self._audit_total
        return {
            "active": True,
            "slices": self.registry.summary(),
            "thresholds": {"up_p95_s": self.up_p95_s,
                           "down_p95_s": self.down_p95_s,
                           "cooldown_s": self.cooldown_s},
            "capacity": self._audit_cap,
            "decisions_total": total,
            "decisions": entries,
            "unhealthy_workers": self.unhealthy_workers(),
            "firing_alerts": self.firing_alerts(),
        }


# -- module-level active engine (server/api.py reads it) -------------------

_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[AutoscaleEngine] = None  # guarded-by: _ACTIVE_LOCK


def set_autoscale(engine: Optional[AutoscaleEngine]) -> None:
    """Install ``engine`` as the process-wide autoscaler (last one wins);
    ``AutoscaleEngine.__init__`` calls this automatically."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = engine


def get_autoscale() -> Optional[AutoscaleEngine]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def _default_quantile_source() -> float:
    """Worst per-class p95 of the fleet queue-wait histograms — the
    autoscaler keys on the most-starved class, not the average. With
    SDTPU_FEDERATION on, the federated worst-of-fleet p95 folds in, so
    the scale signal is fleet-wide rather than node-local."""
    from stable_diffusion_webui_distributed_tpu.obs import (
        prometheus as obs_prom,
    )

    local = obs_prom.fleet_queue_wait_p95()
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            federation as obs_fed,
        )

        if obs_fed.enabled():
            return max(local, obs_fed.fleet_queue_wait_p95())
    except Exception:  # noqa: BLE001 — the scale signal stays node-local
        pass
    return local


def _default_alert_source() -> List[str]:
    """Firing scale_up-marked alert rules ([] with SDTPU_ALERTS off)."""
    from stable_diffusion_webui_distributed_tpu.obs import (
        alerts as obs_alerts,
    )

    return obs_alerts.scale_up_firing()

"""Per-tenant token-bucket quotas (images as the metered unit).

Every admitted request withdraws ``total_images`` tokens from its
tenant's bucket; buckets refill continuously at ``SDTPU_QUOTA_IPM``
images per minute up to a burst ceiling of ``SDTPU_QUOTA_BURST`` tokens.
An empty bucket throttles the request — the dispatcher surfaces that as
HTTP 429 with a ``Retry-After`` derived from the refill rate — so one
flooding tenant cannot crowd the fleet out from under everyone else
(the paper's per-worker pixel-cap guard, generalized to request rate).

``SDTPU_QUOTA_IPM`` unset or <= 0 disables metering entirely (the
default — single-tenant deployments pay nothing).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

DEFAULT_BURST = 8.0


class TokenBucket:
    """Classic continuous-refill token bucket (rate in tokens/second)."""

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate = max(0.0, float(rate))
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst  # guarded-by: _lock
        self._stamp = clock()  # guarded-by: _lock

    def try_take(self, n: float) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._stamp) * self.rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refund(self, n: float) -> None:
        """Return ``n`` previously-taken tokens (capped at the burst
        ceiling) — for withdrawals whose request was never admitted."""
        with self._lock:
            self._tokens = min(self.burst, self._tokens + max(0.0, n))

    def retry_after(self, n: float) -> float:
        """Seconds until ``n`` tokens will be available (0 if now)."""
        with self._lock:
            now = self._clock()
            tokens = min(self.burst,
                         self._tokens + (now - self._stamp) * self.rate)
            if tokens >= n or self.rate <= 0:
                return 0.0
            return (n - tokens) / self.rate

    def available(self) -> float:
        with self._lock:
            now = self._clock()
            return min(self.burst,
                       self._tokens + (now - self._stamp) * self.rate)


class QuotaLedger:
    """Tenant -> bucket registry; buckets are created on first sight."""

    def __init__(self, images_per_minute: float = 0.0,
                 burst: Optional[float] = None,
                 clock=time.monotonic) -> None:
        self.rate = max(0.0, float(images_per_minute)) / 60.0
        self.burst = DEFAULT_BURST if burst is None else max(1.0, burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}  # guarded-by: _lock
        self._throttled = 0  # guarded-by: _lock
        self._admitted = 0  # guarded-by: _lock

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    @classmethod
    def from_env(cls, clock=time.monotonic) -> "QuotaLedger":
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            env_float,
        )

        return cls(images_per_minute=env_float("SDTPU_QUOTA_IPM", 0.0),
                   burst=env_float("SDTPU_QUOTA_BURST", DEFAULT_BURST),
                   clock=clock)

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = TokenBucket(self.rate, self.burst, self._clock)
                self._buckets[tenant] = b
            return b

    def admit(self, tenant: str, images: int) -> Optional[float]:
        """None = admitted; a float = throttled, retry after that many
        seconds. Disabled metering admits everything for free."""
        if not self.enabled:
            return None
        b = self._bucket(tenant)
        if b.try_take(float(images)):
            with self._lock:
                self._admitted += 1
            return None
        with self._lock:
            self._throttled += 1
        return max(1.0, b.retry_after(float(images)))

    def refund(self, tenant: str, images: int) -> None:
        """Give back tokens withdrawn for a request that was rejected
        after the quota check (e.g. by SLO admission): tenants are charged
        only for work the fleet actually accepted, and cannot be
        quota-throttled by their own rejected requests."""
        if not self.enabled:
            return
        self._bucket(tenant).refund(float(images))

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "images_per_minute": self.rate * 60.0,
                "burst": self.burst,
                "tenants": {t: round(b.available(), 3)
                            for t, b in self._buckets.items()},
                "admitted": self._admitted,
                "throttled": self._throttled,
            }

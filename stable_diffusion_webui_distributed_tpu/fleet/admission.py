"""ETA-SLO admission control: accept, degrade, or reject at the door.

Before a request is ever bucketed or queued, its completion time is
predicted with the benchmark-calibrated ETA model (scheduler/eta.py) plus
the serving layer's observed queue wait and padding overhead
(``ServingDispatcher.eta_overhead``), corrected by the live process-wide
MPE gauge (``sdtpu_eta_mpe_percent``). A prediction inside the class SLO
is admitted untouched. One that misses is *degraded* first — the
step-cache cadence ladder and a few-step budget are auto-applied, the
same knobs a user could set by hand (pipeline/stepcache.py) — and only
rejected with 429 when no degrade rung fits either.

Degrade cost model: a cached (reuse) step prices at ~45% of a full UNet
eval on the XLA cost-analysis grid (tools/flops_report.py), so cadence
``c`` scales the compute part of the ETA by ``1/c + (1 - 1/c) * 0.45``.
Queue wait is latency, not compute — it is never rescaled.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from stable_diffusion_webui_distributed_tpu.fleet.policy import ClassPolicy

#: relative cost of a deep-feature-reuse step vs a full eval (the
#: rows-proportional pricing the FLOPs report pins; see module docstring)
REUSE_STEP_COST = 0.45
#: degrade rungs tried in order: step-cache cadence, then cadence + the
#: few-step budget (SDTPU_FLEET_FEWSTEP)
CADENCE_RUNGS = (2, 3)
DEFAULT_FEWSTEP = 12


class FleetRejected(Exception):
    """Raised by the dispatcher when admission control refuses a request;
    the API layer maps it to HTTP 429 + Retry-After."""

    def __init__(self, reason: str, detail: str,
                 retry_after: float = 1.0) -> None:
        super().__init__(detail)
        self.reason = reason        # "slo" | "quota"
        self.detail = detail
        self.retry_after = max(1.0, float(retry_after))


@dataclasses.dataclass
class AdmissionDecision:
    action: str                      # "accept" | "degrade" | "reject"
    predicted_s: Optional[float] = None
    slo_s: Optional[float] = None
    #: payload mutations applied on degrade (override_settings additions
    #: and/or a reduced step count)
    overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    steps: Optional[int] = None
    detail: str = ""


def cadence_speedup(cadence: int) -> float:
    """Compute-time multiplier for step-cache cadence ``c`` (< 1)."""
    c = max(1, int(cadence))
    return 1.0 / c + (1.0 - 1.0 / c) * REUSE_STEP_COST


class AdmissionController:
    """Per-dispatcher admission policy. Stateless between calls except for
    the calibration handle — safe to share across handler threads."""

    def __init__(self, calibration=None, benchmark=None,
                 fewstep: Optional[int] = None) -> None:
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            env_int,
        )

        self.calibration = calibration  # scheduler.eta.EtaCalibration
        self.benchmark = benchmark
        self.fewstep = env_int("SDTPU_FLEET_FEWSTEP", DEFAULT_FEWSTEP) \
            if fewstep is None else fewstep

    def decide(self, payload, policy: ClassPolicy,
               overhead: Optional[Dict[str, float]] = None
               ) -> AdmissionDecision:
        """Admission verdict for ``payload`` under ``policy``'s SLO. The
        caller applies ``overrides``/``steps`` on degrade and raises
        :class:`FleetRejected` on reject."""
        from stable_diffusion_webui_distributed_tpu.scheduler import eta

        slo = policy.slo_s
        cal = self.calibration
        if slo is None or cal is None or not cal.benchmarked:
            # no SLO, or no calibration evidence yet: admission cannot
            # reason about time — let the request through untouched
            return AdmissionDecision("accept", slo_s=slo)

        overhead = overhead or {}
        wait = float(overhead.get("queue_wait", 0.0))
        pad = float(overhead.get("padding_overhead", 1.0))

        # a request that already asks for a reduced precision is predicted
        # at that precision's speed (payload channel; pipeline/precision.py)
        from stable_diffusion_webui_distributed_tpu.pipeline import (
            precision as precision_mod,
        )

        requested_prec = precision_mod.resolve(payload).name

        def predict(steps: Optional[int] = None) -> float:
            return eta.admission_eta(
                cal, payload, benchmark=self.benchmark, steps=steps,
                queue_wait=wait, padding_overhead=pad,
                precision=requested_prec)

        predicted = predict()
        if predicted <= slo:
            return AdmissionDecision("accept", predicted, slo)

        # degrade ladder: compute part scales, queue wait does not
        compute = max(0.0, predicted - wait)
        existing_cadence = int(
            (payload.override_settings or {}).get("deepcache", 1) or 1)
        for cadence in CADENCE_RUNGS:
            if cadence <= existing_cadence:
                continue
            scaled = compute * cadence_speedup(cadence) + wait
            if scaled <= slo:
                return AdmissionDecision(
                    "degrade", scaled, slo,
                    overrides={"deepcache": cadence},
                    detail=f"step-cache cadence {cadence} applied to meet "
                           f"{slo:.1f}s SLO")
        # next rung: deepest cadence + the few-step budget
        cadence = CADENCE_RUNGS[-1]
        few = self.fewstep
        if few and 0 < few < payload.steps:
            scaled = max(0.0, predict(steps=few) - wait) \
                * cadence_speedup(cadence) + wait
            if scaled <= slo:
                return AdmissionDecision(
                    "degrade", scaled, slo,
                    overrides={"deepcache": cadence}, steps=few,
                    detail=f"few-step budget {few} + cadence {cadence} "
                           f"applied to meet {slo:.1f}s SLO")

        # final rung before reject: the int8 serving precision stacked on
        # cadence + few-step (pipeline/precision.py). The compute part
        # scales by the calibration's per-precision factor (learned from
        # int8's OWN samples, prior ~0.55); a request already asking for
        # a non-bf16 precision has nothing left to give here. Quality
        # stays inside the tier-1 PSNR/SSIM floors (test_quality_int8).
        int8_factor = cal.precision_factor("int8")
        if requested_prec == "bf16" and int8_factor < 1.0:
            steps_arg = few if few and 0 < few < payload.steps else None
            scaled = max(0.0, predict(steps=steps_arg) - wait) \
                * cadence_speedup(cadence) * int8_factor + wait
            if scaled <= slo:
                overrides = {"deepcache": cadence, "precision": "int8"}
                return AdmissionDecision(
                    "degrade", scaled, slo,
                    overrides=overrides, steps=steps_arg,
                    detail=f"int8 precision + cadence {cadence}"
                           + (f" + few-step budget {steps_arg}"
                              if steps_arg else "")
                           + f" applied to meet {slo:.1f}s SLO")

        return AdmissionDecision(
            "reject", predicted, slo,
            detail=f"predicted {predicted:.1f}s exceeds the "
                   f"{policy.name} SLO of {slo:.1f}s at every degrade rung")

"""Multi-tenant fleet tier above the serving dispatcher.

- :mod:`.policy` — priority classes, weighted-fair queueing with aging,
  the device gate, and chunk-boundary preemption hooks;
- :mod:`.quotas` — per-tenant token-bucket admission quotas;
- :mod:`.admission` — ETA-SLO accept / degrade / reject control;
- :mod:`.slices` — slice registry + queue-wait-driven autoscale signals.

Everything is host-side policy over the existing engine/dispatcher
machinery; ``SDTPU_FLEET=0`` (the default) keeps the whole tier inert
and the serving path byte-identical to the pre-fleet build.
"""

from stable_diffusion_webui_distributed_tpu.fleet.admission import (
    AdmissionController,
    AdmissionDecision,
    FleetRejected,
)
from stable_diffusion_webui_distributed_tpu.fleet.policy import (
    BATCH,
    BEST_EFFORT,
    INTERACTIVE,
    ClassPolicy,
    EnginePreemptHook,
    FleetGate,
    FleetPolicy,
    GateEntry,
    WeightedFairQueue,
    fleet_enabled,
)
from stable_diffusion_webui_distributed_tpu.fleet.quotas import (
    QuotaLedger,
    TokenBucket,
)
from stable_diffusion_webui_distributed_tpu.fleet.slices import (
    AutoscaleEngine,
    ScaleDecision,
    SliceInfo,
    SliceRegistry,
)

__all__ = [
    "AdmissionController", "AdmissionDecision", "FleetRejected",
    "BATCH", "BEST_EFFORT", "INTERACTIVE", "ClassPolicy",
    "EnginePreemptHook", "FleetGate", "FleetPolicy", "GateEntry",
    "WeightedFairQueue", "fleet_enabled",
    "QuotaLedger", "TokenBucket",
    "AutoscaleEngine", "ScaleDecision", "SliceInfo", "SliceRegistry",
]

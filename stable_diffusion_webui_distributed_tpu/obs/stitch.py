"""Cross-node trace stitching: one timeline for a fan-out request.

Each node's span store timestamps events against its own private
``perf_counter`` epoch (obs/spans.py ``_EPOCH``), so a master trace and a
remote worker's trace cannot be overlaid directly. This module pulls each
remote's ``/internal/trace.json`` through the worker's existing HTTP
session, estimates the remote trace clock's offset from the fetch RTT
(NTP-style: the remote's ``clock_us`` sample is assumed to land at the
midpoint of the request), shifts every remote event onto the master
clock, retags its ``pid`` with the worker label, and merges everything
into one Chrome trace — a single Perfetto timeline showing the master's
dispatch spans above each worker's generate spans.

Correlation across nodes is free: outbound jobs carry
``X-SDTPU-Request-Id`` (scheduler/worker.py ``HTTPBackend.generate``), so
the remote roots its trace under the same request id and the merged
events share ``args.request_id``.

Pull-based and on-demand (``GET /internal/stitched-trace.json``) — no
background threads, nothing on the hot path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..runtime.config import env_float
from . import spans

#: Per-remote fetch timeout (seconds); a dead worker must not hang the
#: stitched export.
FETCH_TIMEOUT_S = 5.0


def http_timeout_s(default: float = FETCH_TIMEOUT_S) -> float:
    """The obs-plane-wide outbound HTTP timeout (SDTPU_OBS_HTTP_TIMEOUT_S).

    Every outbound call the observability plane makes — trace stitching,
    federation polls, webhook delivery, the heartbeat prober — resolves
    its timeout here, so one knob bounds how long a hung remote can stall
    any of them. Floored at 0.05s so a typo cannot disable the bound."""
    t = env_float("SDTPU_OBS_HTTP_TIMEOUT_S", default)
    return max(0.05, float(t if t is not None else default))


def _workers_of(source: Any) -> List[Any]:
    """Accept a World (``.workers``) or a plain iterable of workers."""
    ws = getattr(source, "workers", None)
    if ws is None:
        ws = source or []
    return list(ws)


def fetch_remote_trace(backend: Any,
                       timeout: Optional[float] = None,
                       ) -> Tuple[Dict[str, Any], float, float]:
    """GET a remote's /internal/trace.json through its session; returns
    (document, t0_us, t1_us) with the local trace-clock fetch bracket."""
    if timeout is None:
        timeout = http_timeout_s()
    scheme = "https" if getattr(backend, "tls", False) else "http"
    url = (f"{scheme}://{backend.address}:{backend.port}"
           f"/internal/trace.json")
    t0 = spans.now_us()
    resp = backend.session.get(url, timeout=timeout)
    t1 = spans.now_us()
    resp.raise_for_status()
    return resp.json(), t0, t1


def clock_offset_us(doc: Dict[str, Any], t0_us: float,
                    t1_us: float) -> Tuple[float, float]:
    """(offset, rtt) in µs: add ``offset`` to a remote ``ts`` to place it
    on the local trace clock. The remote's ``clock_us`` sample is taken to
    correspond to the RTT midpoint."""
    remote = float(doc.get("clock_us") or 0.0)
    rtt = max(0.0, t1_us - t0_us)
    midpoint = t0_us + rtt / 2.0
    return midpoint - remote, rtt


def merge_remote(events: List[Dict[str, Any]], doc: Dict[str, Any],
                 label: str, offset_us: float) -> int:
    """Shift one remote document's events onto the local clock and append
    them, retagged with ``pid="worker:<label>"``; returns how many."""
    remote_events = doc.get("traceEvents") or []
    for ev in remote_events:
        ev = dict(ev)
        ev["ts"] = float(ev.get("ts", 0.0)) + offset_us
        ev["pid"] = f"worker:{label}"
        events.append(ev)
    return len(remote_events)


def stitch(source: Any,
           tracer: Optional[spans.SpanTracer] = None) -> Dict[str, Any]:
    """The merged master+remotes Chrome trace document. ``source`` is a
    World (or any iterable of workers); workers without an HTTP backend
    (stubs, in-process) contribute nothing, unreachable remotes are
    reported in ``nodes`` rather than failing the export."""
    tracer = tracer or spans.TRACER
    base = tracer.export_chrome()
    events: List[Dict[str, Any]] = list(base.get("traceEvents") or [])
    nodes: List[Dict[str, Any]] = [{
        "node": "master", "events": len(events),
        "offset_us": 0.0, "rtt_us": 0.0, "error": None,
    }]
    for w in _workers_of(source):
        backend = getattr(w, "backend", None)
        label = getattr(w, "label", "?")
        if backend is None or not hasattr(backend, "session") \
                or not getattr(backend, "address", None):
            continue
        node = {"node": f"worker:{label}", "events": 0,
                "offset_us": 0.0, "rtt_us": 0.0, "error": None}
        try:
            doc, t0, t1 = fetch_remote_trace(backend)
            offset, rtt = clock_offset_us(doc, t0, t1)
            node["offset_us"] = offset
            node["rtt_us"] = rtt
            node["events"] = merge_remote(events, doc, label, offset)
        except Exception as e:  # noqa: BLE001 — per-node fault isolation
            node["error"] = f"{type(e).__name__}: {e}"
        nodes.append(node)
    events.sort(key=lambda ev: float(ev.get("ts", 0.0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "clock_us": spans.now_us(), "nodes": nodes}

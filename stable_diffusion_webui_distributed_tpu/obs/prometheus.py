"""Prometheus text exposition (format 0.0.4) — no client library needed.

Four fixed-ladder latency histograms give real p50/p95/p99 where
``StageStats`` only has rolling means:

- ``sdtpu_request_e2e_seconds`` — full request latency (obs/spans.py
  observes it when a request context closes);
- ``sdtpu_queue_wait_seconds`` — coalesce-queue wait (dispatcher);
- ``sdtpu_device_dispatch_seconds`` — denoise-chunk device time
  (fed from ``StageStats.timer("denoise_chunk")`` via
  :func:`observe_stage`);
- ``sdtpu_decode_seconds`` — VAE decode dispatch + fetch.

:func:`render` additionally exposes every ``DispatchMetrics`` and
``StageStats`` scalar plus the live ETA mean-percent-error gauge
(:data:`ETA_GAUGE`, fed by ``scheduler/eta.record_eta_error``), so
``/internal/metrics`` is a strict superset of ``/internal/status``'s
numbers in scrapeable form.
"""

from __future__ import annotations

import bisect
import re
import threading
from collections import deque
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

#: Fixed bucket ladder (seconds). Spans sub-ms host work up to the minutes
#: an XLA compile can take; identical for every histogram so dashboards can
#: aggregate across them.
BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 120.0)


def _fmt(v: Any) -> str:
    """Prometheus sample value: ints bare, floats via repr, None -> NaN."""
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: Longest label value exposed; tenant/class names are user-supplied and a
#: kilobyte tenant string must not bloat every scrape.
_MAX_LABEL_LEN = 100


def sanitize_label_value(v: Any) -> str:
    """User-supplied label values (tenant names, fleet classes, precision
    aliases) made exposition-safe: C0 control characters and DEL are
    dropped (``\\n`` survives — it escapes losslessly), then the value is
    truncated. Escaping alone is NOT enough: a ``\\r`` would survive the
    0.0.4 escape rules verbatim and split the sample line."""
    s = str(v)
    s = "".join(ch for ch in s if (ord(ch) >= 32 or ch == "\n")
                and ord(ch) != 127)
    return s[:_MAX_LABEL_LEN]


def _label(v: Any) -> str:
    s = sanitize_label_value(v)
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


# -- metric registry ---------------------------------------------------------

#: Legal metric-family name: the Prometheus exposition grammar. The
#: ``sdtpu_`` prefix discipline is lexical (OB002 flags prefixed literals
#: outside this module), not a registry constraint — tests register
#: throwaway families under other names.
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

_REGISTRY_LOCK = threading.Lock()
#: family name -> (type, help). Every family this module exposes is
#: declared here; lint rule OB002 (analysis/metricrules.py) forbids ad-hoc
#: ``sdtpu_``-prefixed metric-name strings anywhere else in the package,
#: so this registry IS the metric namespace.
_REGISTRY: Dict[str, Tuple[str, str]] = {}  # guarded-by: _REGISTRY_LOCK


class MetricRegistrationError(ValueError):
    """Bad metric name, bad type, or a name re-registered as a different
    type (two families colliding on one name corrupts the exposition)."""


def register_metric(name: str, mtype: str, help_text: str) -> str:
    """Declare (idempotently) a metric family; returns the name so call
    sites can use it inline. The single sanctioned way to mint a
    ``sdtpu_*`` metric name (OB002)."""
    if not _NAME_RE.match(name):
        raise MetricRegistrationError(
            f"metric name {name!r} must match {_NAME_RE.pattern}")
    if mtype not in ("counter", "gauge", "histogram"):
        raise MetricRegistrationError(
            f"metric type {mtype!r} must be counter/gauge/histogram")
    with _REGISTRY_LOCK:
        prev = _REGISTRY.get(name)
        if prev is not None and prev[0] != mtype:
            raise MetricRegistrationError(
                f"metric {name} already registered as {prev[0]}, "
                f"not {mtype}")
        _REGISTRY[name] = (mtype, help_text)
    return name


def registered_metrics() -> Dict[str, Tuple[str, str]]:
    """Snapshot of the declared families (name -> (type, help))."""
    with _REGISTRY_LOCK:
        return dict(_REGISTRY)


def _bucket_label(b: float) -> str:
    return _fmt(b) if b != int(b) else f"{b:.1f}"


class Histogram:
    """Thread-safe fixed-bucket histogram (cumulative ``le`` exposition)."""

    def __init__(self, name: str, help_text: str,
                 buckets: Iterable[float] = BUCKETS,
                 labels: str = "") -> None:
        self.name = register_metric(name, "histogram", help_text)
        self.help = help_text
        #: pre-rendered label body (e.g. ``class="interactive"``) merged
        #: into every sample; HELP/TYPE are emitted by the caller when a
        #: labeled family has several instances
        self.labels = labels
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts: List[int] = [0] * (len(self.bounds) + 1)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def observe(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def clear(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. +Inf overflow, sum, count)."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the q-quantile (0 when empty)."""
        counts, _total, n = self.snapshot()
        if n <= 0:
            return 0.0
        target = q * n
        running = 0
        for i, c in enumerate(counts):
            running += c
            if running >= target:
                return self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1]
        return self.bounds[-1]

    def render(self, header: bool = True) -> List[str]:
        counts, total, n = self.snapshot()
        lines = []
        if header:
            lines += [f"# HELP {self.name} {self.help}",
                      f"# TYPE {self.name} histogram"]
        pre = f"{self.labels}," if self.labels else ""
        suf = f"{{{self.labels}}}" if self.labels else ""
        running = 0
        for bound, c in zip(self.bounds, counts):
            running += c
            lines.append(f'{self.name}_bucket{{{pre}le='
                         f'"{_bucket_label(bound)}"}} {running}')
        lines.append(f'{self.name}_bucket{{{pre}le="+Inf"}} {n}')
        lines.append(f"{self.name}_sum{suf} {_fmt(total)}")
        lines.append(f"{self.name}_count{suf} {n}")
        return lines


HISTOGRAMS: Dict[str, Histogram] = {
    "e2e": Histogram(
        "sdtpu_request_e2e_seconds",
        "End-to-end request latency (span-root duration)."),
    "queue_wait": Histogram(
        "sdtpu_queue_wait_seconds",
        "Time a request waited in the coalesce queue before its device "
        "dispatch."),
    "device_dispatch": Histogram(
        "sdtpu_device_dispatch_seconds",
        "Denoise-chunk device dispatch latency (host-observed)."),
    "decode": Histogram(
        "sdtpu_decode_seconds",
        "VAE decode latency (dispatch + fetch halves observed "
        "separately)."),
    "lora_apply": Histogram(
        "sdtpu_lora_apply_seconds",
        "LoRA adapter activation latency: traced factor-set builds "
        "(SDTPU_LORA_TRACED, host-side padding/bucketing only — zero "
        "merges, zero recompiles) observed per build."),
    "cold_start": Histogram(
        "sdtpu_cold_start_seconds",
        "Fresh-engine time to first served image (AOT bench and warm "
        "pool spawns, serving/aot.py + fleet/pool.py)."),
}

#: StageStats stage name -> histogram key (stages not listed only appear as
#: ``sdtpu_stage_seconds`` gauges).
STAGE_TO_HIST: Dict[str, str] = {
    "denoise_chunk": "device_dispatch",
    "vae_decode_dispatch": "decode",
    "vae_decode_fetch": "decode",
}


def observe_hist(name: str, value: float) -> None:
    h = HISTOGRAMS.get(name)
    if h is not None:
        h.observe(value)


def observe_lora_apply(seconds: float) -> None:
    """One traced factor-set build (``Engine._traced_set_for`` cache
    miss): the full host cost of an adapter activation on the traced
    path — the merged path's equivalent is a param-tree merge plus a
    recompile, which this histogram exists to show the absence of."""
    HISTOGRAMS["lora_apply"].observe(seconds)


def observe_stage(stage: str, seconds: float) -> None:
    key = STAGE_TO_HIST.get(stage)
    if key is not None:
        HISTOGRAMS[key].observe(seconds)


def clear_histograms() -> None:
    for h in HISTOGRAMS.values():
        h.clear()
    with _FLEET_LOCK:
        _FLEET_QUEUE_WAIT.clear()
    with _COMPILE_LOCK:
        _COMPILE_LAT.clear()
    with _AOT_LOAD_LOCK:
        _AOT_LOAD_LAT.clear()
    with _STAGE_GRAPH_LOCK:
        _STAGE_GRAPH_LAT.clear()
    for c in FLEET_COUNTERS.values():
        c.clear()
    PRECISION_COUNTER.clear()
    LORA_SWITCH_COUNTER.clear()
    AOT_COUNTER.clear()
    for c in WORKER_COUNTERS.values():
        c.clear()
    WATCHDOG_COUNTER.clear()
    CACHE_COUNTER.clear()
    SIM_FAULT_COUNTER.clear()
    ALERT_COUNTER.clear()
    NOTIFY_COUNTER.clear()
    with _ALERT_LOCK:
        _ALERT_STATE.clear()
    set_sim_slo_burn(None)
    with _WORKER_LOCK:
        _WORKER_LATENCY_EWMA.clear()


# -- compile latency (pipeline/engine.py via obs/perf.py) --------------------

_COMPILE_LOCK = threading.Lock()
#: per-stage-kind compile-latency histograms, created on first build
_COMPILE_LAT: Dict[str, Histogram] = {}  # guarded-by: _COMPILE_LOCK


def observe_compile(kind: str, seconds: float) -> None:
    """One compiled-stage build's latency (``Engine._cached`` reports it
    through the perf ledger; gated there on ``SDTPU_PERF``)."""
    with _COMPILE_LOCK:
        h = _COMPILE_LAT.get(kind)
        if h is None:
            h = Histogram(
                "sdtpu_compile_seconds",
                "XLA stage-build (compile) latency by stage kind.",
                labels=f'kind="{_label(kind)}"')
            _COMPILE_LAT[kind] = h
    h.observe(seconds)


# -- AOT executable artifacts (serving/aot.py) -------------------------------

_AOT_LOAD_LOCK = threading.Lock()
#: per-stage-kind artifact-deserialize latency, created on first load.
#: A SIBLING of sdtpu_compile_seconds, never the same family: MFU/ledger
#: analysis must not mistake a 200ms deserialize for a real compile.
_AOT_LOAD_LAT: Dict[str, Histogram] = {}  # guarded-by: _AOT_LOAD_LOCK


def observe_aot_load(kind: str, seconds: float) -> None:
    """One artifact deserialize's latency by stage kind (the cheap
    hydration that replaces a fresh compile on an AOT hit)."""
    with _AOT_LOAD_LOCK:
        h = _AOT_LOAD_LAT.get(kind)
        if h is None:
            h = Histogram(
                "sdtpu_aot_load_seconds",
                "AOT artifact deserialize latency by stage kind.",
                labels=f'kind="{_label(kind)}"')
            _AOT_LOAD_LAT[kind] = h
    h.observe(seconds)


def observe_cold_start(seconds: float) -> None:
    """One fresh engine's time-to-first-image (bench arms, pool spawns)."""
    HISTOGRAMS["cold_start"].observe(seconds)


# -- stage-graph executor (parallel/stage_graph.py) --------------------------

_STAGE_GRAPH_LOCK = threading.Lock()
#: per-stage-node host latency histograms, created on first observation.
#: Family name is sdtpu_stage_graph_seconds, NOT the sdtpu_stage_seconds
#: the issue sketch suggested: that family is already registered as a
#: GAUGE (StageStats rolling stats above) and register_metric enforces
#: one type per name — a histogram re-registration would raise.
_STAGE_GRAPH_LAT: Dict[str, Histogram] = {}  # guarded-by: _STAGE_GRAPH_LOCK


def observe_stage_graph(stage: str, seconds: float) -> None:
    """One stage-graph node's host interval (encode / denoise dispatch /
    decode dispatch / merge fetch), labeled by stage name."""
    with _STAGE_GRAPH_LOCK:
        h = _STAGE_GRAPH_LAT.get(stage)
        if h is None:
            h = Histogram(
                "sdtpu_stage_graph_seconds",
                "Stage-graph node host seconds by stage.",
                labels=f'stage="{_label(stage)}"')
            _STAGE_GRAPH_LAT[stage] = h
    h.observe(seconds)


# -- fleet tier (fleet/ package) --------------------------------------------

class LabeledCounter:
    """Thread-safe counter family with a fixed label-name tuple."""

    def __init__(self, name: str, help_text: str,
                 label_names: Tuple[str, ...]) -> None:
        self.name = register_metric(name, "counter", help_text)
        self.help = help_text
        self.label_names = label_names
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, ...], float] = {}  # guarded-by: _lock

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        key = tuple(str(labels.get(ln, "")) for ln in self.label_names)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0.0) + float(n)

    def value(self, **labels: Any) -> float:
        key = tuple(str(labels.get(ln, "")) for ln in self.label_names)
        with self._lock:
            return self._counts.get(key, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._counts.values())

    def snapshot(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._counts = {}

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        for key in sorted(self.snapshot()):
            body = ",".join(f'{ln}="{_label(v)}"'
                            for ln, v in zip(self.label_names, key))
            lines.append(f"{self.name}{{{body}}} "
                         f"{_fmt(self.snapshot()[key])}")
        return lines


#: Fleet-tier counter families (fleet/policy.py, fleet/admission.py and
#: the dispatcher feed these; /internal/metrics renders them).
FLEET_COUNTERS: Dict[str, LabeledCounter] = {
    "admissions": LabeledCounter(
        "sdtpu_fleet_admissions_total",
        "Admission decisions by class and outcome "
        "(accept/degrade/reject).", ("class", "decision")),
    "quota_throttles": LabeledCounter(
        "sdtpu_fleet_quota_throttles_total",
        "Requests throttled by per-tenant token-bucket quotas.",
        ("tenant",)),
    "preemptions": LabeledCounter(
        "sdtpu_fleet_preemptions_total",
        "Chunk-boundary device yields by the preempted job's class.",
        ("class",)),
    "requests": LabeledCounter(
        "sdtpu_fleet_requests_total",
        "Requests entering the fleet gate by tenant and class.",
        ("tenant", "class")),
}

#: Device dispatches by resolved serving precision (pipeline/precision.py;
#: the dispatcher counts one increment per device batch, weighted by the
#: requests it carried via :func:`count_precision`).
PRECISION_COUNTER = LabeledCounter(
    "sdtpu_dispatch_precision_total",
    "Requests dispatched to the device by resolved serving precision.",
    ("precision",))

#: Adapter-set activations by serving mode: ``merged`` — host merge into
#: the param tree (epoch bump, caches retired); ``traced`` — factor set
#: installed as jit arguments (SDTPU_LORA_TRACED, no merge, no epoch
#: bump). The engine feeds this through :func:`count_lora_switch`.
LORA_SWITCH_COUNTER = LabeledCounter(
    "sdtpu_lora_switch_total",
    "LoRA adapter-set switches by serving mode (merged/traced).",
    ("mode",))


def count_lora_switch(mode: str, n: float = 1.0) -> None:
    """One adapter-set switch: ``mode`` is ``merged`` (host merge path)
    or ``traced`` (recompile-free traced path)."""
    LORA_SWITCH_COUNTER.inc(n, mode=mode)


#: AOT artifact-store events by outcome: ``hit`` (executable
#: deserialized), ``miss`` (no cell — fresh compile), ``saved`` (fresh
#: compile persisted back), ``fallback`` (cell present but
#: fingerprint-mismatched or corrupt — compiled instead, journaled as
#: ``aot_fallback``). Fed by serving/aot.py through :func:`aot_count`.
AOT_COUNTER = LabeledCounter(
    "sdtpu_aot_total",
    "AOT executable artifact events (SDTPU_AOT) by outcome.",
    ("outcome",))


def aot_count(outcome: str, n: float = 1.0) -> None:
    AOT_COUNTER.inc(n, outcome=outcome)

# -- scheduler tier (scheduler/worker.py health + obs/watchdog.py) -----------

#: Worker-health counter families (WorkerNode.health and World._requeue
#: feed these; /internal/metrics renders them).
WORKER_COUNTERS: Dict[str, LabeledCounter] = {
    "requests": LabeledCounter(
        "sdtpu_worker_requests_total",
        "Generation requests sent to each worker backend.", ("worker",)),
    "failures": LabeledCounter(
        "sdtpu_worker_failures_total",
        "Failed generation requests per worker.", ("worker",)),
    "requeued_images": LabeledCounter(
        "sdtpu_worker_requeued_images_total",
        "Images requeued away from a failed worker.", ("worker",)),
    "transitions": LabeledCounter(
        "sdtpu_worker_state_transitions_total",
        "Worker state-machine transitions by destination state.",
        ("worker", "to")),
}

#: Stall detections by the hang watchdog (obs/watchdog.py), labeled with
#: the watched operation's name (job-<worker> / dispatch.device).
WATCHDOG_COUNTER = LabeledCounter(
    "sdtpu_watchdog_stalls_total",
    "Dispatches or remote jobs that exceeded k x their ETA "
    "(SDTPU_WATCHDOG_FACTOR).", ("name",))

# -- caching tier (cache/: embed dedupe, result dedupe, prefix sharing) ------

#: Cache events by layer (embed_pos/embed_neg/result/prefix) and outcome
#: (hit/miss/joined/resumed/captured). The cache modules feed this through
#: :func:`cache_count`; /internal/metrics and /internal/cache render it.
CACHE_COUNTER = LabeledCounter(
    "sdtpu_cache_events_total",
    "Caching-tier events (SDTPU_CACHE) by layer and outcome.",
    ("layer", "outcome"))


def cache_count(layer: str, outcome: str, n: float = 1.0) -> None:
    """One caching-tier event: ``layer`` names the cache (embed_pos,
    embed_neg, result, prefix), ``outcome`` what happened there (hit,
    miss, joined, resumed, captured)."""
    CACHE_COUNTER.inc(n, layer=layer, outcome=outcome)


# -- alerting plane (obs/alerts.py state machine) ----------------------------

#: Alert state transitions by rule and state (firing / resolved); the
#: alert engine feeds this through :func:`alert_count`.
ALERT_COUNTER = LabeledCounter(
    "sdtpu_alerts_total",
    "Alert state transitions (SDTPU_ALERTS) by rule and state.",
    ("rule", "state"))

_ALERT_LOCK = threading.Lock()
#: rule name -> 1.0 while firing, 0.0 after resolve; absent until the
#: rule's first transition (the family renders only what happened).
_ALERT_STATE: Dict[str, float] = {}  # guarded-by: _ALERT_LOCK


def alert_count(rule: str, state: str, n: float = 1.0) -> None:
    ALERT_COUNTER.inc(n, rule=rule, state=state)


def set_alert_state(rule: str, value: float) -> None:
    with _ALERT_LOCK:
        _ALERT_STATE[str(rule)] = float(value)


def alert_states() -> Dict[str, float]:
    with _ALERT_LOCK:
        return dict(_ALERT_STATE)


#: Webhook delivery outcomes (sent / failed / deduped / dropped) from
#: obs/notify.py, by channel (severity route; "default" for the single
#: SDTPU_NOTIFY_URL channel). Zero families with no route configured.
NOTIFY_COUNTER = LabeledCounter(
    "sdtpu_notify_total",
    "Alert notification delivery outcomes (SDTPU_NOTIFY_URL / "
    "SDTPU_NOTIFY_ROUTES) by channel and outcome.",
    ("channel", "outcome"))


def notify_count(outcome: str, n: float = 1.0,
                 channel: str = "default") -> None:
    NOTIFY_COUNTER.inc(n, channel=channel, outcome=outcome)


# -- scenario engine (sim/: chaos injection + SLO scoring) -------------------

#: Chaos faults actually delivered by sim/chaos.py, by fault kind
#: (kill / stall / slow / http_error). Zero outside scenario runs.
SIM_FAULT_COUNTER = LabeledCounter(
    "sdtpu_sim_faults_total",
    "Chaos faults injected by the scenario engine (SDTPU_SIM) by kind.",
    ("kind",))

_SIM_LOCK = threading.Lock()
#: worst per-(tenant, class) SLO burn rate from the last scored scenario
#: run; None until sim/score.py scores one, omitted from /internal/metrics
#: while None.
_SIM_SLO_BURN: Optional[float] = None  # guarded-by: _SIM_LOCK


def sim_fault_count(kind: str, n: float = 1.0) -> None:
    SIM_FAULT_COUNTER.inc(n, kind=kind)


def set_sim_slo_burn(value: Optional[float]) -> None:
    """Record the last scenario run's worst SLO burn rate (sim/score.py)."""
    global _SIM_SLO_BURN
    with _SIM_LOCK:
        _SIM_SLO_BURN = None if value is None else float(value)


def sim_slo_burn() -> Optional[float]:
    with _SIM_LOCK:
        return _SIM_SLO_BURN

_WORKER_LOCK = threading.Lock()
#: per-worker generate-latency EWMA gauge values
_WORKER_LATENCY_EWMA: Dict[str, float] = {}  # guarded-by: _WORKER_LOCK


def worker_count(name: str, n: float = 1.0, **labels: Any) -> None:
    c = WORKER_COUNTERS.get(name)
    if c is not None:
        c.inc(n, **labels)


def set_worker_latency(worker: str, ewma_s: float) -> None:
    with _WORKER_LOCK:
        _WORKER_LATENCY_EWMA[str(worker)] = float(ewma_s)


def count_watchdog_stall(name: str) -> None:
    WATCHDOG_COUNTER.inc(name=name)


def watchdog_stalls_total() -> float:
    return WATCHDOG_COUNTER.total()


_FLEET_LOCK = threading.Lock()
#: per-class queue-wait histograms, created on first observation
_FLEET_QUEUE_WAIT: Dict[str, Histogram] = {}  # guarded-by: _FLEET_LOCK


def fleet_count(name: str, n: float = 1.0, **labels: Any) -> None:
    c = FLEET_COUNTERS.get(name)
    if c is not None:
        c.inc(n, **labels)


def count_precision(precision: str, n: float = 1.0) -> None:
    """One device dispatch carrying ``n`` requests at ``precision``."""
    if precision:
        PRECISION_COUNTER.inc(n, precision=precision)


def fleet_observe_queue_wait(cls: str, seconds: float) -> None:
    """Per-class companion to the unlabeled ``queue_wait`` histogram —
    the autoscaler keys its p95 signal on these."""
    with _FLEET_LOCK:
        h = _FLEET_QUEUE_WAIT.get(cls)
        if h is None:
            h = Histogram(
                "sdtpu_fleet_queue_wait_seconds",
                "Gate queue wait by priority class.",
                labels=f'class="{_label(cls)}"')
            _FLEET_QUEUE_WAIT[cls] = h
    h.observe(seconds)


def fleet_queue_wait_p95(cls: Optional[str] = None) -> float:
    """p95 gate wait for one class, or the worst class when ``cls`` is
    None (the autoscale signal keys on the most-starved class)."""
    with _FLEET_LOCK:
        hists = ([_FLEET_QUEUE_WAIT[cls]]
                 if cls is not None and cls in _FLEET_QUEUE_WAIT
                 else list(_FLEET_QUEUE_WAIT.values()))
    if not hists:
        return 0.0
    return max(h.quantile(0.95) for h in hists)


class EtaGauge:
    """Live predicted-vs-actual ETA calibration across every backend.

    Mirrors the paper's per-worker MPE feedback (scheduler/eta.py,
    reference worker.py:476-492) as one process-wide gauge: same window,
    same |error| >= 500% rejection, fed by ``record_eta_error``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: window/rejection adopted from scheduler.eta at first record —
        #: importing the scheduler package here (obs import time) would
        #: drag worker/world in and risk an import cycle
        self._errors: Optional[Deque[float]] = None  # guarded-by: _lock
        self._samples = 0  # guarded-by: _lock
        self._last_predicted: Optional[float] = None  # guarded-by: _lock
        self._last_actual: Optional[float] = None  # guarded-by: _lock

    def record(self, predicted: float, actual: float) -> None:
        from stable_diffusion_webui_distributed_tpu.scheduler import (
            eta as eta_mod,
        )

        if actual <= 0 or predicted <= 0:
            return
        error = (predicted - actual) / actual * 100.0
        if abs(error) >= eta_mod.MPE_REJECT_ABS_PERCENT:
            return
        with self._lock:
            if self._errors is None:
                self._errors = deque(maxlen=eta_mod.MPE_WINDOW)
            self._errors.append(error)
            self._samples += 1
            self._last_predicted = float(predicted)
            self._last_actual = float(actual)

    def mpe(self) -> float:
        with self._lock:
            if not self._errors:
                return 0.0
            return sum(self._errors) / len(self._errors)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            mpe = (sum(self._errors) / len(self._errors)
                   if self._errors else 0.0)
            return {
                "mpe_percent": mpe,
                "samples": self._samples,
                "last_predicted_s": self._last_predicted,
                "last_actual_s": self._last_actual,
            }

    def clear(self) -> None:
        with self._lock:
            self._errors = None
            self._samples = 0
            self._last_predicted = None
            self._last_actual = None


#: Process-wide ETA calibration gauge (scheduler/eta.py feeds it).
ETA_GAUGE = EtaGauge()


def _scalar(lines: List[str], name: str, mtype: str, help_text: str,
            value: Any, labels: str = "") -> None:
    register_metric(name, mtype, help_text)
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} {mtype}")
    lines.append(f"{name}{labels} {_fmt(value)}")


def _labeled_family(lines: List[str], name: str, mtype: str,
                    help_text: str,
                    samples: List[Tuple[str, Any]]) -> None:
    """One HELP/TYPE header + one sample per (label-body, value) pair;
    families with no samples are omitted entirely."""
    if not samples:
        return
    register_metric(name, mtype, help_text)
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} {mtype}")
    for body, value in samples:
        lines.append(f"{name}{{{body}}} {_fmt(value)}")


def _render_perf(lines: List[str]) -> None:
    """The perf-ledger families: per-(bucket, cadence, precision) MFU /
    padding / device-time attribution and per-(tenant, class) SLO gauges.
    All pulled live from obs/perf.py's LEDGER — empty (and absent from
    the exposition) until SDTPU_PERF turns recording on."""
    from stable_diffusion_webui_distributed_tpu.obs import perf as obs_perf

    s = obs_perf.LEDGER.summary()

    def body(g):
        # lora: traced-adapter cell ("r8s1") or "" — adapter-active MFU
        # rows stay separable from the adapterless baseline
        return (f'bucket="{_label(g["bucket"])}",'
                f'cadence="{g["cadence"]}",'
                f'precision="{_label(g["precision"])}",'
                f'lora="{_label(g.get("lora", ""))}"')

    groups = s["groups"]
    _labeled_family(
        lines, "sdtpu_perf_dispatches_total", "counter",
        "Device dispatches by serving group (perf ledger).",
        [(body(g), g["dispatches"]) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_device_seconds_total", "counter",
        "Host-observed device-dispatch seconds by serving group.",
        [(body(g), g["device_s"]) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_flops_total", "counter",
        "Dispatched UNet FLOPs by serving group (cost_analysis priced).",
        [(body(g), g["flops"]) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_mfu", "gauge",
        "Live MFU: dispatched FLOPs / device seconds / chip peak "
        "(NaN when the peak is unknown, e.g. CPU).",
        [(body(g), g["mfu"]) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_padding_ratio", "gauge",
        "Padded-dispatched pixels / true-requested pixels by group.",
        [(body(g), g["padding_ratio"]) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_padding_waste", "gauge",
        "Fraction of dispatched pixels that were bucket padding.",
        [(body(g), g["padding_waste"]) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_compute_padding_ratio", "gauge",
        "Attention-computed pixels / true-requested pixels by group "
        "(masked ragged rows excluded from the numerator).",
        [(body(g), g.get("compute_padding_ratio")) for g in groups])
    _labeled_family(
        lines, "sdtpu_perf_token_padding_ratio", "gauge",
        "Padded conditioning tokens / true prompt tokens by group.",
        [(body(g), g.get("token_padding_ratio")) for g in groups])

    def slo_body(r):
        return (f'tenant="{_label(r["tenant"])}",'
                f'class="{_label(r["class"])}"')

    slo = s["slo"]
    _labeled_family(
        lines, "sdtpu_fleet_slo_attainment", "gauge",
        "Fraction of fleet-gated requests meeting their SLO, by tenant "
        "and class.", [(slo_body(r), r["attainment"]) for r in slo])
    _labeled_family(
        lines, "sdtpu_fleet_slo_burn_rate", "gauge",
        "Windowed SLO miss fraction over the error budget (1.0 = burning "
        "exactly the budget).", [(slo_body(r), r["burn_rate"]) for r in slo])


def render() -> str:
    """The full /internal/metrics body (Prometheus text format 0.0.4)."""
    # lazy imports: this module must stay importable without dragging the
    # serving/runtime stacks in at obs import time (no cycles)
    from stable_diffusion_webui_distributed_tpu.runtime.trace import STATS
    from stable_diffusion_webui_distributed_tpu.serving.metrics import (
        METRICS,
    )

    lines: List[str] = []
    for h in HISTOGRAMS.values():
        lines.extend(h.render())

    s = METRICS.summary()
    _scalar(lines, "sdtpu_serving_requests_total", "counter",
            "Requests accepted by the serving dispatcher.", s["requests"])
    _scalar(lines, "sdtpu_serving_bucket_hits_total", "counter",
            "Requests whose shape matched a bucket exactly.",
            s["bucket_hits"])
    _scalar(lines, "sdtpu_serving_bucket_misses_total", "counter",
            "Requests padded up to a bucket.", s["bucket_misses"])
    _scalar(lines, "sdtpu_serving_bucket_bypasses_total", "counter",
            "Requests that bypassed bucketing (hires/img2img/no fit).",
            s["bucket_bypasses"])
    _scalar(lines, "sdtpu_serving_bucket_hit_rate", "gauge",
            "bucket_hits / (bucket_hits + bucket_misses).",
            s["bucket_hit_rate"])
    _scalar(lines, "sdtpu_serving_dispatches_total", "counter",
            "Device batches executed.", s["dispatches"])
    _scalar(lines, "sdtpu_serving_coalesced_dispatches_total", "counter",
            "Dispatches that merged >= 2 requests.",
            s["coalesced_dispatches"])
    _scalar(lines, "sdtpu_serving_coalesce_factor", "gauge",
            "Mean requests per device dispatch.", s["coalesce_factor"])
    _scalar(lines, "sdtpu_serving_avg_queue_wait_seconds", "gauge",
            "Rolling mean coalesce-queue wait.", s["avg_queue_wait_s"])
    _scalar(lines, "sdtpu_serving_avg_padding_ratio", "gauge",
            "Mean bucket-px / requested-px over bucketed requests.",
            s["avg_padding_ratio"])
    _scalar(lines, "sdtpu_serving_unet_flops_total", "counter",
            "UNet FLOPs dispatched (XLA cost_analysis pricing).",
            s["unet_flops_total"])
    _scalar(lines, "sdtpu_serving_unet_images_total", "counter",
            "Images decoded to outputs.", s["unet_images"])
    _scalar(lines, "sdtpu_serving_unet_flops_per_image", "gauge",
            "Mean dispatched UNet FLOPs per output image.",
            s["unet_flops_per_image"])

    _labeled_family(
        lines, "sdtpu_stage_compiles_total", "counter",
        "XLA stage builds (one compile each) by stage kind.",
        [(f'kind="{_label(kind)}"', s["compiles"][kind])
         for kind in sorted(s["compiles"])])
    _labeled_family(
        lines, "sdtpu_stage_cache_hits_total", "counter",
        "Compiled-stage cache hits by stage kind.",
        [(f'kind="{_label(kind)}"', s["cache_hits"][kind])
         for kind in sorted(s["cache_hits"])])

    timings = STATS.summary()
    _labeled_family(
        lines, "sdtpu_stage_seconds", "gauge",
        "Rolling stage wall-clock stats (StageStats window).",
        [(f'stage="{_label(stage)}",stat="{stat}"', timings[stage][stat])
         for stage in sorted(timings)
         for stat in ("mean", "p50", "last")])
    _labeled_family(
        lines, "sdtpu_stage_samples", "gauge",
        "Rolling StageStats sample count per stage.",
        [(f'stage="{_label(stage)}"', timings[stage]["count"])
         for stage in sorted(timings)])

    lines.extend(PRECISION_COUNTER.render())
    lines.extend(LORA_SWITCH_COUNTER.render())
    lines.extend(AOT_COUNTER.render())
    for c in FLEET_COUNTERS.values():
        lines.extend(c.render())
    for c in WORKER_COUNTERS.values():
        lines.extend(c.render())
    lines.extend(WATCHDOG_COUNTER.render())
    lines.extend(CACHE_COUNTER.render())
    lines.extend(SIM_FAULT_COUNTER.render())
    lines.extend(ALERT_COUNTER.render())
    lines.extend(NOTIFY_COUNTER.render())
    _labeled_family(
        lines, "sdtpu_alert_state", "gauge",
        "Current alert state by rule (1 = firing, 0 = resolved/ok); "
        "rules absent until their first transition.",
        [(f'rule="{_label(k)}"', v)
         for k, v in sorted(alert_states().items())])
    burn = sim_slo_burn()
    if burn is not None:
        _scalar(lines, "sdtpu_sim_slo_burn", "gauge",
                "Worst per-(tenant, class) SLO burn rate from the last "
                "scored scenario run (sim/score.py).", burn)
    with _WORKER_LOCK:
        worker_lat = dict(_WORKER_LATENCY_EWMA)
    _labeled_family(
        lines, "sdtpu_worker_latency_ewma_seconds", "gauge",
        "EWMA of per-worker generate latency (WorkerHealth window).",
        [(f'worker="{_label(k)}"', v)
         for k, v in sorted(worker_lat.items())])
    with _FLEET_LOCK:
        fleet_hists = [_FLEET_QUEUE_WAIT[k]
                       for k in sorted(_FLEET_QUEUE_WAIT)]
    for i, h in enumerate(fleet_hists):
        lines.extend(h.render(header=(i == 0)))
    with _COMPILE_LOCK:
        compile_hists = [_COMPILE_LAT[k] for k in sorted(_COMPILE_LAT)]
    for i, h in enumerate(compile_hists):
        lines.extend(h.render(header=(i == 0)))
    with _AOT_LOAD_LOCK:
        aot_hists = [_AOT_LOAD_LAT[k] for k in sorted(_AOT_LOAD_LAT)]
    for i, h in enumerate(aot_hists):
        lines.extend(h.render(header=(i == 0)))
    with _STAGE_GRAPH_LOCK:
        stage_hists = [_STAGE_GRAPH_LAT[k]
                       for k in sorted(_STAGE_GRAPH_LAT)]
    for i, h in enumerate(stage_hists):
        lines.extend(h.render(header=(i == 0)))
    _render_perf(lines)

    eta = ETA_GAUGE.summary()
    _scalar(lines, "sdtpu_eta_mpe_percent", "gauge",
            "Live ETA mean percent error (paper MPE window).",
            eta["mpe_percent"])
    _scalar(lines, "sdtpu_eta_samples_total", "counter",
            "Accepted predicted-vs-actual ETA samples.", eta["samples"])
    return "\n".join(lines) + "\n"

"""Request-scoped observability: span trees, Prometheus exposition, and a
failure flight recorder.

The reference's only "profiler" is the benchmark/ETA wall-clock loop
(SURVEY.md §5, worker.py:477-481); our StageStats/DispatchMetrics surfaces
aggregate globally, so nothing can answer "where did THIS request's nine
seconds go" or "what was the p99 queue wait under coalescing". This package
adds the per-request layer:

- :mod:`.spans` — a ``request_id`` contextvar minted at API ingress and
  threaded through bucketer -> coalesce queue -> compile -> device dispatch
  -> decode, recorded into a bounded lock-disciplined store with
  Chrome-trace-event export (``/internal/trace.json``, Perfetto-loadable).
- :mod:`.prometheus` — text exposition (``/internal/metrics``) of every
  DispatchMetrics/StageStats scalar plus fixed-ladder latency histograms
  (e2e, queue wait, device dispatch, decode) for real p50/p95/p99, and the
  live ETA mean-percent-error gauge.
- :mod:`.flightrec` — the last N failed/interrupted/slow requests' full
  span trees plus their correlated log lines (``/internal/flightrec``;
  ``bench.py`` dumps it on error).

Everything is host-side ``time.perf_counter()`` — no device sync ever rides
on the hot path — and spans are default-on (``SDTPU_OBS=0`` disables).
"""

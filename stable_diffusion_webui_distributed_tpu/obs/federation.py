"""Fleet-federated metrics (SDTPU_FEDERATION): one view of every node.

``/internal/metrics`` and ``/internal/tsdb`` cover the local process;
the HTTP fleet tier's remote workers are invisible except as trace
stitches. This module is the master-side prober: on each :func:`tick`
(or on the daemon's cadence — the TSDB sampler's interval, one clock
for the whole plane) it scrapes every pollable worker's
``/internal/metrics`` + ``/internal/tsdb``, digests the responses, and
records them into the local TSDB (obs/tsdb.py) as

- ``worker:<label>/<series>`` — per-worker staleness gauge, error rate,
  queue-wait/e2e p95, request/failure totals, poll RTT;
- ``fleet/...`` aggregates — worst-of-fleet queue-wait p95 (local node
  included), mean fleet error rate (an unreachable worker counts as
  1.0), the stale-worker count, and a cumulative poll-failure counter.

Fault isolation is per node: a dead or hung worker journals one
``federation_poll_failed``, marks its staleness series, and never
stalls the tick — every fetch carries an explicit timeout from the
obs-plane-wide ``SDTPU_OBS_HTTP_TIMEOUT_S`` knob (obs/stitch.py), and
the fetch bracket reuses stitch's clock-correction pattern (the
response is attributed to the RTT midpoint, so staleness measures data
age, not transfer time).

The recorded series feed the fleet-scope alert rules
(``worker_metrics_stale``, ``fleet_error_rate`` in obs/alerts.py) and
:func:`fleet_queue_wait_p95` gives ``fleet/slices.py`` a fleet-wide
(not node-local) scale signal. Served at ``GET /internal/fleet``;
``tools/fed_report.py`` renders it.

Gated off by default: with ``SDTPU_FEDERATION`` unset no source is
registered, :func:`tick` is a no-op, no daemon starts, and the serving
path is byte-identical to the unfederated build (hash-pinned in
tests/test_federation.py).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..runtime.config import env_flag
from ..runtime.daemon import StoppableDaemon
from . import stitch

#: A worker is stale when its freshest successful poll is older than
#: STALE_FACTOR sampling intervals (floored so a fast test cadence
#: cannot flag a healthy worker between back-to-back ticks).
STALE_FACTOR = 3.0
STALE_FLOOR_S = 0.25

#: Remote series latched per worker from its /internal/tsdb document.
_REMOTE_SERIES: Tuple[str, ...] = ("queue_wait_p95_s", "e2e_p95_s")


def enabled() -> bool:
    """Federation gate — re-read per call so tests can flip the env var."""
    return env_flag("SDTPU_FEDERATION", False)


def stale_after_s() -> float:
    """Freshness deadline for a worker's federated metrics."""
    from . import tsdb as obs_tsdb

    return max(STALE_FLOOR_S, STALE_FACTOR * obs_tsdb.interval_s())


def parse_prom_text(text: str) -> Dict[str, float]:
    """Minimal Prometheus text-format digest: family name -> sum of its
    sample values across label sets (enough for counter totals; comments
    and malformed lines are skipped)."""
    out: Dict[str, float] = {}
    for line in (text or "").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            continue
        try:
            value = float(parts[1])
        except ValueError:
            continue
        name = parts[0].split("{", 1)[0].strip()
        if name:
            out[name] = out.get(name, 0.0) + value
    return out


def _pollable(worker: Any) -> bool:
    """A worker the prober can scrape: its backend exposes a test/bench
    fetch seam (``fed_fetch``) or an HTTP endpoint (address + port)."""
    backend = getattr(worker, "backend", None)
    if backend is None:
        return False
    if callable(getattr(backend, "fed_fetch", None)):
        return True
    return bool(getattr(backend, "address", None)) \
        and bool(getattr(backend, "port", None))


def fetch_documents(backend: Any, clock=time.monotonic) -> Tuple[
        Optional[str], Optional[Dict[str, Any]], float, float]:
    """(metrics_text, tsdb_doc, t0, t1): one worker's scrape through one
    bracketed fetch window. ``fed_fetch`` is the in-process seam the
    bench/tests use; the HTTP path carries the obs-plane timeout on
    every call so a hung worker cannot stall the caller. Shared by the
    poll prober and the push plane's per-node poll fallback
    (obs/push.py)."""
    t0 = clock()
    fetcher = getattr(backend, "fed_fetch", None)
    if callable(fetcher):
        metrics_text, tsdb_doc = fetcher()
    else:
        timeout = stitch.http_timeout_s()
        scheme = "https" if getattr(backend, "tls", False) else "http"
        base = f"{scheme}://{backend.address}:{backend.port}"
        with urllib.request.urlopen(f"{base}/internal/metrics",
                                    timeout=timeout) as resp:
            metrics_text = resp.read().decode("utf-8", "replace")
        with urllib.request.urlopen(f"{base}/internal/tsdb",
                                    timeout=timeout) as resp:
            tsdb_doc = json.loads(resp.read().decode("utf-8", "replace"))
    return metrics_text, tsdb_doc, t0, clock()


class FederationProber:
    """Per-worker poll state machine + TSDB series writer.

    ``store`` defaults to the live TSDB; tests pass their own
    :class:`~.tsdb.SeriesStore` and drive :meth:`tick` with an explicit
    clock for determinism. ``source`` is a World (``.workers``) or any
    iterable of workers, same contract as obs/stitch.py.
    """

    def __init__(self, source: Any = None, store=None,
                 clock=time.monotonic) -> None:
        self._store = store
        self._clock = clock
        self._lock = threading.Lock()
        self._source = source                          # guarded-by: _lock
        # label -> poll/staleness bookkeeping            guarded-by: _lock
        self._status: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._polls = 0                                # guarded-by: _lock
        self._poll_failures = 0                        # guarded-by: _lock
        self._ticks = 0                                # guarded-by: _lock

    def store(self):
        if self._store is not None:
            return self._store
        from . import tsdb as obs_tsdb

        return obs_tsdb.STORE

    def set_source(self, source: Any) -> None:
        with self._lock:
            self._source = source

    def source(self) -> Any:
        with self._lock:
            return self._source

    # -- one worker ---------------------------------------------------------

    def _fetch(self, backend: Any) -> Tuple[Optional[str],
                                            Optional[Dict[str, Any]],
                                            float, float]:
        """One worker's scrape bracket; see :func:`fetch_documents`."""
        return fetch_documents(backend, clock=self._clock)

    @staticmethod
    def _digest(metrics_text: Optional[str],
                tsdb_doc: Optional[Dict[str, Any]]) -> Dict[str, float]:
        """Flatten one worker's scrape into the per-worker series row."""
        row: Dict[str, float] = {}
        prom = parse_prom_text(metrics_text or "")
        # sdtpu-lint: metric — reads of the remote's registered families
        requests = prom.get("sdtpu_worker_requests_total", 0.0)
        # sdtpu-lint: metric
        failures = prom.get("sdtpu_worker_failures_total", 0.0)
        row["requests_total"] = requests
        row["failures_total"] = failures
        row["error_rate"] = failures / requests if requests > 0 else 0.0
        series = (tsdb_doc or {}).get("series") or {}
        for name in _REMOTE_SERIES:
            entry = series.get(name) or {}
            latest = entry.get("latest") if isinstance(entry, dict) else None
            if isinstance(latest, (list, tuple)) and len(latest) == 2:
                try:
                    row[name] = float(latest[1])
                except (TypeError, ValueError):
                    pass
        row.setdefault("queue_wait_p95_s", 0.0)
        return row

    # -- the tick -----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> int:
        """One poll sweep over every pollable worker; returns how many
        TSDB samples landed. No-op (0) with the gate off or no source."""
        if not enabled():
            return 0
        source = self.source()
        if source is None:
            return 0
        if now is None:
            now = self._clock()
        workers = [w for w in stitch._workers_of(source) if _pollable(w)]
        rows: List[Tuple[str, Optional[Dict[str, float]]]] = []
        for w in workers:
            label = str(getattr(w, "label", "?"))
            with self._lock:
                st = self._status.setdefault(label, {
                    "first_seen": now, "polls": 0, "failures": 0,
                    "last_ok": None, "last_error": None, "rtt_s": None,
                    "stale": False})
                st["polls"] += 1
                self._polls += 1
            try:
                metrics_text, doc, t0, t1 = self._fetch(w.backend)
                rtt = max(0.0, t1 - t0)
                row = self._digest(metrics_text, doc)
                row["poll_rtt_s"] = rtt
                with self._lock:
                    # clock-correction pattern (obs/stitch.py): the
                    # document corresponds to the fetch RTT midpoint
                    st["last_ok"] = t0 + rtt / 2.0
                    st["last_error"] = None
                    st["rtt_s"] = rtt
            except Exception as e:  # noqa: BLE001 — per-node fault isolation
                row = None
                with self._lock:
                    st["failures"] += 1
                    self._poll_failures += 1
                    st["last_error"] = f"{type(e).__name__}: {e}"
                self._journal_failure(label, e)
            rows.append((label, row))
        return self._record(rows, now)

    def _record(self, rows: List[Tuple[str, Optional[Dict[str, float]]]],
                now: float) -> int:
        store = self.store()
        landed = 0
        stale_count = 0
        error_rates: List[float] = []
        p95s: List[float] = []
        for label, row in rows:
            with self._lock:
                st = self._status[label]
                anchor = st["last_ok"] if st["last_ok"] is not None \
                    else st["first_seen"]
                staleness = max(0.0, now - anchor)
                st["stale"] = staleness >= stale_after_s()
                stale = st["stale"]
            if stale:
                stale_count += 1
            store.record(f"worker:{label}/staleness_s", staleness, t=now)
            landed += 1
            if row is None:
                # unreachable: its share of the fleet error rate is 1.0
                error_rates.append(1.0)
                continue
            for key, value in row.items():
                store.record(f"worker:{label}/{key}", value, t=now)
                landed += 1
            error_rates.append(row.get("error_rate", 0.0))
            p95s.append(row.get("queue_wait_p95_s", 0.0))
        with self._lock:
            self._ticks += 1
            poll_failures = self._poll_failures
        if rows:
            local_p95 = 0.0
            try:
                from . import prometheus as obs_prom

                local_p95 = obs_prom.fleet_queue_wait_p95()
            except Exception:  # noqa: BLE001 — aggregation stays passive
                pass
            for name, value in (
                    ("fleet/queue_wait_p95_s", max([local_p95] + p95s)),
                    ("fleet/error_rate",
                     sum(error_rates) / len(error_rates)),
                    ("fleet/worker_stale_count", float(stale_count)),
                    ("fleet/poll_failures_total", float(poll_failures))):
                store.record(name, value, t=now)
                landed += 1
        return landed

    @staticmethod
    def _journal_failure(label: str, exc: Exception) -> None:
        try:
            from . import journal as obs_journal

            if obs_journal.enabled():
                obs_journal.emit("federation_poll_failed",
                                 f"federation-{label}", worker=label,
                                 error=f"{type(exc).__name__}: {exc}")
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass

    # -- views --------------------------------------------------------------

    def fleet_queue_wait_p95(self) -> float:
        """Latest federated worst-of-fleet queue-wait p95 (0.0 before the
        first tick) — the autoscaler's fleet-wide scale signal."""
        latest = self.store().latest("fleet/queue_wait_p95_s")
        return float(latest[1]) if latest is not None else 0.0

    def summary(self) -> Dict[str, Any]:
        """The ``GET /internal/fleet`` document."""
        now = self._clock()
        deadline = stale_after_s()
        with self._lock:
            workers = {}
            for label, st in self._status.items():
                anchor = st["last_ok"] if st["last_ok"] is not None \
                    else st["first_seen"]
                staleness = max(0.0, now - anchor)
                workers[label] = {
                    "polls": st["polls"],
                    "failures": st["failures"],
                    "staleness_s": staleness,
                    "stale": staleness >= deadline,
                    "rtt_s": st["rtt_s"],
                    "last_error": st["last_error"],
                }
            polls = self._polls
            poll_failures = self._poll_failures
            ticks = self._ticks
        store = self.store()
        for label, row in workers.items():
            for metric in ("error_rate", "queue_wait_p95_s"):
                latest = store.latest(f"worker:{label}/{metric}")
                row[metric] = (float(latest[1])
                               if latest is not None else None)
        fleet = {}
        for name in ("fleet/queue_wait_p95_s", "fleet/error_rate",
                     "fleet/worker_stale_count"):
            latest = store.latest(name)
            fleet[name.split("/", 1)[1]] = (
                float(latest[1]) if latest is not None else None)
        with _DAEMON_LOCK:
            daemon_alive = _DAEMON is not None and _DAEMON.alive()
        return {
            "enabled": enabled(),
            "stale_after_s": deadline,
            "ticks": ticks,
            "polls_total": polls,
            "poll_failures_total": poll_failures,
            "daemon": daemon_alive,
            "workers": workers,
            "fleet": fleet,
        }

    def clear(self) -> None:
        with self._lock:
            self._status.clear()
            self._polls = 0
            self._poll_failures = 0
            self._ticks = 0


#: Process-wide prober. A World registers itself as the source at
#: construction when the gate is on (scheduler/world.py); tests and
#: bench call :func:`set_source` / :func:`tick` directly.
PROBER = FederationProber()


# -- polling daemon ----------------------------------------------------------

_DAEMON_LOCK = threading.Lock()
_DAEMON: Optional[StoppableDaemon] = None  # guarded-by: _DAEMON_LOCK


def _probe_tick() -> None:
    """One guarded poll sweep (reads PROBER at call time so reset()'s
    rebind takes effect without a daemon restart)."""
    try:
        PROBER.tick()
    except Exception:  # noqa: BLE001 — the sweep must survive
        pass


def set_source(source: Any) -> None:
    """Register the prober's worker source (a World or iterable)."""
    PROBER.set_source(source)


def source() -> Any:
    return PROBER.source()


def tick(now: Optional[float] = None) -> int:
    """One gated poll sweep; 0 with SDTPU_FEDERATION off."""
    return PROBER.tick(now=now)


def fleet_queue_wait_p95() -> float:
    """Fleet-wide scale signal for the autoscaler; 0.0 when off."""
    if not enabled():
        return 0.0
    return PROBER.fleet_queue_wait_p95()


def start_daemon() -> bool:
    """Start the poll daemon (idempotent); False with the gate off."""
    global _DAEMON
    if not enabled():
        return False
    from . import tsdb as obs_tsdb

    with _DAEMON_LOCK:
        if _DAEMON is not None and _DAEMON.alive():
            return True
        _DAEMON = StoppableDaemon("sdtpu-federation-prober", _probe_tick,
                                  obs_tsdb.interval_s)
        _DAEMON.start()
    return True


def stop_daemon() -> None:
    global _DAEMON
    with _DAEMON_LOCK:
        daemon = _DAEMON
        _DAEMON = None
    if daemon is not None:
        daemon.stop(timeout_s=2.0)


def reset() -> None:
    """Stop the daemon and rebuild the prober (tests/bench between
    phases); the source registration does not survive — a World
    re-registers at construction."""
    global PROBER
    stop_daemon()
    PROBER = FederationProber()


def summary() -> Dict[str, Any]:
    """The ``GET /internal/fleet`` document (served even when off)."""
    return PROBER.summary()

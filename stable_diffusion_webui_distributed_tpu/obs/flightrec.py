"""Failure flight recorder: the last N failed/interrupted/slow requests.

A postmortem needs MORE than aggregate metrics — it needs the dead
request's own span tree and the log lines it emitted. ``obs/spans.py``
hands every non-``ok`` request trace here at close time (exported trace
events, so entries stay plain JSON), and this module attaches the
correlated log lines captured by ``runtime/logging.py``'s per-request
index. The ring is bounded (``SDTPU_OBS_FLIGHTREC`` entries, default 16 —
the same capacity instinct as the GUI log ring) and exposed at
``/internal/flightrec``; ``bench.py`` dumps it to a JSON file when a run
dies so the evidence survives the process.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from stable_diffusion_webui_distributed_tpu.runtime.config import env_int

#: Default retained failure entries.
DEFAULT_CAPACITY = 16


class FlightRecorder:
    """Bounded ring of failure records (thread-safe, JSON-plain entries)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        if capacity is None:
            capacity = env_int("SDTPU_OBS_FLIGHTREC", DEFAULT_CAPACITY)
        self._lock = threading.Lock()
        self._entries: Deque[Dict[str, Any]] = deque(
            maxlen=max(1, int(capacity or DEFAULT_CAPACITY)))  # guarded-by: _lock

    def record(self, request_id: str, reason: str, detail: str,
               events: List[Dict[str, Any]],
               duration_s: float = 0.0,
               perf: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Append one failure entry; returns it (already JSON-plain).

        ``perf`` carries the failing request's device-time attribution
        (MFU / padding / compile totals); left ``None`` the recorder pulls
        the perf ledger's last-dispatch snapshot itself, so span-layer
        callers need no knowledge of the ledger."""
        from stable_diffusion_webui_distributed_tpu.runtime.logging import (
            lines_for_request,
        )

        if perf is None:
            try:
                from stable_diffusion_webui_distributed_tpu.obs import (
                    perf as obs_perf,
                )

                perf = obs_perf.LEDGER.last_dispatch()
            except Exception:  # noqa: BLE001 — recorder must never fail
                perf = None
        entry = {
            "request_id": str(request_id),
            "reason": str(reason),
            "detail": str(detail),
            # wall clock, not perf_counter: postmortems are read next to
            # log files and dashboards, which speak wall time
            "recorded_at": time.time(),  # sdtpu-lint: wallclock
            "duration_s": float(duration_s),
            # None until a dispatch ran with SDTPU_PERF on
            "perf": perf,
            "spans": list(events),
            "logs": lines_for_request(request_id),
            # what the detectors saw (satellite: postmortem enrichment)
            # — both None with the SDTPU_ALERTS / SDTPU_TSDB gates off
            "alerts": self._alert_snapshot(),
            "tsdb": self._tsdb_window(),
        }
        with self._lock:
            self._entries.append(entry)
        return entry

    @staticmethod
    def _alert_snapshot() -> Optional[Dict[str, Any]]:
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                alerts as obs_alerts,
            )

            return obs_alerts.state_snapshot()
        except Exception:  # noqa: BLE001 — recorder must never fail
            return None

    @staticmethod
    def _tsdb_window() -> Optional[Dict[str, Any]]:
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                tsdb as obs_tsdb,
            )

            return obs_tsdb.flight_window()
        except Exception:  # noqa: BLE001 — recorder must never fail
            return None

    def dump(self) -> Dict[str, Any]:
        """All retained entries, oldest first (the /internal/flightrec
        body)."""
        with self._lock:
            entries = list(self._entries)
            capacity = self._entries.maxlen
        return {"entries": entries, "capacity": capacity,
                "count": len(entries)}

    def dump_to_file(self, path: str) -> str:
        """Write :meth:`dump` as JSON (bench.py's on-error escape hatch)."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.dump(), f, indent=2, default=str)
        return path

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: Process-wide recorder (obs/spans.py feeds it; bench.py dumps it).
RECORDER = FlightRecorder()

"""Request lifecycle journal: bounded append-only event log + replay source.

The span tree (:mod:`.spans`) answers "where did this request's time go";
it cannot answer "what *decisions* were made about it" — was it throttled,
which bucket did it land in, did it ride a coalesced group as leader or
follower, which worker got which slice, was a failed slice requeued and
where. This module records that decision trail as a bounded, append-only
sequence of structured events so a failed request can be reconstructed
and re-executed deterministically (``tools/replay.py``).

Every event carries a monotonically increasing ``seq``, a monotonic
timestamp, the request id, a causal ``parent`` seq (the previous event of
the same request unless overridden — e.g. a coalesce follower points at
the leader's event), and free-form attrs. The "received" event embeds the
full post-``fix_seed`` payload dump plus a fingerprint, which is what
makes replay byte-deterministic.

Gated off by default: ``SDTPU_JOURNAL=1`` enables, ``SDTPU_JOURNAL_MAX``
bounds retention (events, not requests). ``emit()`` is a no-op returning
``None`` when disabled, so call sites that build expensive attrs (payload
dumps) guard on :func:`enabled` first. Event *types* are a closed enum:
emitting an unregistered type raises, and lint rule OB003 enforces at the
AST level that literals passed to ``emit()`` outside this module are
members of :data:`EVENTS`.

The ring silently drops history on runs longer than its capacity;
``SDTPU_JOURNAL_SINK=<path>`` spills every ring-evicted event to that
file as one JSONL line, so ring + sink together stay a complete record
on long scenario runs (``tools/replay.py`` and ``sim/workload.py`` load
sink files as well as snapshots).

Served at ``GET /internal/journal[?request_id=]``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from ..runtime.config import env_flag, env_float, env_int, env_str

#: The closed set of journal event types. Serving-tier lifecycle first,
#: then the scheduler/worker tier, then the health/watchdog plane.
EVENTS = frozenset({
    # serving tier (dispatcher)
    "received",
    "admitted",
    "throttled",
    "degraded",
    "bucketed",
    "coalesced_leader",
    "coalesced_follower",
    "dispatched",
    "preempted",
    "resumed",
    "decoded",
    "merged",
    "completed",
    "failed",
    # caching tier (cache/, emitted by the dispatcher)
    "embed_cache_hit",
    "result_dedupe_hit",
    "prefix_resumed",
    # scheduler tier (World/Job)
    "planned",
    "job_dispatched",
    "job_completed",
    "job_failed",
    "requeued",
    # health / watchdog plane
    "watchdog_stall",
    "worker_state",
    # scenario engine / chaos tier (sim/chaos.py)
    "fault_injected",
    "fault_cleared",
    # alerting plane (obs/alerts.py state machine)
    "alert_firing",
    "alert_resolved",
    # delivery / federation plane (obs/notify.py, obs/federation.py)
    "notify_sent",
    "notify_failed",
    "notify_dropped",
    "federation_poll_failed",
    # push control plane (obs/push.py delta streaming)
    "push_buffer_evicted",
    "push_fallback",
    # AOT artifact / warm-pool plane (serving/aot.py, fleet/pool.py)
    "aot_fallback",
    "pool_spawned",
    "pool_retired",
})

DEFAULT_CAPACITY = 4096

#: How many distinct request ids keep a live causal-parent pointer.
_PARENT_INDEX_CAP = 256


def enabled() -> bool:
    """Journal gate — re-read per call so tests can flip the env var."""
    return env_flag("SDTPU_JOURNAL", False)


def sink_path() -> str:
    """Spill file for ring-evicted events ('' = no sink). Re-read per
    call so scenario runs can point successive phases at fresh files."""
    return env_str("SDTPU_JOURNAL_SINK", "")


def sink_max_bytes() -> int:
    """Size cap for the spill file (SDTPU_JOURNAL_SINK_MAX_MB); 0 =
    unbounded. Past the cap the sink rotates once: the current file is
    renamed to ``<sink>.1`` (replacing any previous ``.1``) and writing
    restarts on a fresh file, so a long scenario run keeps at most
    2 x cap bytes on disk."""
    mb = env_float("SDTPU_JOURNAL_SINK_MAX_MB", 0.0)
    return max(0, int(mb * 1024 * 1024))


def fingerprint(obj: Any) -> str:
    """Stable short hash of a JSON-able object (payload dumps)."""
    data = json.dumps(obj, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(data).hexdigest()[:16]


class EventJournal:
    """Bounded, lock-disciplined, append-only structured event log."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = env_int("SDTPU_JOURNAL_MAX", DEFAULT_CAPACITY)
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._seq = 0                                      # guarded-by: _lock
        # request_id -> seq of its latest event, for causal chaining
        self._last_by_rid: OrderedDict = OrderedDict()     # guarded-by: _lock
        # Sink spill state kept under its own lock so the file write
        # never happens while _lock is held.
        self._sink_lock = threading.Lock()
        self._sink_spilled = 0                             # guarded-by: _sink_lock
        self._sink_bytes = 0                               # guarded-by: _sink_lock
        self._sink_rotations = 0                           # guarded-by: _sink_lock
        self._sink_seen = ""                               # guarded-by: _sink_lock

    def emit(self, event: str, request_id: str,
             parent: Optional[int] = None,
             **attrs: Any) -> Optional[Dict[str, Any]]:
        """Append one event; no-op (returns None) when the journal is off.

        ``parent`` defaults to the request's previous event seq; pass it
        explicitly to splice causality across requests (e.g. a coalesce
        follower pointing at the leader's event).
        """
        if not enabled():
            return None
        if event not in EVENTS:
            raise ValueError(f"unregistered journal event {event!r}; "
                             f"add it to obs.journal.EVENTS")
        rid = str(request_id)
        t_mono = time.monotonic()
        sink = sink_path()
        spill = None
        with self._lock:
            self._seq += 1
            if parent is None:
                parent = self._last_by_rid.get(rid)
            entry = {
                "seq": self._seq,
                "event": event,
                "request_id": rid,
                "t_mono": t_mono,
                "parent": parent,
                "attrs": dict(attrs),
            }
            if sink and len(self._events) == self._events.maxlen:
                spill = self._events[0]
            self._events.append(entry)
            self._last_by_rid[rid] = self._seq
            self._last_by_rid.move_to_end(rid)
            while len(self._last_by_rid) > _PARENT_INDEX_CAP:
                self._last_by_rid.popitem(last=False)
        if spill is not None:
            self._spill(sink, spill)
        return entry

    def _spill(self, sink: str, entry: Dict[str, Any]) -> None:
        """Best-effort JSONL append of one evicted event. Concurrent
        evictions may land out of seq order; sink consumers sort by seq.
        With ``SDTPU_JOURNAL_SINK_MAX_MB`` set, a write that would push
        the file past the cap first rotates it to ``<sink>.1`` (single
        rollover; ``tools/replay.py`` loads the pair in order)."""
        try:
            line = json.dumps(entry, sort_keys=True, default=str) + "\n"
            cap = sink_max_bytes()
            with self._sink_lock:
                if sink != self._sink_seen:
                    # fresh sink path: adopt whatever is already on disk
                    # so the cap covers pre-existing bytes too
                    self._sink_seen = sink
                    try:
                        self._sink_bytes = os.path.getsize(sink)
                    except OSError:
                        self._sink_bytes = 0
                if cap > 0 and self._sink_bytes > 0 \
                        and self._sink_bytes + len(line) > cap:
                    try:
                        os.replace(sink, sink + ".1")
                        self._sink_rotations += 1
                        self._sink_bytes = 0
                    except OSError:
                        pass  # keep appending; rotation is best-effort
                with open(sink, "a", encoding="utf-8") as fh:
                    fh.write(line)
                self._sink_spilled += 1
                self._sink_bytes += len(line)
        except OSError:
            pass

    def sink_status(self) -> Dict[str, Any]:
        """Sink configuration + spill/rotation accounting (surfaced via
        /internal/sim; kept out of snapshot(), whose schema is pinned by
        tests)."""
        with self._sink_lock:
            spilled = self._sink_spilled
            nbytes = self._sink_bytes
            rotations = self._sink_rotations
        return {"path": sink_path(), "spilled": spilled,
                "bytes": nbytes, "rotations": rotations}

    def events_for(self, request_id: str) -> List[Dict[str, Any]]:
        """The journal slice for one request, in seq order."""
        rid = str(request_id)
        with self._lock:
            return [dict(e) for e in self._events if e["request_id"] == rid]

    def snapshot(self, request_id: Optional[str] = None) -> Dict[str, Any]:
        """The ``/internal/journal`` document."""
        with self._lock:
            if request_id:
                events = [dict(e) for e in self._events
                          if e["request_id"] == str(request_id)]
            else:
                events = [dict(e) for e in self._events]
            total = self._seq
        return {
            "enabled": enabled(),
            "capacity": self.capacity,
            "count": len(events),
            "total_emitted": total,
            "events": events,
        }

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._last_by_rid.clear()
            self._seq = 0
        with self._sink_lock:
            self._sink_spilled = 0
            self._sink_bytes = 0
            self._sink_rotations = 0
            self._sink_seen = ""

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


#: Process-wide journal. Capacity is re-resolved only at construction;
#: tests that need a different bound construct their own EventJournal.
JOURNAL = EventJournal()


def emit(event: str, request_id: str, parent: Optional[int] = None,
         **attrs: Any) -> Optional[Dict[str, Any]]:
    """Module-level convenience for :meth:`EventJournal.emit`."""
    return JOURNAL.emit(event, request_id, parent=parent, **attrs)

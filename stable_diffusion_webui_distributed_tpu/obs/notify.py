"""Alert notification delivery (SDTPU_NOTIFY_URL / SDTPU_NOTIFY_ROUTES).

The alert engine (obs/alerts.py) journals ``alert_firing`` /
``alert_resolved`` transitions and exports them as metrics, but nothing
leaves the process — an operator learns about a 3am burn-rate page by
polling ``/internal/alerts``. This module is the delivery channel: every
firing/resolved transition is routed by its *severity* to a channel,
enqueued onto that channel's bounded in-memory queue, and drained by a
daemon thread that POSTs one JSON document per transition to the
channel's webhook URL.

Routing: ``SDTPU_NOTIFY_ROUTES`` maps severities (and tenant-scoped
overrides) to URLs — ``page=<url1>,warn=<url2>`` sends pages to url1
and warnings to url2; a ``tenantA:page=<url3>`` entry overrides the
page route for transitions carrying ``tenant="tenantA"``. Lookup order
is ``tenant:severity`` → ``severity`` → the ``SDTPU_NOTIFY_URL``
default channel. A transition whose severity has no route and no
default URL is not queued (same as the gate being off). With only
``SDTPU_NOTIFY_URL`` set there is exactly one channel ("default") and
behavior is identical to the single-URL notifier.

Delivery discipline (per channel):

- **off-thread, never under a lock** — the queue hand-off is the only
  locked region; the HTTP POST, its retries, and the backoff sleeps all
  run on the drain thread with no lock held (LK004).
- **retry + exponential backoff** — ``_MAX_ATTEMPTS`` tries per
  transition, sleeping ``_BACKOFF_BASE_S * 2**attempt`` between them;
  a transition that exhausts its attempts is counted and journaled as
  failed, never re-queued (the queue must drain even with the webhook
  down).
- **dedup** — an identical (channel, rule, event) transition enqueued
  within ``SDTPU_NOTIFY_DEDUP_S`` seconds of the previous one is
  dropped (outcome ``deduped``), so a flapping rule cannot page-storm.
- **bounded** — past ``_MAX_QUEUE`` undelivered transitions per channel
  the newest is dropped (outcome ``dropped``, journaled as
  ``notify_dropped`` and surfaced in :meth:`Notifier.summary` — paging
  loss must be visible, not just a counter); lag must not grow memory.

Every outcome bumps ``sdtpu_notify_total{channel,outcome}`` and
delivery results journal through the closed vocabulary
(``notify_sent`` / ``notify_failed`` / ``notify_dropped``) when the
journal is on. The POST timeout comes from the obs-plane-wide
``SDTPU_OBS_HTTP_TIMEOUT_S`` knob (obs/stitch.py).

Gated off by default: with ``SDTPU_NOTIFY_URL`` and
``SDTPU_NOTIFY_ROUTES`` both empty (the default)
:func:`notify_transition` returns before touching any queue and no
thread ever starts — the serving path is byte-identical to the
unnotified build (hash-pinned in tests/test_federation.py).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

from ..runtime.config import env_float, env_str
from ..runtime.daemon import StoppableDaemon
from . import stitch

#: Undelivered-transition queue depth per channel; the newest transition
#: past it is dropped (paging lag must not grow memory without bound).
_MAX_QUEUE = 256

#: Delivery attempts per transition before it counts as failed.
_MAX_ATTEMPTS = 3

#: Backoff base: sleep ``_BACKOFF_BASE_S * 2**attempt`` between tries.
_BACKOFF_BASE_S = 0.05

#: Idle re-check cadence of the drain daemon; ``wake()`` on enqueue cuts
#: it short, so this only bounds shutdown/straggler latency.
_DRAIN_PERIOD_S = 0.2

DEFAULT_DEDUP_S = 60.0

#: Channel name of the single-URL (SDTPU_NOTIFY_URL) route.
DEFAULT_CHANNEL = "default"


def enabled() -> bool:
    """Notify gate — any configured route arms delivery."""
    return bool(url()) or bool(routes())


def url() -> str:
    """Default-channel webhook endpoint (SDTPU_NOTIFY_URL); '' = none."""
    return env_str("SDTPU_NOTIFY_URL", "")


def routes() -> Dict[str, str]:
    """Severity-routing table (SDTPU_NOTIFY_ROUTES): comma-separated
    ``key=url`` entries where ``key`` is a severity (``page``/``warn``/
    ``info``) or a tenant-scoped override (``tenant:severity``).
    Malformed entries are skipped; URLs must not contain commas."""
    out: Dict[str, str] = {}
    for part in env_str("SDTPU_NOTIFY_ROUTES", "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        key, target = part.split("=", 1)
        key, target = key.strip(), target.strip()
        if key and target:
            out[key] = target
    return out


def channel_for(severity: str,
                tenant: Optional[str] = None) -> Optional[Tuple[str, str]]:
    """Resolve a transition's (channel name, URL): the tenant-scoped
    route wins, then the severity route, then the SDTPU_NOTIFY_URL
    default channel; None when nothing is configured for it."""
    table = routes()
    sev = str(severity)
    if tenant:
        key = f"{tenant}:{sev}"
        if key in table:
            return key, table[key]
    if sev in table:
        return sev, table[sev]
    base = url()
    if base:
        return DEFAULT_CHANNEL, base
    return None


def dedup_s() -> float:
    """Dedup window: identical (channel, rule, event) transitions inside
    it are dropped instead of delivered twice (SDTPU_NOTIFY_DEDUP_S)."""
    return max(0.0, env_float("SDTPU_NOTIFY_DEDUP_S", DEFAULT_DEDUP_S))


class Notifier:
    """Per-channel bounded queues + one daemon drain thread."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        # channel -> FIFO of undelivered items         guarded-by: _lock
        self._queues: Dict[str, Deque[Dict[str, Any]]] = {}
        # (channel, rule, event) -> enqueue time of the last accepted
        self._last_sent: Dict[Any, float] = {}         # guarded-by: _lock
        # channel -> outcome -> count                  guarded-by: _lock
        self._counts: Dict[str, Dict[str, int]] = {}   # guarded-by: _lock
        self._pending = 0                              # guarded-by: _lock
        self._daemon = StoppableDaemon("sdtpu-notify-drain",
                                       self._drain_once, _DRAIN_PERIOD_S)

    # -- enqueue (alert-engine side; cheap, lock only for the hand-off) ----

    def notify_transition(self, rule: str, event: str, value: Any,
                          detail: str, *, severity: str = "warn",
                          tenant: Optional[str] = None,
                          force: bool = False) -> bool:
        """Route + queue one firing/resolved transition for delivery;
        returns True when it was accepted (not deduped/dropped/gated
        off). ``force=True`` bypasses the env gate — the
        schedule-explorer harness exercises the queue/drain protocol
        without a URL."""
        route = channel_for(severity, tenant)
        if route is None:
            if not force:
                return False
            # forced (harness-seam) transitions with no configured route
            # land on a channel named by their severity, so the
            # multi-channel queue/drain protocol is exercisable without
            # any env routes (EV001 — sim/harnesses.py)
            route = (str(severity) or DEFAULT_CHANNEL, "")
        channel = route[0]
        now = self._clock()
        item = {"rule": str(rule), "event": str(event), "value": value,
                "detail": str(detail), "severity": str(severity),
                "channel": channel}
        if tenant:
            item["tenant"] = str(tenant)
        key = (channel, item["rule"], item["event"])
        rejected = None
        with self._lock:
            q = self._queues.setdefault(channel, deque())
            last = self._last_sent.get(key)
            if last is not None and now - last < dedup_s():
                rejected = "deduped"
            elif len(q) >= _MAX_QUEUE:
                rejected = "dropped"
            else:
                self._last_sent[key] = now
                q.append(item)
                self._pending += 1
            if rejected is not None:
                per = self._counts.setdefault(channel, {})
                per[rejected] = per.get(rejected, 0) + 1
        if rejected is not None:
            _count_outcome(rejected, channel)
            if rejected == "dropped":
                _journal_dropped(item)
            return False
        self._daemon.start()  # idempotent; restart-safe after stop()
        self._daemon.wake()
        return True

    # -- drain daemon (all blocking work lives here, no locks held) --------

    def _next_item(self) -> Optional[Dict[str, Any]]:
        """Pop the head of the first non-empty channel queue, rotating
        that channel to the back so a busy page channel cannot starve
        the warn/info channels."""
        with self._lock:
            for name in list(self._queues):
                q = self._queues[name]
                if q:
                    self._queues[name] = self._queues.pop(name)
                    return q.popleft()
        return None

    def _drain_once(self) -> None:
        """One daemon tick: drain everything queued right now."""
        while not self._daemon.stopped():
            item = self._next_item()
            if item is None:
                return
            delivered, attempts = self._deliver(item)
            outcome = "sent" if delivered else "failed"
            channel = item.get("channel", DEFAULT_CHANNEL)
            with self._lock:
                self._pending -= 1
                per = self._counts.setdefault(channel, {})
                per[outcome] = per.get(outcome, 0) + 1
            _count_outcome(outcome, channel)
            _journal_outcome(item, delivered, attempts)

    def _deliver(self, item: Dict[str, Any]) -> "tuple[bool, int]":
        """POST one transition with retry + exponential backoff; returns
        (delivered, attempts). Runs on the drain thread only — never
        call with any lock held (LK004). The URL is re-resolved from the
        routing table at delivery time so env flips apply mid-queue."""
        channel = item.get("channel", DEFAULT_CHANNEL)
        target = routes().get(channel) or (
            url() if channel == DEFAULT_CHANNEL else "")
        if not target:
            return False, 0
        body = dict(item)
        body["ts"] = time.time()  # sdtpu-lint: wallclock — pager-facing timestamp
        data = json.dumps(body, sort_keys=True, default=str).encode("utf-8")
        timeout = stitch.http_timeout_s()
        for attempt in range(_MAX_ATTEMPTS):
            if attempt:
                time.sleep(_BACKOFF_BASE_S * (2 ** (attempt - 1)))
            try:
                req = urllib.request.Request(
                    target, data=data,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    if 200 <= resp.status < 300:
                        return True, attempt + 1
            except Exception:  # noqa: BLE001 — delivery is best-effort
                pass
        return False, _MAX_ATTEMPTS

    # -- synchronization + views -------------------------------------------

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until every queued transition has a delivery outcome
        (tests/bench determinism); False on timeout."""
        deadline = self._clock() + max(0.0, timeout_s)
        while True:
            with self._lock:
                pending = self._pending
            if pending <= 0:
                return True
            if self._clock() >= deadline:
                return False
            self._daemon.wake()
            time.sleep(0.005)

    def stop(self) -> None:
        self._daemon.stop(timeout_s=2.0)

    def counts(self) -> Dict[str, int]:
        """Outcome counts aggregated across channels (the single-channel
        notifier's historical shape)."""
        with self._lock:
            out: Dict[str, int] = {}
            for per in self._counts.values():
                for outcome, n in per.items():
                    out[outcome] = out.get(outcome, 0) + n
            return out

    def counts_by_channel(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {ch: dict(per) for ch, per in self._counts.items()}

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            per_queue = {ch: len(q) for ch, q in self._queues.items()}
            pending = self._pending
            by_channel = {ch: dict(per) for ch, per in self._counts.items()}
        counts: Dict[str, int] = {}
        for per in by_channel.values():
            for outcome, n in per.items():
                counts[outcome] = counts.get(outcome, 0) + n
        channels = {}
        for ch in sorted(set(per_queue) | set(by_channel)):
            channels[ch] = {"queued": per_queue.get(ch, 0),
                            "outcomes": by_channel.get(ch, {})}
        alive = self._daemon.alive()
        return {"enabled": enabled(), "dedup_s": dedup_s(),
                "queued": sum(per_queue.values()), "pending": pending,
                "outcomes": counts, "dropped": counts.get("dropped", 0),
                "draining": alive, "channels": channels}


def _count_outcome(outcome: str, channel: str = DEFAULT_CHANNEL) -> None:
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        obs_prom.notify_count(outcome, channel=channel)
    except Exception:  # noqa: BLE001 — telemetry stays passive
        pass


def _journal_outcome(item: Dict[str, Any], delivered: bool,
                     attempts: int) -> None:
    """Journal one delivery outcome (URL deliberately omitted: webhook
    URLs routinely embed tokens and the journal is replayable)."""
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
        )

        if obs_journal.enabled():
            obs_journal.emit(
                "notify_sent" if delivered else "notify_failed",
                f"notify-{item.get('rule', '')}",
                rule=item.get("rule"), alert_event=item.get("event"),
                severity=item.get("severity"),
                channel=item.get("channel"), attempts=attempts)
    except Exception:  # noqa: BLE001 — telemetry stays passive
        pass


def _journal_dropped(item: Dict[str, Any]) -> None:
    """Journal one queue-overflow drop (no URL, same token discipline):
    a page that never left the process must be visible in the decision
    trail, not just a counter."""
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
        )

        if obs_journal.enabled():
            obs_journal.emit(
                "notify_dropped", f"notify-{item.get('rule', '')}",
                rule=item.get("rule"), alert_event=item.get("event"),
                severity=item.get("severity"),
                channel=item.get("channel"))
    except Exception:  # noqa: BLE001 — telemetry stays passive
        pass


#: Process-wide notifier (the alert engine feeds it). Tests construct
#: their own or call :func:`reset` after flipping the env knobs.
NOTIFIER = Notifier()


def notify_transition(rule: str, event: str, value: Any, detail: str, *,
                      severity: str = "warn",
                      tenant: Optional[str] = None) -> bool:
    """Module-level convenience for :meth:`Notifier.notify_transition`;
    no-op (False) with no route configured for the severity."""
    return NOTIFIER.notify_transition(rule, event, value, detail,
                                      severity=severity, tenant=tenant)


def flush(timeout_s: float = 5.0) -> bool:
    return NOTIFIER.flush(timeout_s)


def reset() -> None:
    """Stop the drain thread and rebuild the notifier (tests/bench)."""
    global NOTIFIER
    NOTIFIER.stop()
    NOTIFIER = Notifier()


def summary() -> Dict[str, Any]:
    return NOTIFIER.summary()

"""Alert notification delivery (SDTPU_NOTIFY_URL): webhook paging.

The alert engine (obs/alerts.py) journals ``alert_firing`` /
``alert_resolved`` transitions and exports them as metrics, but nothing
leaves the process — an operator learns about a 3am burn-rate page by
polling ``/internal/alerts``. This module is the delivery channel: every
firing/resolved transition is enqueued onto a bounded in-memory queue
and drained by a daemon thread that POSTs one JSON document per
transition to the configured webhook URL.

Delivery discipline:

- **off-thread, never under a lock** — the queue hand-off is the only
  locked region; the HTTP POST, its retries, and the backoff sleeps all
  run on the drain thread with no lock held (LK004).
- **retry + exponential backoff** — ``_MAX_ATTEMPTS`` tries per
  transition, sleeping ``_BACKOFF_BASE_S * 2**attempt`` between them;
  a transition that exhausts its attempts is counted and journaled as
  failed, never re-queued (the queue must drain even with the webhook
  down).
- **dedup** — an identical (rule, event) transition enqueued within
  ``SDTPU_NOTIFY_DEDUP_S`` seconds of the previous one is dropped
  (outcome ``deduped``), so a flapping rule cannot page-storm.
- **bounded** — past ``_MAX_QUEUE`` undelivered transitions the newest
  is dropped (outcome ``dropped``); paging lag must not grow memory.

Every outcome bumps ``sdtpu_notify_total{outcome}`` and delivery
results journal through the closed vocabulary (``notify_sent`` /
``notify_failed``) when the journal is on. The POST timeout comes from
the obs-plane-wide ``SDTPU_OBS_HTTP_TIMEOUT_S`` knob (obs/stitch.py).

Gated off by default: an empty ``SDTPU_NOTIFY_URL`` (the default) means
:func:`notify_transition` returns before touching the queue and no
thread ever starts — the serving path is byte-identical to the
unnotified build (hash-pinned in tests/test_federation.py).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Deque, Dict, Optional

from ..runtime.config import env_float, env_str
from ..runtime.daemon import StoppableDaemon
from . import stitch

#: Undelivered-transition queue depth; the newest transition past it is
#: dropped (paging lag must not grow memory without bound).
_MAX_QUEUE = 256

#: Delivery attempts per transition before it counts as failed.
_MAX_ATTEMPTS = 3

#: Backoff base: sleep ``_BACKOFF_BASE_S * 2**attempt`` between tries.
_BACKOFF_BASE_S = 0.05

#: Idle re-check cadence of the drain daemon; ``wake()`` on enqueue cuts
#: it short, so this only bounds shutdown/straggler latency.
_DRAIN_PERIOD_S = 0.2

DEFAULT_DEDUP_S = 60.0


def enabled() -> bool:
    """Notify gate — a non-empty webhook URL arms delivery."""
    return bool(url())


def url() -> str:
    """Webhook endpoint (SDTPU_NOTIFY_URL); '' = delivery off."""
    return env_str("SDTPU_NOTIFY_URL", "")


def dedup_s() -> float:
    """Dedup window: identical (rule, event) transitions inside it are
    dropped instead of delivered twice (SDTPU_NOTIFY_DEDUP_S)."""
    return max(0.0, env_float("SDTPU_NOTIFY_DEDUP_S", DEFAULT_DEDUP_S))


class Notifier:
    """Bounded queue + daemon drain thread for webhook delivery."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._queue: Deque[Dict[str, Any]] = deque()   # guarded-by: _lock
        # (rule, event) -> enqueue time of the last accepted transition
        self._last_sent: Dict[Any, float] = {}         # guarded-by: _lock
        self._counts: Dict[str, int] = {}              # guarded-by: _lock
        self._pending = 0                              # guarded-by: _lock
        self._daemon = StoppableDaemon("sdtpu-notify-drain",
                                       self._drain_once, _DRAIN_PERIOD_S)

    # -- enqueue (alert-engine side; cheap, lock only for the hand-off) ----

    def notify_transition(self, rule: str, event: str, value: Any,
                          detail: str, *, force: bool = False) -> bool:
        """Queue one firing/resolved transition for delivery; returns
        True when it was accepted (not deduped/dropped/gated off).
        ``force=True`` bypasses the env gate — the schedule-explorer
        harness exercises the queue/drain protocol without a URL."""
        if not force and not enabled():
            return False
        now = self._clock()
        item = {"rule": str(rule), "event": str(event), "value": value,
                "detail": str(detail)}
        key = (item["rule"], item["event"])
        rejected = None
        with self._lock:
            last = self._last_sent.get(key)
            if last is not None and now - last < dedup_s():
                rejected = "deduped"
            elif len(self._queue) >= _MAX_QUEUE:
                rejected = "dropped"
            else:
                self._last_sent[key] = now
                self._queue.append(item)
                self._pending += 1
            if rejected is not None:
                self._counts[rejected] = self._counts.get(rejected, 0) + 1
        if rejected is not None:
            _count_outcome(rejected)
            return False
        self._daemon.start()  # idempotent; restart-safe after stop()
        self._daemon.wake()
        return True

    # -- drain daemon (all blocking work lives here, no locks held) --------

    def _drain_once(self) -> None:
        """One daemon tick: drain everything queued right now."""
        while not self._daemon.stopped():
            with self._lock:
                if not self._queue:
                    return
                item = self._queue.popleft()
            delivered, attempts = self._deliver(item)
            outcome = "sent" if delivered else "failed"
            with self._lock:
                self._pending -= 1
                self._counts[outcome] = self._counts.get(outcome, 0) + 1
            _count_outcome(outcome)
            _journal_outcome(item, delivered, attempts)

    def _deliver(self, item: Dict[str, Any]) -> "tuple[bool, int]":
        """POST one transition with retry + exponential backoff; returns
        (delivered, attempts). Runs on the drain thread only — never
        call with any lock held (LK004)."""
        target = url()
        if not target:
            return False, 0
        body = dict(item)
        body["ts"] = time.time()  # sdtpu-lint: wallclock — pager-facing timestamp
        data = json.dumps(body, sort_keys=True, default=str).encode("utf-8")
        timeout = stitch.http_timeout_s()
        for attempt in range(_MAX_ATTEMPTS):
            if attempt:
                time.sleep(_BACKOFF_BASE_S * (2 ** (attempt - 1)))
            try:
                req = urllib.request.Request(
                    target, data=data,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    if 200 <= resp.status < 300:
                        return True, attempt + 1
            except Exception:  # noqa: BLE001 — delivery is best-effort
                pass
        return False, _MAX_ATTEMPTS

    # -- synchronization + views -------------------------------------------

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until every queued transition has a delivery outcome
        (tests/bench determinism); False on timeout."""
        deadline = self._clock() + max(0.0, timeout_s)
        while True:
            with self._lock:
                pending = self._pending
            if pending <= 0:
                return True
            if self._clock() >= deadline:
                return False
            self._daemon.wake()
            time.sleep(0.005)

    def stop(self) -> None:
        self._daemon.stop(timeout_s=2.0)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            queued = len(self._queue)
            pending = self._pending
            counts = dict(self._counts)
        alive = self._daemon.alive()
        return {"enabled": enabled(), "dedup_s": dedup_s(),
                "queued": queued, "pending": pending,
                "outcomes": counts, "draining": alive}


def _count_outcome(outcome: str) -> None:
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        obs_prom.notify_count(outcome)
    except Exception:  # noqa: BLE001 — telemetry stays passive
        pass


def _journal_outcome(item: Dict[str, Any], delivered: bool,
                     attempts: int) -> None:
    """Journal one delivery outcome (URL deliberately omitted: webhook
    URLs routinely embed tokens and the journal is replayable)."""
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
        )

        if obs_journal.enabled():
            obs_journal.emit(
                "notify_sent" if delivered else "notify_failed",
                f"notify-{item.get('rule', '')}",
                rule=item.get("rule"), alert_event=item.get("event"),
                attempts=attempts)
    except Exception:  # noqa: BLE001 — telemetry stays passive
        pass


#: Process-wide notifier (the alert engine feeds it). Tests construct
#: their own or call :func:`reset` after flipping the env knobs.
NOTIFIER = Notifier()


def notify_transition(rule: str, event: str, value: Any,
                      detail: str) -> bool:
    """Module-level convenience for :meth:`Notifier.notify_transition`;
    no-op (False) with SDTPU_NOTIFY_URL unset."""
    return NOTIFIER.notify_transition(rule, event, value, detail)


def flush(timeout_s: float = 5.0) -> bool:
    return NOTIFIER.flush(timeout_s)


def reset() -> None:
    """Stop the drain thread and rebuild the notifier (tests/bench)."""
    global NOTIFIER
    NOTIFIER.stop()
    NOTIFIER = Notifier()


def summary() -> Dict[str, Any]:
    return NOTIFIER.summary()

"""Per-request span trees behind a ``contextvars`` request context.

One :class:`SpanTracer` (the module singleton :data:`TRACER`) holds every
in-flight and recently-finished request trace. A request context is minted
at API ingress (or lazily by the serving dispatcher for direct callers) via
:func:`request`; any code on that thread — or on a thread entered through
:func:`bind_current` — can then open child spans with :func:`span`, and
``runtime/trace.py`` feeds every ``StageStats.timer`` block in as a leaf
span automatically (:func:`stage_event`).

Coalesced dispatches link leader and followers: the leader's device span is
mirrored into each follower's trace with ``leader_request_id`` /
``leader_span_id`` attrs (:func:`mirror_span`), so a follower's tree still
shows where its wall-clock went even though another request drove the TPU.

Timing is host-side ``time.perf_counter()`` only — recording a span never
syncs the device. The store is bounded (``SDTPU_OBS_MAX_REQUESTS`` finished
traces) and lock-disciplined: one lock, nothing external called while
holding it. Export is Chrome trace-event JSON ("X" complete events with
ph/ts/dur/pid/tid), loadable in Perfetto / ``chrome://tracing``.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import itertools
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

from stable_diffusion_webui_distributed_tpu.obs import flightrec, prometheus
from stable_diffusion_webui_distributed_tpu.runtime.config import (
    env_flag, env_float, env_int,
)

#: Finished request traces retained for /internal/trace.json.
DEFAULT_MAX_REQUESTS = 256
#: e2e latency (seconds) above which a request is flight-recorded as a
#: slow outlier; 0 disables slow capture.
DEFAULT_SLOW_S = 30.0

#: perf_counter base for trace-event timestamps (µs since process start of
#: tracing, not wall clock — Perfetto only needs a shared monotonic base).
_EPOCH = time.perf_counter()
_PID = os.getpid()

#: Process-wide span-id allocator. ``next()`` on itertools.count is atomic
#: under the GIL, so ids are unique without touching the tracer lock.
_IDS = itertools.count(1)

#: (RequestTrace, parent span id) for the code currently executing, or None
#: outside any request. Thread- and contextvars-scoped: HTTP handler
#: threads each see only their own request.
_CURRENT: "contextvars.ContextVar[Optional[Tuple[RequestTrace, int]]]" = \
    contextvars.ContextVar("sdtpu_obs_request", default=None)  # sdtpu-lint: metric


class Span:
    """One timed region. ``t0`` is perf_counter seconds, ``dur`` seconds."""

    __slots__ = ("span_id", "parent_id", "name", "t0", "dur", "tid", "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 t0: float, dur: float, tid: int,
                 attrs: Dict[str, Any]) -> None:
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.dur = dur
        self.tid = tid
        self.attrs = attrs


class RequestTrace:
    """All spans of one request plus its terminal status."""

    __slots__ = ("request_id", "name", "attrs", "t0", "dur", "status",
                 "detail", "spans", "root_id")

    def __init__(self, request_id: str, name: str,
                 attrs: Dict[str, Any]) -> None:
        self.request_id = request_id
        self.name = name
        self.attrs = attrs
        self.t0 = time.perf_counter()
        self.dur = 0.0
        self.status = "active"  # active | ok | error | interrupted | slow
        self.detail = ""
        self.spans: List[Span] = []  # appended under TRACER's lock
        self.root_id = next(_IDS)


def _span_event(req: RequestTrace, sp: Span) -> Dict[str, Any]:
    """One Chrome trace-event ("X" = complete event, timestamps in µs)."""
    args: Dict[str, Any] = {"request_id": req.request_id,
                            "span_id": sp.span_id}
    if sp.parent_id is not None:
        args["parent_id"] = sp.parent_id
    for k, v in sp.attrs.items():
        args.setdefault(str(k), v)
    return {
        "ph": "X",
        "cat": "sdtpu",
        "name": sp.name,
        "pid": _PID,
        "tid": sp.tid,
        "ts": (sp.t0 - _EPOCH) * 1e6,
        "dur": sp.dur * 1e6,
        "args": args,
    }


class SpanTracer:
    """Bounded, lock-disciplined store of request traces."""

    def __init__(self, enabled: Optional[bool] = None,
                 max_requests: Optional[int] = None,
                 slow_s: Optional[float] = None) -> None:
        if enabled is None:
            enabled = env_flag("SDTPU_OBS", True)
        if max_requests is None:
            max_requests = env_int("SDTPU_OBS_MAX_REQUESTS",
                                   DEFAULT_MAX_REQUESTS)
        if slow_s is None:
            slow_s = env_float("SDTPU_OBS_SLOW_S", DEFAULT_SLOW_S)
        #: set once at construction; tests flip it to measure overhead
        self.enabled = bool(enabled)
        self.slow_s = max(0.0, float(slow_s or 0.0))
        self._lock = threading.Lock()
        self._active: Dict[str, RequestTrace] = {}  # guarded-by: _lock
        self._done: Deque[RequestTrace] = deque(
            maxlen=max(1, int(max_requests or DEFAULT_MAX_REQUESTS)))  # guarded-by: _lock

    # -- store ------------------------------------------------------------

    def open(self, req: RequestTrace) -> None:
        with self._lock:
            self._active[req.request_id] = req

    def close(self, req: RequestTrace) -> None:
        with self._lock:
            self._active.pop(req.request_id, None)
            self._done.append(req)

    def record(self, req: Optional[RequestTrace], sp: Span) -> None:
        """Append a finished span to a trace (any thread)."""
        if req is None or not self.enabled:
            return
        with self._lock:
            req.spans.append(sp)

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._done.clear()

    # -- export -----------------------------------------------------------

    def export_chrome(self) -> Dict[str, Any]:
        """All retained traces as a Chrome trace-event JSON object."""
        events: List[Dict[str, Any]] = []
        with self._lock:
            reqs = list(self._done) + list(self._active.values())
            for req in reqs:
                for sp in req.spans:
                    events.append(_span_event(req, sp))
        # clock_us lets a remote puller (obs/stitch.py) estimate this
        # process's trace-clock offset from one RTT-bracketed fetch.
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "clock_us": now_us()}

    def events_for(self, req: RequestTrace) -> List[Dict[str, Any]]:
        with self._lock:
            return [_span_event(req, sp) for sp in req.spans]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "active": len(self._active),
                "retained": len(self._done),
                "capacity": self._done.maxlen,
                "slow_threshold_s": self.slow_s,
            }

    def finished(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._done)


#: Process-wide tracer (mirrors trace.STATS / metrics.METRICS).
TRACER = SpanTracer()


# -- request / span context managers ----------------------------------------

@contextlib.contextmanager
def request(request_id: Optional[str] = None, name: str = "request",
            **attrs: Any) -> Iterator[Optional[RequestTrace]]:
    """Root context for one request. Mints/propagates the request id, opens
    the root span, and on exit records e2e latency, feeds the e2e histogram
    and hands failed/interrupted/slow traces to the flight recorder."""
    tr = TRACER
    if not tr.enabled:
        yield None
        return
    rid = str(request_id or uuid.uuid4().hex)
    req = RequestTrace(rid, name, dict(attrs))
    tr.open(req)
    token = _CURRENT.set((req, req.root_id))
    error: Optional[str] = None
    try:
        yield req
    except BaseException as e:  # noqa: BLE001 — recorded, then re-raised
        error = f"{type(e).__name__}: {e}"
        raise
    finally:
        _CURRENT.reset(token)
        _finish(tr, req, error)


def _finish(tr: SpanTracer, req: RequestTrace, error: Optional[str]) -> None:
    req.dur = time.perf_counter() - req.t0
    if error is not None:
        req.status, req.detail = "error", error
    elif req.status == "interrupted":
        pass  # marked mid-flight by cancel/interrupt
    elif tr.slow_s > 0 and req.dur >= tr.slow_s:
        req.status = "slow"
        req.detail = f"e2e {req.dur:.3f}s >= {tr.slow_s:.3f}s threshold"
    else:
        req.status = "ok"
    root = Span(req.root_id, None, req.name, req.t0, req.dur,
                threading.get_ident(), dict(req.attrs, status=req.status))
    tr.record(req, root)
    tr.close(req)
    prometheus.observe_hist("e2e", req.dur)
    if req.status != "ok":
        flightrec.RECORDER.record(
            request_id=req.request_id, reason=req.status, detail=req.detail,
            duration_s=req.dur, events=tr.events_for(req))


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[Optional[Span]]:
    """Child span under the active request; cheap no-op outside one."""
    tr = TRACER
    ctx = _CURRENT.get()
    if ctx is None or not tr.enabled:
        yield None
        return
    req, parent = ctx
    sp = Span(next(_IDS), parent, name, time.perf_counter(), 0.0,
              threading.get_ident(), dict(attrs))
    token = _CURRENT.set((req, sp.span_id))
    try:
        yield sp
    finally:
        _CURRENT.reset(token)
        sp.dur = time.perf_counter() - sp.t0
        tr.record(req, sp)


@contextlib.contextmanager
def maybe_request(request_id: Optional[str] = None, name: str = "request",
                  **attrs: Any) -> Iterator[Optional[RequestTrace]]:
    """:func:`request` unless one is already active (the HTTP ingress minted
    it); then just yield the active trace. Lets the dispatcher serve both
    API traffic and direct callers without double-rooting."""
    ctx = _CURRENT.get()
    if ctx is not None:
        yield ctx[0]
        return
    with request(request_id, name, **attrs) as req:
        yield req


# -- cross-thread / cross-request recording ----------------------------------

def now_us() -> float:
    """Current trace-clock reading (µs on the same base as event ``ts``)."""
    return (time.perf_counter() - _EPOCH) * 1e6


def traceparent() -> Optional[str]:
    """W3C traceparent for the active request (trace id derived from the
    request id so every hop agrees without coordination), or None outside
    a request context."""
    ctx = _CURRENT.get()
    if ctx is None:
        return None
    req, parent = ctx
    trace_id = hashlib.sha256(req.request_id.encode("utf-8")).hexdigest()[:32]
    span_id = f"{parent & ((1 << 64) - 1):016x}"
    return f"00-{trace_id}-{span_id}-01"


def current() -> Optional[RequestTrace]:
    ctx = _CURRENT.get()
    return None if ctx is None else ctx[0]


def current_request_id() -> Optional[str]:
    ctx = _CURRENT.get()
    return None if ctx is None else ctx[0].request_id


def add_span(req: Optional[RequestTrace], name: str, t0: float, dur: float,
             attrs: Optional[Dict[str, Any]] = None,
             parent_id: Optional[int] = None,
             lane: Optional[int] = None) -> Optional[Span]:
    """Record an already-measured interval into ``req`` from any thread
    (the coalesce leader records queue waits for its followers).

    ``lane`` overrides the span's tid: the stage-graph executor assigns
    each stage kind a fixed negative lane (parallel/stage_graph.py LANES)
    so /internal/trace.json renders overlapped stages from different
    groups on per-stage swimlanes instead of one thread row."""
    if req is None or not TRACER.enabled:
        return None
    sp = Span(next(_IDS), req.root_id if parent_id is None else parent_id,
              name, t0, max(0.0, dur),
              threading.get_ident() if lane is None else lane,
              dict(attrs or {}))
    TRACER.record(req, sp)
    return sp


def mirror_span(req: Optional[RequestTrace], name: str, src: Optional[Span],
                **attrs: Any) -> Optional[Span]:
    """Copy ``src``'s interval into another request's trace — the
    leader/follower link for coalesced dispatches."""
    if req is None or src is None:
        return None
    return add_span(req, name, src.t0, src.dur, attrs=dict(attrs))


def mark(req: Optional[RequestTrace], status: str, detail: str = "") -> None:
    """Flag an in-flight request (e.g. "interrupted"); picked up when its
    root context exits."""
    if req is None:
        return
    req.status = status
    if detail:
        req.detail = detail


def stage_event(stage: str, seconds: float,
                t0: Optional[float] = None) -> None:
    """Leaf span + stage histogram for one ``StageStats.timer`` block
    (called by runtime/trace.py on every timed stage)."""
    prometheus.observe_stage(stage, seconds)
    tr = TRACER
    ctx = _CURRENT.get()
    if ctx is None or not tr.enabled:
        return
    req, parent = ctx
    if t0 is None:
        t0 = time.perf_counter() - seconds
    tr.record(req, Span(next(_IDS), parent, stage, t0, seconds,
                        threading.get_ident(), {}))


def bind_current(fn):
    """Wrap ``fn`` so it runs under the caller's request context in another
    thread (contextvars don't cross thread starts on their own)."""
    ctx = contextvars.copy_context()

    def run(*args: Any, **kwargs: Any) -> Any:
        return ctx.run(fn, *args, **kwargs)

    return run

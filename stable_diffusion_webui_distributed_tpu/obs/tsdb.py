"""In-process time-series store (SDTPU_TSDB): bounded metric history.

``/internal/metrics`` renders *instantaneous* counter values; nothing in
the plane can answer "when did queue-wait p95 start climbing?" or hand
the autoscaler a windowed trend instead of a point read. This module
keeps that history: a fixed-interval ring buffer per series, sampled by
a daemon (or an explicit :func:`tick` for deterministic tests/bench)
from the *existing* registered Prometheus families plus derived series:

- ``queue_wait_p95_s`` / ``e2e_p95_s`` — rank-interpolated p95 over the
  fixed-ladder histograms (sharper than the bucket-upper-bound estimate
  ``Histogram.quantile`` serves);
- ``slo_attainment.<tenant>.<class>`` / ``slo_burn.<tenant>.<class>`` —
  per-tenant SLO rows from the perf ledger (plus ``slo_burn_worst``);
- counter totals (requests, dispatches, compiles, worker failures,
  UNAVAILABLE demotions, watchdog stalls) so windowed ``rate()`` /
  ``increase()`` queries exist for the alert engine (obs/alerts.py);
- ``hbm_bytes_in_use`` / ``hbm_peak_bytes`` / ``device_live_buffers``
  — device-memory telemetry from ``jax.local_devices()[0]
  .memory_stats()``. Null on CPU (no fabricated numbers): the series
  simply never appears.

Query primitives: :meth:`SeriesStore.rate`,
:meth:`SeriesStore.avg_over_time`,
:meth:`SeriesStore.quantile_over_time`, :meth:`SeriesStore.increase`.
Served at ``GET /internal/tsdb`` (exact schema pinned by tests).

Gated off by default: ``SDTPU_TSDB=1`` enables,
``SDTPU_TSDB_INTERVAL_S`` sets the daemon cadence and
``SDTPU_TSDB_POINTS`` the per-series ring depth. With the gate off no
daemon starts, :func:`tick` is a no-op, and the serving path is
byte-identical to the unsampled build (hash-pinned in
tests/test_tsdb.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..runtime.config import env_flag, env_float, env_int, env_str
from ..runtime.daemon import StoppableDaemon

DEFAULT_INTERVAL_S = 1.0
DEFAULT_POINTS = 512

#: Bounded series-name namespace: adversarial tenant names must not grow
#: the store without bound (same philosophy as SDTPU_PERF_GROUPS).
_MAX_SERIES = 256

#: Series the flight recorder snapshots into failure/stall entries —
#: the postmortem view of "what the detectors saw" (satellite: flightrec
#: enrichment). slo_burn.* / hbm_* series ride along by prefix.
FLIGHT_SERIES: Tuple[str, ...] = (
    "queue_wait_p95_s", "e2e_p95_s", "worker_failures_total",
    "worker_unavailable_total", "watchdog_stalls_total",
    "compiles_total", "slo_burn_worst")
_FLIGHT_PREFIXES: Tuple[str, ...] = ("slo_burn.", "hbm_")
_FLIGHT_POINTS = 64


def enabled() -> bool:
    """TSDB gate — re-read per call so tests can flip the env var."""
    return env_flag("SDTPU_TSDB", False)


def interval_s() -> float:
    """Daemon sampling cadence (seconds)."""
    return max(0.01, env_float("SDTPU_TSDB_INTERVAL_S", DEFAULT_INTERVAL_S))


# -- durability (SDTPU_TSDB_DIR) ---------------------------------------------

SNAPSHOT_BASENAME = "tsdb_snapshot.json"

#: The daemon snapshots the store every this-many sampling ticks (plus a
#: final one at shutdown), bounding data loss to a handful of intervals.
_SAVE_EVERY_TICKS = 10


def snapshot_dir() -> str:
    """Snapshot directory (SDTPU_TSDB_DIR); '' = durability off."""
    return env_str("SDTPU_TSDB_DIR", "")


def snapshot_path(base: Optional[str] = None) -> str:
    return os.path.join(base or snapshot_dir(), SNAPSHOT_BASENAME)


# -- derived-series math -----------------------------------------------------

def quantile_from_counts(bounds: Tuple[float, ...], counts: List[int],
                         n: int, q: float) -> float:
    """Rank-interpolated quantile over cumulative-histogram bucket counts
    (``counts`` per-bucket incl. the +Inf overflow slot, as
    ``Histogram.snapshot`` returns them). Interpolates linearly inside
    the bucket holding the target rank instead of reporting its upper
    bound; the +Inf bucket clamps to the top finite bound."""
    if n <= 0:
        return 0.0
    target = max(1.0, q * n)
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        if i >= len(bounds):
            return float(bounds[-1])
        hi = float(bounds[i])
        if c > 0 and cum + c >= target:
            return lo + (hi - lo) * (target - cum) / c
        cum += c
        lo = hi
    return float(bounds[-1])


def device_memory_stats() -> Optional[Dict[str, int]]:
    """HBM stats from the first addressable device, or None when the
    backend has none to give (CPU, stubbed runtimes). Never fabricates
    a number: a missing/empty ``memory_stats()`` reports None and no
    ``hbm_*`` series is ever recorded for it."""
    try:
        import jax

        dev = jax.local_devices()[0]
        getter = getattr(dev, "memory_stats", None)
        stats = getter() if callable(getter) else None
    except Exception:  # noqa: BLE001 — telemetry stays passive
        return None
    if not stats:
        return None
    out: Dict[str, int] = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "num_allocs", "largest_alloc_size"):
        if key in stats:
            try:
                out[key] = int(stats[key])
            except (TypeError, ValueError):
                continue
    return out or None


def live_buffer_count() -> Optional[int]:
    """Count of live device arrays (the buffer census beside the HBM
    watermark); None when the runtime can't enumerate them."""
    try:
        import jax

        return len(jax.live_arrays())
    except Exception:  # noqa: BLE001 — telemetry stays passive
        return None


# -- the store ---------------------------------------------------------------

class SeriesStore:
    """Bounded, lock-disciplined ring-buffer store: one fixed-depth ring
    of (monotonic-time, value) samples per series name."""

    def __init__(self, points: Optional[int] = None) -> None:
        if points is None:
            points = env_int("SDTPU_TSDB_POINTS", DEFAULT_POINTS)
        self.points = max(8, int(points))
        self._lock = threading.Lock()
        # name -> ring of (t_mono, value)                guarded-by: _lock
        self._series: "OrderedDict[str, Deque[Tuple[float, float]]]" = \
            OrderedDict()
        self._samples_total = 0                        # guarded-by: _lock
        self._dropped_series = 0                       # guarded-by: _lock

    def record(self, name: str, value: Any,
               t: Optional[float] = None) -> None:
        """Append one sample; silently drops non-numeric values and (once
        the namespace cap is hit) samples for brand-new series."""
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if t is None:
            t = time.monotonic()
        key = str(name)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= _MAX_SERIES:
                    self._dropped_series += 1
                    return
                ring = deque(maxlen=self.points)
                self._series[key] = ring
            ring.append((float(t), v))
            self._samples_total += 1

    def names(self) -> List[str]:
        with self._lock:
            return list(self._series)

    def window(self, name: str, window_s: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Samples of ``name`` within the trailing ``window_s`` seconds
        (oldest first); the whole ring when ``window_s`` <= 0."""
        with self._lock:
            ring = self._series.get(str(name))
            samples = list(ring) if ring is not None else []
        if not samples or window_s <= 0:
            return samples
        if now is None:
            now = time.monotonic()
        cutoff = now - float(window_s)
        return [s for s in samples if s[0] >= cutoff]

    def latest(self, name: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get(str(name))
            return ring[-1] if ring else None

    # -- windowed query primitives ----------------------------------------

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a counter series over the window
        (prometheus ``rate()`` semantics, no reset handling — these
        counters only reset with the process). None under 2 samples."""
        w = self.window(name, window_s, now=now)
        if len(w) < 2:
            return None
        dt = w[-1][0] - w[0][0]
        if dt <= 0:
            return None
        return (w[-1][1] - w[0][1]) / dt

    def increase(self, name: str, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        """Absolute increase of a counter series over the window; None
        under 2 samples."""
        w = self.window(name, window_s, now=now)
        if len(w) < 2:
            return None
        return w[-1][1] - w[0][1]

    def avg_over_time(self, name: str, window_s: float,
                      now: Optional[float] = None) -> Optional[float]:
        w = self.window(name, window_s, now=now)
        if not w:
            return None
        return sum(v for _t, v in w) / len(w)

    def quantile_over_time(self, name: str, q: float, window_s: float,
                           now: Optional[float] = None) -> Optional[float]:
        """Rank-interpolated q-quantile of the sampled values in the
        window (None when empty)."""
        w = self.window(name, window_s, now=now)
        if not w:
            return None
        values = sorted(v for _t, v in w)
        if len(values) == 1:
            return values[0]
        pos = max(0.0, min(1.0, float(q))) * (len(values) - 1)
        i = int(pos)
        frac = pos - i
        if i + 1 >= len(values):
            return values[-1]
        return values[i] + (values[i + 1] - values[i]) * frac

    # -- sampling ----------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> int:
        """One sampling pass over every source; returns how many samples
        landed. Reads only existing metric objects — never a device sync
        beyond ``memory_stats()`` (a host-side allocator read)."""
        if now is None:
            now = time.monotonic()
        recs: List[Tuple[str, Any]] = []
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                prometheus as obs_prom,
            )

            for key, series in (("queue_wait", "queue_wait_p95_s"),
                                ("e2e", "e2e_p95_s")):
                h = obs_prom.HISTOGRAMS[key]
                counts, _total, n = h.snapshot()
                if n > 0:
                    recs.append((series, quantile_from_counts(
                        h.bounds, counts, n, 0.95)))
            recs.append(("worker_failures_total",
                         obs_prom.WORKER_COUNTERS["failures"].total()))
            recs.append(("worker_unavailable_total", sum(
                v for k, v in
                obs_prom.WORKER_COUNTERS["transitions"].snapshot().items()
                if k and k[-1] == "UNAVAILABLE")))
            recs.append(("watchdog_stalls_total",
                         obs_prom.WATCHDOG_COUNTER.total()))
        except Exception:  # noqa: BLE001 — sampling must never throw
            pass
        try:
            from stable_diffusion_webui_distributed_tpu.serving.metrics \
                import METRICS

            s = METRICS.summary()
            recs.append(("requests_total", s["requests"]))
            recs.append(("dispatches_total", s["dispatches"]))
            recs.append(("compiles_total", sum(s["compiles"].values())))
        except Exception:  # noqa: BLE001
            pass
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                perf as obs_perf,
            )

            worst = None
            for row in obs_perf.LEDGER.summary()["slo"]:
                tag = f'{row["tenant"]}.{row["class"]}'
                recs.append((f"slo_attainment.{tag}", row["attainment"]))
                burn = row["burn_rate"]
                recs.append((f"slo_burn.{tag}", burn))
                if burn is not None:
                    worst = burn if worst is None else max(worst, burn)
            if worst is not None:
                recs.append(("slo_burn_worst", worst))
        except Exception:  # noqa: BLE001
            pass
        mem = device_memory_stats()
        if mem is not None:
            if "bytes_in_use" in mem:
                recs.append(("hbm_bytes_in_use", mem["bytes_in_use"]))
            if "peak_bytes_in_use" in mem:
                recs.append(("hbm_peak_bytes", mem["peak_bytes_in_use"]))
            live = live_buffer_count()
            if live is not None:
                recs.append(("device_live_buffers", live))
        landed = 0
        for name, value in recs:
            if value is None:
                continue
            self.record(name, value, t=now)
            landed += 1
        return landed

    # -- snapshots ---------------------------------------------------------

    def snapshot(self, max_points: Optional[int] = None,
                 names: Optional[List[str]] = None) -> Dict[str, Any]:
        """Per-series sample dump (``samples`` oldest-first, trimmed to
        the trailing ``max_points`` when given)."""
        with self._lock:
            items = [(k, list(ring)) for k, ring in self._series.items()
                     if names is None or k in names]
        out: Dict[str, Any] = {}
        for name, samples in items:
            if max_points is not None and len(samples) > max_points:
                samples = samples[-max_points:]
            out[name] = {
                "count": len(samples),
                "latest": list(samples[-1]) if samples else None,
                "samples": [[t, v] for t, v in samples],
            }
        return out

    def dump(self) -> Dict[str, Any]:
        """Durable snapshot document (every ring, full depth). Timestamps
        are ``time.monotonic()`` — CLOCK_MONOTONIC, boot-relative on
        Linux, so they stay comparable across process restarts within one
        boot; :meth:`load_merge` drops anything from a future clock."""
        with self._lock:
            return {
                "schema": 1,
                "points": self.points,
                "saved_t_mono": time.monotonic(),
                "series": {k: [[t, v] for t, v in ring]
                           for k, ring in self._series.items()},
            }

    def load_merge(self, doc: Any) -> int:
        """Merge a :meth:`dump` document into the live rings; returns how
        many samples landed. Tolerant of garbage: a non-dict document,
        malformed series, or non-numeric samples contribute nothing, and
        samples stamped after *now* (a snapshot from a previous boot,
        where the monotonic clock restarted) are dropped rather than
        poisoning windowed queries. Restored samples do not bump
        ``samples_total`` — that counter means "sampled this process"."""
        if not isinstance(doc, dict):
            return 0
        series = doc.get("series")
        if not isinstance(series, dict):
            return 0
        now = time.monotonic()
        landed = 0
        for name, samples in series.items():
            if not isinstance(samples, (list, tuple)):
                continue
            clean: List[Tuple[float, float]] = []
            for s in samples:
                try:
                    t, v = float(s[0]), float(s[1])
                except (TypeError, ValueError, IndexError):
                    continue
                if t > now:
                    continue
                clean.append((t, v))
            if not clean:
                continue
            key = str(name)
            with self._lock:
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= _MAX_SERIES:
                        self._dropped_series += 1
                        continue
                    ring = deque(maxlen=self.points)
                    self._series[key] = ring
                merged = sorted(set(list(ring) + clean))
                ring.clear()
                ring.extend(merged[-self.points:])
            landed += len(clean)
        return landed

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"series": len(self._series),
                    "samples_total": self._samples_total,
                    "dropped_series": self._dropped_series}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._samples_total = 0
            self._dropped_series = 0


#: Process-wide store. Ring depth is resolved at construction; tests and
#: bench call :func:`reset` after flipping the env knobs.
STORE = SeriesStore()


def save_snapshot(store: Optional[SeriesStore] = None,
                  path: Optional[str] = None) -> bool:
    """Write the store's :meth:`~SeriesStore.dump` to disk crash-safely
    (tmp + ``os.replace``, the journal-sink rotation pattern — a crash
    mid-write leaves the previous snapshot intact, never a truncated
    one). No-op (False) when SDTPU_TSDB_DIR is unset and no explicit
    path is given; write failures are swallowed (telemetry stays
    passive)."""
    if path is None:
        base = snapshot_dir()
        if not base:
            return False
        path = snapshot_path(base)
    store = store if store is not None else STORE
    tmp = f"{path}.tmp"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(store.dump(), f, sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def load_snapshot(store: Optional[SeriesStore] = None,
                  path: Optional[str] = None) -> int:
    """Merge an on-disk snapshot into the store; returns how many samples
    landed (0 for a missing, truncated, or corrupt file — restart must
    never fail on bad history)."""
    if path is None:
        base = snapshot_dir()
        if not base:
            return 0
        path = snapshot_path(base)
    store = store if store is not None else STORE
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return 0
    return store.load_merge(doc)


# -- sampling daemon ---------------------------------------------------------

_DAEMON_LOCK = threading.Lock()
_DAEMON: Optional[StoppableDaemon] = None  # guarded-by: _DAEMON_LOCK
_DAEMON_STORE: Optional[SeriesStore] = None  # guarded-by: _DAEMON_LOCK


def _make_sampler(store: SeriesStore, period_s: float) -> StoppableDaemon:
    """Fixed-interval sampling daemon; also drives the alert engine's
    evaluation when SDTPU_ALERTS is on (one clock for both)."""
    ticks = 0

    def sample() -> None:
        nonlocal ticks
        tick(store=store)
        ticks += 1
        if ticks % _SAVE_EVERY_TICKS == 0 and snapshot_dir():
            save_snapshot(store)

    return StoppableDaemon("sdtpu-tsdb-sampler", sample, period_s)


def tick(store: Optional[SeriesStore] = None) -> int:
    """One sample + alert-evaluation pass; no-op (0) with the gate off.
    The daemon calls this on its cadence; tests and bench call it
    directly for deterministic clocks."""
    if not enabled():
        return 0
    if store is None:
        store = STORE
    landed = store.sample_once()
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            alerts as obs_alerts,
        )

        obs_alerts.evaluate()
    except Exception:  # noqa: BLE001 — sampling must never throw
        pass
    return landed


def start_daemon() -> bool:
    """Start the sampling daemon (idempotent); False with the gate off."""
    global _DAEMON, _DAEMON_STORE
    if not enabled():
        return False
    with _DAEMON_LOCK:
        if _DAEMON is not None and _DAEMON.alive():
            return True
        if snapshot_dir():
            load_snapshot(STORE)
        _DAEMON = _make_sampler(STORE, interval_s())
        _DAEMON_STORE = STORE
        _DAEMON.start()
    return True


def stop_daemon() -> None:
    global _DAEMON, _DAEMON_STORE
    with _DAEMON_LOCK:
        daemon, store = _DAEMON, _DAEMON_STORE
        _DAEMON = _DAEMON_STORE = None
    if daemon is not None:
        daemon.stop(timeout_s=2.0)
        if store is not None and snapshot_dir():
            save_snapshot(store)


def reset() -> None:
    """Stop the daemon and rebuild the store from the current env knobs
    (tests/bench flip SDTPU_TSDB_POINTS between phases). With
    SDTPU_TSDB_DIR set, the rebuilt store merges the on-disk snapshot —
    reset *is* the restart, and history survives it."""
    global STORE
    stop_daemon()
    STORE = SeriesStore()
    if enabled() and snapshot_dir():
        load_snapshot(STORE)


def dispatch_memory_sample() -> Optional[Dict[str, int]]:
    """Per-dispatch device-memory read for the dispatcher: returns the
    raw stats (for the perf ledger's group rows) and, when the TSDB gate
    is on, records the HBM watermark + live-buffer census as series.
    None on CPU — the ledger stores null, never a fabricated number."""
    mem = device_memory_stats()
    if mem is None:
        return None
    if enabled():
        now = time.monotonic()
        if "bytes_in_use" in mem:
            STORE.record("hbm_bytes_in_use", mem["bytes_in_use"], t=now)
        if "peak_bytes_in_use" in mem:
            STORE.record("hbm_peak_bytes", mem["peak_bytes_in_use"], t=now)
        live = live_buffer_count()
        if live is not None:
            STORE.record("device_live_buffers", live, t=now)
    return mem


def flight_window() -> Optional[Dict[str, Any]]:
    """The bounded TSDB view the flight recorder attaches to failure and
    watchdog-stall entries; None with the gate off (no-op enrichment)."""
    if not enabled():
        return None
    keep = [n for n in STORE.names()
            if n in FLIGHT_SERIES or n.startswith(_FLIGHT_PREFIXES)]
    return {"interval_s": interval_s(),
            "series": STORE.snapshot(max_points=_FLIGHT_POINTS,
                                     names=keep)}


def summary() -> Dict[str, Any]:
    """The ``GET /internal/tsdb`` document (schema pinned by tests)."""
    stats = STORE.stats()
    with _DAEMON_LOCK:
        daemon_alive = _DAEMON is not None and _DAEMON.alive()
    return {
        "enabled": enabled(),
        "interval_s": interval_s(),
        "points": STORE.points,
        "daemon": daemon_alive,
        "series_count": stats["series"],
        "samples_total": stats["samples_total"],
        "dropped_series": stats["dropped_series"],
        "series": STORE.snapshot(),
    }

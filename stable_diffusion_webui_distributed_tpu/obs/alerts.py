"""Burn-rate + anomaly alerting over the TSDB (SDTPU_ALERTS).

The TSDB (obs/tsdb.py) keeps the metric history; this module evaluates
a **closed registry** of alert rules against it and runs each rule
through a pending -> firing -> resolved state machine:

- ``burn_rate`` — multi-window multi-burn-rate SLO alerts (the SRE-book
  shape): the fast pair reads the 5m and 1h windows at burn >= 14.4,
  the slow pair the 1h and 6h windows at burn >= 6. Both windows must
  agree, which is what kills the single-window flappiness. Window
  lengths scale by ``SDTPU_ALERT_TIMESCALE`` so scenario runs compress
  hours into seconds without touching thresholds.
- ``anomaly`` — EWMA z-score detection on a sampled series (queue-wait
  p95) or a windowed counter rate (compile rate, error rate): an
  exponentially-weighted mean/variance tracks the series, and a value
  ``z`` deviations above the mean (with an absolute floor so a quiet
  series can't alarm on noise) marks the condition true. ``for_count``
  consecutive true evaluations are required before firing, so a single
  bucket-quantization jump pends and self-clears while a genuine
  regime change latches.
- ``increase`` — windowed threshold on a counter that is structurally
  zero in healthy operation (watchdog stalls, UNAVAILABLE demotions):
  any increase over the fast window is a condition hit. These are the
  deterministic detectors the chaos recall gate leans on.

Every state transition journals through the closed vocabulary
(``alert_firing`` / ``alert_resolved``), bumps
``sdtpu_alerts_total{rule,state}``, sets ``sdtpu_alert_state{rule}``,
and a firing additionally lands a flight-recorder entry carrying the
TSDB window the detector saw. ``fleet/slices.py`` consumes
:func:`scale_up_firing` as a scale-up signal beside its queue-wait
trigger.

Rule registration is confined to this module's registry: lint rule
OB004 (analysis/alertrules.py) flags :func:`register_rule` calls
anywhere else in the package.

Gated off by default: ``SDTPU_ALERTS=1`` enables (it needs
``SDTPU_TSDB=1`` for data); off, :func:`evaluate` returns immediately
and the serving path is byte-identical to the unalerted build.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..runtime.config import env_flag, env_float

#: SRE-book burn thresholds: the fast pair catches a budget-exhausting
#: burn in minutes, the slow pair a slow leak in hours.
FAST_BURN = 14.4
SLOW_BURN = 6.0

#: Transition history retained per engine (state(), /internal/alerts).
_HISTORY_CAP = 256


def enabled() -> bool:
    """Alert-engine gate — re-read per call so tests can flip it."""
    return env_flag("SDTPU_ALERTS", False)


def timescale() -> float:
    """Window compression factor: rule windows (wall-clock seconds) are
    multiplied by this, so scenario runs replay the 5m/1h/6h SLO windows
    in seconds (``SDTPU_ALERT_TIMESCALE=0.01`` -> 3s/36s/216s)."""
    return max(1e-6, env_float("SDTPU_ALERT_TIMESCALE", 1.0))


#: The closed severity vocabulary. Routing (SDTPU_NOTIFY_ROUTES) keys on
#: these literals, so a typo'd severity would silently never page —
#: construction rejects it and OB004 flags the literal at lint time.
SEVERITIES = frozenset({"page", "warn", "info"})


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One closed-registry alert rule.

    ``kind`` selects the detector: ``burn_rate`` (``series`` is a
    prefix matched against ``slo_burn.*`` series, ``windows_s`` the
    (short, long) pair, ``threshold`` the burn floor), ``anomaly``
    (EWMA z-score on the series value, or on its windowed rate when
    ``use_rate``), ``increase`` (windowed counter increase >=
    ``threshold``). ``for_count`` consecutive true evaluations gate
    pending -> firing. ``scale_up`` marks the rule as an autoscaler
    scale-up signal. ``severity`` routes the rule's notifications
    (obs/notify.py SDTPU_NOTIFY_ROUTES): a closed set — ``page`` wakes
    a human, ``warn`` is actionable during business hours, ``info`` is
    context only — enforced here and at the AST level by OB004."""

    name: str
    kind: str                        # "burn_rate" | "anomaly" | "increase"
    series: str
    description: str
    windows_s: Tuple[float, float] = (300.0, 3600.0)
    threshold: float = 1.0
    for_count: int = 1
    use_rate: bool = False
    z: float = 6.0
    alpha: float = 0.3
    warmup: int = 8
    min_value: float = 0.0
    scale_up: bool = False
    severity: str = "warn"           # "page" | "warn" | "info"

    def __post_init__(self) -> None:
        if self.kind not in ("burn_rate", "anomaly", "increase"):
            raise ValueError(f"unknown alert-rule kind {self.kind!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"unknown alert severity {self.severity!r} "
                f"(expected one of {sorted(SEVERITIES)})")


_REGISTRY_LOCK = threading.Lock()
#: name -> rule. The closed rule set every engine evaluates; OB004
#: confines register_rule calls to this module.
_RULES: "collections.OrderedDict[str, AlertRule]" = \
    collections.OrderedDict()  # guarded-by: _REGISTRY_LOCK


def register_rule(rule: AlertRule) -> AlertRule:
    """Declare one alert rule (the only sanctioned registration site —
    OB004). Re-registering a name raises: two detectors sharing a name
    would corrupt the lifecycle metrics."""
    with _REGISTRY_LOCK:
        if rule.name in _RULES:
            raise ValueError(f"alert rule {rule.name!r} already registered")
        _RULES[rule.name] = rule
    return rule


def registered_rules() -> Dict[str, AlertRule]:
    with _REGISTRY_LOCK:
        return dict(_RULES)


# -- the closed rule set -----------------------------------------------------

register_rule(AlertRule(
    name="slo_burn_fast", kind="burn_rate", series="slo_burn.",
    description="Fast SLO budget burn: 5m AND 1h windows both >= 14.4x "
                "(exhausts a 30d budget in ~2 days).",
    windows_s=(300.0, 3600.0), threshold=FAST_BURN, for_count=1,
    scale_up=True, severity="page"))
register_rule(AlertRule(
    name="slo_burn_slow", kind="burn_rate", series="slo_burn.",
    description="Slow SLO budget burn: 1h AND 6h windows both >= 6x.",
    windows_s=(3600.0, 21600.0), threshold=SLOW_BURN, for_count=1,
    scale_up=True, severity="warn"))
register_rule(AlertRule(
    name="queue_wait_anomaly", kind="anomaly", series="queue_wait_p95_s",
    description="Queue-wait p95 running away from its EWMA baseline "
                "(z-score with sustain requirement).",
    for_count=3, z=6.0, alpha=0.3, warmup=8, min_value=0.25,
    scale_up=True, severity="warn"))
register_rule(AlertRule(
    name="compile_rate_anomaly", kind="anomaly", series="compiles_total",
    description="Compile-storm detector: windowed stage-compile rate "
                "z-scoring far above its EWMA baseline.",
    windows_s=(300.0, 3600.0), use_rate=True, for_count=2, z=6.0,
    warmup=8, min_value=2.0, severity="info"))
register_rule(AlertRule(
    name="error_rate_anomaly", kind="anomaly",
    series="worker_failures_total",
    description="Worker-failure rate above its EWMA baseline (a healthy "
                "fleet's failure counter is flat).",
    windows_s=(300.0, 3600.0), use_rate=True, for_count=1, z=6.0,
    warmup=4, min_value=1e-6, severity="warn"))
register_rule(AlertRule(
    name="worker_flap", kind="increase",
    series="worker_unavailable_total",
    description="Worker health flap: any UNAVAILABLE demotion inside "
                "the fast window.",
    windows_s=(300.0, 3600.0), threshold=1.0, for_count=1,
    severity="warn"))
register_rule(AlertRule(
    name="watchdog_stall", kind="increase",
    series="watchdog_stalls_total",
    description="Hang-watchdog stall detections inside the fast window.",
    windows_s=(300.0, 3600.0), threshold=1.0, for_count=1,
    severity="page"))
register_rule(AlertRule(
    name="worker_metrics_stale", kind="increase",
    series="fleet/worker_stale_count",
    description="Fleet-scope: a federated worker's metrics went stale "
                "(no successful poll inside the freshness deadline) — "
                "the worker is dead or partitioned. Dormant without "
                "SDTPU_FEDERATION (series never recorded).",
    windows_s=(300.0, 3600.0), threshold=1.0, for_count=1,
    severity="page"))
register_rule(AlertRule(
    name="fleet_error_rate", kind="anomaly", series="fleet/error_rate",
    description="Fleet-scope: federated mean worker error rate jumping "
                "off its EWMA baseline (an unreachable worker counts as "
                "1.0). Dormant without SDTPU_FEDERATION.",
    for_count=1, z=6.0, warmup=4, min_value=0.1, severity="page"))


class AlertEngine:
    """Pending/firing/resolved state machine over the rule registry.

    ``store`` defaults to the live TSDB; tests pass their own
    :class:`~.tsdb.SeriesStore` and drive :meth:`evaluate` with an
    explicit clock for determinism.
    """

    def __init__(self, store=None, clock: Callable[[], float]
                 = time.monotonic) -> None:
        self._store = store
        self._clock = clock
        self._lock = threading.Lock()
        # rule name -> mutable state                    guarded-by: _lock
        self._state: Dict[str, Dict[str, Any]] = {
            name: self._fresh_state() for name in registered_rules()}
        # bounded transition history                    guarded-by: _lock
        self._history: Deque[Dict[str, Any]] = \
            collections.deque(maxlen=_HISTORY_CAP)
        self._evaluations = 0                          # guarded-by: _lock

    @staticmethod
    def _fresh_state() -> Dict[str, Any]:
        return {"state": "ok", "true_count": 0, "pending_since": None,
                "firing_since": None, "ewma": None, "ewvar": 0.0,
                "ewma_samples": 0, "last_value": None, "last_z": None,
                "since_eval": 0}

    def store(self):
        if self._store is not None:
            return self._store
        from stable_diffusion_webui_distributed_tpu.obs import (
            tsdb as obs_tsdb,
        )

        return obs_tsdb.STORE

    # -- per-kind conditions ----------------------------------------------

    def _burn_condition(self, rule: AlertRule, store, now: float,
                        st: Dict[str, Any]) -> Tuple[bool, Any, str]:
        ts = timescale()
        short_w, long_w = (rule.windows_s[0] * ts, rule.windows_s[1] * ts)
        names = [n for n in store.names() if n.startswith(rule.series)]
        worst: Optional[float] = None
        worst_name = ""
        for name in names:
            short = store.avg_over_time(name, short_w, now=now)
            long = store.avg_over_time(name, long_w, now=now)
            if short is None or long is None:
                continue
            burn = min(short, long)  # both windows must clear the bar
            if worst is None or burn > worst:
                worst, worst_name = burn, name
        if worst is None:
            return False, None, "no burn samples"
        return (worst >= rule.threshold, worst,
                f"{worst_name} min-window burn {worst:.2f} "
                f"vs {rule.threshold:.1f}")

    def _anomaly_condition(self, rule: AlertRule, store, now: float,
                           st: Dict[str, Any]) -> Tuple[bool, Any, str]:
        if rule.use_rate:
            value = store.rate(rule.series,
                               rule.windows_s[0] * timescale(), now=now)
        else:
            latest = store.latest(rule.series)
            value = latest[1] if latest is not None else None
        if value is None:
            return False, None, "no samples"
        mean = st["ewma"]
        var = st["ewvar"]
        samples = st["ewma_samples"]
        z = None
        cond = False
        if mean is not None and samples >= rule.warmup:
            # std floor: 10% of |mean| or a small absolute epsilon, so a
            # near-constant series cannot z-explode on measurement noise
            std = math.sqrt(max(var, 0.0))
            std = max(std, 0.1 * abs(mean), 1e-6)
            z = (value - mean) / std
            cond = z >= rule.z and value >= rule.min_value
        # EWMA/EWVar update AFTER the test: the detector compares against
        # the pre-sample baseline
        if mean is None:
            st["ewma"], st["ewvar"] = float(value), 0.0
        else:
            a = rule.alpha
            delta = float(value) - mean
            st["ewma"] = mean + a * delta
            st["ewvar"] = (1.0 - a) * (var + a * delta * delta)
        st["ewma_samples"] = samples + 1
        detail = (f"value {value:.4g}, ewma {st['ewma']:.4g}"
                  + (f", z {z:.2f} vs {rule.z:.1f}" if z is not None
                     else ", warming up"))
        st["last_z"] = z
        return cond, value, detail

    def _increase_condition(self, rule: AlertRule, store, now: float,
                            st: Dict[str, Any]) -> Tuple[bool, Any, str]:
        inc = store.increase(rule.series,
                             rule.windows_s[0] * timescale(), now=now)
        if inc is None:
            return False, None, "no samples"
        return (inc >= rule.threshold, inc,
                f"window increase {inc:.4g} vs {rule.threshold:.4g}")

    # -- the state machine -------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass over every rule; returns (and records)
        the state transitions it produced."""
        if now is None:
            now = self._clock()
        store = self.store()
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self._evaluations += 1
        for name, rule in registered_rules().items():
            with self._lock:
                st = self._state.setdefault(name, self._fresh_state())
                if rule.kind == "burn_rate":
                    cond, value, detail = self._burn_condition(
                        rule, store, now, st)
                elif rule.kind == "anomaly":
                    cond, value, detail = self._anomaly_condition(
                        rule, store, now, st)
                else:
                    cond, value, detail = self._increase_condition(
                        rule, store, now, st)
                st["last_value"] = value
                prev = st["state"]
                new = prev
                if cond:
                    st["true_count"] += 1
                    if prev == "ok":
                        new = "pending"
                        st["pending_since"] = now
                    if st["true_count"] >= rule.for_count \
                            and prev != "firing":
                        new = "firing"
                        st["firing_since"] = now
                else:
                    st["true_count"] = 0
                    if prev == "firing":
                        new = "ok"  # resolved
                    elif prev == "pending":
                        new = "ok"
                    st["pending_since"] = None
                    if new == "ok":
                        st["firing_since"] = None
                st["state"] = new
                entry = None
                if new != prev:
                    entry = {"rule": name, "from": prev, "to": new,
                             "t": now, "value": value, "detail": detail}
                    self._history.append(entry)
            if entry is not None:
                transitions.append(entry)
                if new == "firing" or (prev == "firing" and new == "ok"):
                    self._announce(rule, prev, new, value, detail)
        return transitions

    def _announce(self, rule: AlertRule, prev: str, new: str,
                  value: Any, detail: str) -> None:
        """Journal + Prometheus + flight-recorder side effects of a
        firing/resolved transition; best-effort, never throws into the
        evaluation loop."""
        firing = new == "firing"
        event = "alert_firing" if firing else "alert_resolved"
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                journal as obs_journal,
            )

            if obs_journal.enabled():
                obs_journal.emit(event, f"alert-{rule.name}",
                                 rule=rule.name, kind=rule.kind,
                                 series=rule.series, value=value,
                                 severity=rule.severity, detail=detail)
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                prometheus as obs_prom,
            )

            obs_prom.alert_count(rule.name,
                                 "firing" if firing else "resolved")
            obs_prom.set_alert_state(rule.name, 1.0 if firing else 0.0)
        except Exception:  # noqa: BLE001
            pass
        try:
            from stable_diffusion_webui_distributed_tpu.obs import (
                notify as obs_notify,
            )

            obs_notify.notify_transition(rule.name, event, value, detail,
                                         severity=rule.severity)
        except Exception:  # noqa: BLE001
            pass
        if firing:
            try:
                from stable_diffusion_webui_distributed_tpu.obs import (
                    flightrec,
                )

                flightrec.RECORDER.record(
                    f"alert-{rule.name}", "alert_firing",
                    f"{rule.name}: {detail}", events=[])
            except Exception:  # noqa: BLE001
                pass

    # -- views -------------------------------------------------------------

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, st in self._state.items()
                          if st["state"] == "firing")

    def scale_up_firing(self) -> List[str]:
        """Firing rules marked as autoscaler scale-up signals."""
        rules = registered_rules()
        return [n for n in self.firing()
                if n in rules and rules[n].scale_up]

    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._history]

    def state(self) -> Dict[str, Any]:
        rules = registered_rules()
        with self._lock:
            per_rule = {
                name: {"state": st["state"],
                       "kind": rules[name].kind if name in rules else "",
                       "scale_up": bool(rules[name].scale_up)
                       if name in rules else False,
                       "true_count": st["true_count"],
                       "pending_since": st["pending_since"],
                       "firing_since": st["firing_since"],
                       "last_value": st["last_value"],
                       "last_z": st["last_z"]}
                for name, st in self._state.items()}
            history = [dict(e) for e in self._history]
        return {"rules": per_rule,
                "firing": sorted(n for n, r in per_rule.items()
                                 if r["state"] == "firing"),
                "history": history}

    def clear(self) -> None:
        with self._lock:
            self._state = {name: self._fresh_state()
                           for name in registered_rules()}
            self._history.clear()
            self._evaluations = 0


#: Process-wide engine (the TSDB daemon drives it; /internal/alerts and
#: the autoscaler read it). Tests construct their own for odd clocks.
ENGINE = AlertEngine()


def reset() -> None:
    """Rebuild the process-wide engine (tests/bench between phases)."""
    global ENGINE
    ENGINE = AlertEngine()


def evaluate() -> List[Dict[str, Any]]:
    """One gated evaluation pass; [] with SDTPU_ALERTS off."""
    if not enabled():
        return []
    return ENGINE.evaluate()


def firing() -> List[str]:
    if not enabled():
        return []
    return ENGINE.firing()


def scale_up_firing() -> List[str]:
    """The autoscaler's alert-sourced scale-up signal; [] when off."""
    if not enabled():
        return []
    return ENGINE.scale_up_firing()


def state_snapshot() -> Optional[Dict[str, Any]]:
    """Bounded alert-state view for flight-recorder enrichment; None
    with the gate off (no-op enrichment)."""
    if not enabled():
        return None
    return ENGINE.state()


def summary() -> Dict[str, Any]:
    """The ``GET /internal/alerts`` document (schema pinned by tests)."""
    doc: Dict[str, Any] = {
        "enabled": enabled(),
        "timescale": timescale(),
        "registered": {name: {"kind": r.kind, "series": r.series,
                              "description": r.description,
                              "scale_up": r.scale_up,
                              "severity": r.severity}
                       for name, r in registered_rules().items()},
    }
    doc.update(ENGINE.state())
    return doc

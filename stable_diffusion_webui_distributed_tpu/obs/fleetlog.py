"""Fleet-merged journal timeline (``GET /internal/fleet/timeline``).

Each node's event journal (obs/journal.py) is a causally-chained,
single-clock record — but a fan-out request's story spans the master
*and* every worker it touched, and each worker's ``t_mono`` lives on a
different monotonic clock. This module holds the master-side merge:

- :func:`ingest` — the push plane (obs/push.py DeltaSubscriber) streams
  each worker's journal events here together with the RTT-midpoint
  clock offset (obs/stitch.py) estimated on the same fetch, so every
  remote timestamp lands on the master's clock: ``t_fleet = t_mono +
  offset_s``. Per-node buffers are bounded and dedupe by ``seq`` —
  cursor-resumed redelivery after a reconnect cannot double-insert.
- :func:`timeline` — one causally-ordered fleet timeline: the local
  journal (offset zero, node ``local``) merged with every streamed
  worker, ordered by ``t_fleet`` with ``(node, seq)`` tie-breaks, and
  per-node ``seq`` order enforced even when a later offset estimate
  would reorder a node against itself (``t_fleet`` is clamped
  monotonic per node at ingest). Filterable by ``request_id`` — the
  W3C traceparent thread (obs/spans.py) gives master and worker the
  same request id, so one filter returns the cross-node story.
- :func:`causal_violations` — parent/child order check over a merged
  timeline (a child placed before its same-node parent means a broken
  offset or merge); ``tools/fed_report.py --timeline`` exits non-zero
  on any, and the doc carries the count.

Passive and bounded: nothing here is on the serving path, the merge is
O(total retained events) at read time, and with the journal disabled
the doc is empty with ``enabled: false``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..runtime.config import env_int

#: Node label for the master's own journal in the merged timeline.
LOCAL_NODE = "local"


def capacity() -> int:
    """Per-node retained-event bound (rides SDTPU_JOURNAL_MAX — the
    fleet view never retains more per node than a node itself does)."""
    return max(16, env_int("SDTPU_JOURNAL_MAX", 4096))


def enabled() -> bool:
    from . import journal as obs_journal

    return obs_journal.enabled()


class FleetLog:
    """Bounded per-node event buffers + the merge."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # node -> seq -> event row; OrderedDict gives FIFO eviction in
        # seq order (ingest only ever appends higher seqs per node).
        self._nodes: Dict[str, "OrderedDict[int, Dict[str, Any]]"] = {}
        self._offsets: Dict[str, float] = {}           # guarded-by: _lock
        self._last_t_fleet: Dict[str, float] = {}      # guarded-by: _lock
        self._ingested = 0                             # guarded-by: _lock
        self._deduped = 0                              # guarded-by: _lock
        self._evicted = 0                              # guarded-by: _lock

    def ingest(self, node: str, events: List[Dict[str, Any]],
               offset_s: float = 0.0) -> int:
        """Add a batch of one node's journal events, with the clock
        offset that places them on the master clock. Events already
        held (same node+seq — a redelivered batch) are dropped;
        returns how many were new."""
        node = str(node)
        added = 0
        cap = capacity()
        with self._lock:
            ring = self._nodes.setdefault(node, OrderedDict())
            self._offsets[node] = float(offset_s)
            last_t = self._last_t_fleet.get(node)
            for ev in events:
                try:
                    seq = int(ev["seq"])
                    t_mono = float(ev["t_mono"])
                except (KeyError, TypeError, ValueError):
                    continue
                if seq in ring:
                    self._deduped += 1
                    continue
                t_fleet = t_mono + float(offset_s)
                # per-node seq order must survive offset re-estimates:
                # clamp t_fleet monotonic within the node
                if last_t is not None and t_fleet < last_t:
                    t_fleet = last_t
                last_t = t_fleet
                ring[seq] = {
                    "node": node,
                    "seq": seq,
                    "event": str(ev.get("event", "")),
                    "request_id": str(ev.get("request_id", "")),
                    "t_mono": t_mono,
                    "t_fleet": t_fleet,
                    "parent": ev.get("parent"),
                    "attrs": dict(ev.get("attrs") or {}),
                }
                added += 1
                while len(ring) > cap:
                    ring.popitem(last=False)
                    self._evicted += 1
            if last_t is not None:
                self._last_t_fleet[node] = last_t
            self._ingested += added
        return added

    def merged(self, request_id: Optional[str] = None,
               ) -> List[Dict[str, Any]]:
        """The fleet timeline: local journal + every streamed node,
        ordered by ``(t_fleet, node, seq)``."""
        rows: List[Dict[str, Any]] = []
        try:
            from . import journal as obs_journal

            if obs_journal.enabled():
                local = obs_journal.JOURNAL.snapshot()["events"]
            else:
                local = []
        except Exception:  # noqa: BLE001 — the view stays passive
            local = []
        for ev in local:
            rows.append({
                "node": LOCAL_NODE,
                "seq": ev.get("seq"),
                "event": ev.get("event"),
                "request_id": ev.get("request_id"),
                "t_mono": ev.get("t_mono"),
                "t_fleet": ev.get("t_mono"),
                "parent": ev.get("parent"),
                "attrs": dict(ev.get("attrs") or {}),
            })
        with self._lock:
            for ring in self._nodes.values():
                rows.extend(dict(r) for r in ring.values())
        if request_id is not None:
            rid = str(request_id)
            rows = [r for r in rows if r["request_id"] == rid]
        rows.sort(key=lambda r: (r["t_fleet"], r["node"], r["seq"]))
        return rows

    def nodes(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {}
            for node, ring in self._nodes.items():
                out[node] = {
                    "count": len(ring),
                    "offset_s": self._offsets.get(node, 0.0),
                }
            return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"ingested": self._ingested,
                    "deduped": self._deduped,
                    "evicted": self._evicted}

    def clear(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._offsets.clear()
            self._last_t_fleet.clear()
            self._ingested = 0
            self._deduped = 0
            self._evicted = 0


def causal_violations(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Parent-before-child check over a merged timeline.

    An event whose ``parent`` seq (same node — journal parents are
    node-local) appears *later* in the list is a violation: the merge
    (or a clock offset) placed an effect before its cause. Parents
    missing entirely (evicted from the bounded buffers, or outside a
    ``request_id`` filter) are not violations. Returns one row per
    violation with both positions — ``tools/fed_report.py --timeline``
    exits non-zero when any exist."""
    pos: Dict[Any, int] = {}
    for i, ev in enumerate(events):
        pos[(ev.get("node"), ev.get("seq"))] = i
    out: List[Dict[str, Any]] = []
    for i, ev in enumerate(events):
        parent = ev.get("parent")
        if parent is None:
            continue
        j = pos.get((ev.get("node"), parent))
        if j is not None and j > i:
            out.append({
                "node": ev.get("node"),
                "seq": ev.get("seq"),
                "event": ev.get("event"),
                "request_id": ev.get("request_id"),
                "parent": parent,
                "child_index": i,
                "parent_index": j,
            })
    return out


#: Process-wide fleet log; the push plane's subscribers feed it.
LOG = FleetLog()


def ingest(node: str, events: List[Dict[str, Any]],
           offset_s: float = 0.0) -> int:
    """Stream one node's journal events into the fleet timeline."""
    return LOG.ingest(node, events, offset_s=offset_s)


def timeline(request_id: Optional[str] = None) -> Dict[str, Any]:
    """The ``GET /internal/fleet/timeline`` document."""
    events = LOG.merged(request_id=request_id)
    violations = causal_violations(events)
    return {
        "enabled": enabled(),
        "nodes": LOG.nodes(),
        "count": len(events),
        "violations": len(violations),
        "violation_rows": violations,
        "events": events,
    }


def reset() -> None:
    """Drop every buffered node (tests/bench between phases)."""
    global LOG
    LOG = FleetLog()

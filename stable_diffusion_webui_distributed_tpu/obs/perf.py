"""Perf ledger: always-on device-time attribution and self-checking budgets.

PERF.md's roofline was computed by hand from one-shot BENCH files; this
module makes the same numbers *live*. The serving dispatcher reports every
device dispatch here — host-observed seconds joined with the FLOPs that
``FlopsAccountant`` priced for the same denoise range — and the ledger
folds them into per-(bucket, cadence, precision) groups carrying:

- **MFU**: dispatched FLOPs / device seconds / chip peak (``None`` on CPU
  or unknown hardware, so a dev box can never fabricate an MFU claim);
- **padding waste**: true-requested pixels vs padded-dispatched pixels —
  the per-bucket version of BENCH_serving.json's ``avg_padding_ratio``,
  the gauge the ragged-dispatch work will be judged against (ROADMAP);
- **compile latency** per stage kind (``Engine._cached`` reports builds);
- **SLO attainment + burn rate** per (tenant, class) when the fleet gate
  is on (burn rate = windowed miss fraction / error budget, the
  Google-SRE multi-window signal shape).

Everything is gated on ``SDTPU_PERF`` (default OFF): with the knob off
every record call is a cheap no-op and the dispatch path stays
byte-identical to the uninstrumented build. Recording is host-side
arithmetic under one lock — never a device sync. ``/internal/perf``
serves :meth:`PerfLedger.summary`; ``obs/prometheus.py`` renders the same
groups as ``sdtpu_perf_*`` families.

:func:`executables_census` is the compile-budget self-check behind
``/internal/executables``: it groups the engine's live compiled-stage
keys by shape bucket and alarms when any bucket exceeds the contracted
≤2 step-cache × ≤3 precision chunk executables (PR 3 / PR 7 invariants).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

from stable_diffusion_webui_distributed_tpu.runtime.config import (
    env_flag, env_float, env_int,
)

#: Default cap on distinct (bucket, cadence, precision) ledger groups and
#: on distinct (tenant, class) SLO rows — adversarial tenant names must
#: not grow the ledger without bound (oldest-touched rows are evicted).
DEFAULT_GROUPS = 64
#: Sliding window (dispatch completions) behind the SLO burn-rate gauge.
SLO_WINDOW = 64
#: Default SLO attainment target: burn rate 1.0 means missing exactly the
#: (1 - target) error budget.
DEFAULT_SLO_TARGET = 0.95

#: Contracted executable budget per shape bucket (PR 3: plain + step-cache
#: variants; PR 7: ≤3 precision rungs over the same param tree).
STEP_CACHE_BUDGET = 2
PRECISION_BUDGET = 3
#: Distinct traced-LoRA (rank_bucket, slot_count) cells allowed per shape
#: bucket (SDTPU_LORA_TRACED): adapter NAMES never mint executables — only
#: ladder cells do — so this bounds the whole adapter-diverse workload.
#: The adapterless variant ("" sig) rides outside this allowance.
LORA_BUDGET = 4

#: bf16 peak FLOPs/s per chip by device_kind substring (public specs);
#: bench.py's MFU estimate shares this table via :func:`peak_flops_for`.
PEAK_FLOPS_BF16: Dict[str, float] = {
    "v6e": 918e12, "v6": 918e12,
    "v5p": 459e12,
    "v5e": 197e12, "v5litepod": 197e12, "v5": 197e12,
    "v4": 275e12,
}
#: int8 MXU peak relative to bf16 (BENCH_int8.json's mxu_peak_ratio).
INT8_PEAK_RATIO = 2.0


def enabled() -> bool:
    """Live read of the master knob — tests and bench phases flip the env
    var at runtime, so this is re-read per record call (it is one dict
    lookup; the off path must stay near-free)."""
    return env_flag("SDTPU_PERF", False)


def peak_flops_for(device_kind: str, precision: str = "bf16"
                   ) -> Optional[float]:
    """Peak FLOPs/s for a device kind at a serving precision, or ``None``
    when the hardware is unknown (CPU dev boxes: MFU stays null rather
    than inventing a denominator). ``SDTPU_PERF_PEAK_FLOPS`` overrides
    the table outright — deterministic MFU in tests, and a forward knob
    for chips the table hasn't met."""
    override = env_float("SDTPU_PERF_PEAK_FLOPS", 0.0)
    if override > 0:
        return override
    dk = str(device_kind or "").lower().replace(" ", "")
    for key, val in PEAK_FLOPS_BF16.items():
        if key in dk:
            if str(precision or "").startswith("int8"):
                return val * INT8_PEAK_RATIO
            return val
    return None


def _device_kind() -> str:
    """Best-effort device kind for the MFU denominator. jax is already
    imported by the time anything dispatches; failure means "unknown"
    (MFU null), never an exception on the dispatch path."""
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — telemetry must not fail dispatch
        return ""


class PerfLedger:
    """Thread-safe accumulator behind ``/internal/perf``.

    Group rows and SLO rows are bounded ``OrderedDict`` rings: recording
    touches move a row to the back, and inserts beyond ``max_groups``
    evict the least-recently-touched row (counted, so the summary can say
    coverage was dropped rather than silently truncating)."""

    def __init__(self, max_groups: Optional[int] = None,
                 slo_target: Optional[float] = None) -> None:
        if max_groups is None:
            max_groups = env_int("SDTPU_PERF_GROUPS", DEFAULT_GROUPS)
        if slo_target is None:
            slo_target = env_float("SDTPU_PERF_SLO_TARGET",
                                   DEFAULT_SLO_TARGET)
        self.max_groups = max(1, int(max_groups or DEFAULT_GROUPS))
        self.slo_target = min(0.9999, max(0.0, float(slo_target)))
        self._lock = threading.Lock()
        self._groups: \
            "OrderedDict[Tuple[str, int, str, str], Dict[str, float]]" \
            = OrderedDict()  # guarded-by: _lock
        self._groups_evicted = 0  # guarded-by: _lock
        self._compiles: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock
        #: artifact deserializes, keyed like _compiles but never mixed in
        #: (serving/aot.py record_compile(source="aot_load"))
        self._aot_loads: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock
        self._slo: "OrderedDict[Tuple[str, str], Dict[str, Any]]" \
            = OrderedDict()  # guarded-by: _lock
        self._slo_evicted = 0  # guarded-by: _lock
        self._last_dispatch: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        self._device_kind: Optional[str] = None  # guarded-by: _lock

    # -- recording (dispatcher / engine side) ------------------------------

    def record_dispatch(self, *, bucket: str, cadence: int, precision: str,
                        lora: str = "",
                        device_s: float, flops: float, requests: int,
                        batch_raw: int, batch_run: int, true_pixels: int,
                        padded_pixels: int, masked_pixels: int = 0,
                        true_tokens: int = 0, padded_tokens: int = 0,
                        hbm: Optional[Dict[str, int]] = None
                        ) -> None:
        """One device dispatch: host-observed seconds + the FLOPs priced
        for the same denoise range + true-vs-padded shape accounting.
        No-op (and never raises) when ``SDTPU_PERF`` is off.

        ``padded_pixels`` counts everything RESIDENT in the dispatch
        (bucket area x batch_run); ``masked_pixels`` is the slice of that
        the ragged attention kernel masks instead of attending to —
        resident HBM but no attention FLOPs — so the summary can split
        masked padding from compute padding. ``true_tokens`` /
        ``padded_tokens`` carry the conditioning's true-vs-padded token
        counts behind the ``token_padding_ratio`` gauge.

        ``hbm`` is the device-memory sample for this dispatch
        (``obs/tsdb.dispatch_memory_sample()``: bytes_in_use /
        peak_bytes_in_use / live_buffers keys as available) — ``None``
        on CPU or when memory_stats is unsupported, and the group row
        then reports null watermarks rather than fabricating them.

        ``lora`` is the traced-adapter cell label (``"r8s1"``-style, "" on
        adapterless and merged-path dispatches) — appended as the LAST
        group-key axis so adapter-active traffic gets its own MFU rows
        without disturbing key[0..2] consumers."""
        if not enabled():
            return
        try:
            key = (str(bucket), int(cadence), str(precision), str(lora))
            with self._lock:
                if self._device_kind is None:
                    self._device_kind = _device_kind()
                g = self._groups.get(key)
                if g is None:
                    if len(self._groups) >= self.max_groups:
                        self._groups.popitem(last=False)
                        self._groups_evicted += 1
                    g = {"dispatches": 0, "requests": 0, "device_s": 0.0,
                         "flops": 0.0, "true_pixels": 0, "padded_pixels": 0,
                         "batch_raw": 0, "batch_run": 0, "masked_pixels": 0,
                         "true_tokens": 0, "padded_tokens": 0}
                    self._groups[key] = g
                else:
                    self._groups.move_to_end(key)
                g["dispatches"] += 1
                g["requests"] += int(requests)
                g["device_s"] += max(0.0, float(device_s))
                g["flops"] += max(0.0, float(flops))
                g["true_pixels"] += int(true_pixels)
                g["padded_pixels"] += int(padded_pixels)
                g["batch_raw"] += int(batch_raw)
                g["batch_run"] += int(batch_run)
                g["masked_pixels"] += int(masked_pixels)
                g["true_tokens"] += int(true_tokens)
                g["padded_tokens"] += int(padded_tokens)
                if hbm:
                    # watermark semantics: keep the highest peak / latest
                    # in-use the group has seen (never fabricated on CPU)
                    if hbm.get("peak_bytes_in_use") is not None:
                        g["hbm_peak_bytes"] = max(
                            int(g.get("hbm_peak_bytes", 0)),
                            int(hbm["peak_bytes_in_use"]))
                    if hbm.get("bytes_in_use") is not None:
                        g["hbm_bytes_in_use"] = int(hbm["bytes_in_use"])
                    if hbm.get("live_buffers") is not None:
                        g["live_buffers"] = int(hbm["live_buffers"])
                compiles_total = sum(int(c["count"])
                                     for c in self._compiles.values())
                self._last_dispatch = self._dispatch_entry(
                    key, g, device_s, flops, self._device_kind,
                    compiles_total)
        except Exception:  # noqa: BLE001 — telemetry must not fail dispatch
            pass

    def record_stages(self, *, bucket: str, cadence: int, precision: str,
                      lora: str = "", stage_s: float,
                      overlap_s: float) -> None:
        """Stage-graph accounting for one dispatch group
        (SDTPU_STAGE_GRAPH, parallel/stage_graph.py): host seconds spent
        in the non-denoise stages (encode / decode dispatch / merge
        fetch) and the slice of them that overlapped OTHER groups'
        denoise windows. Merged into the same (bucket, cadence,
        precision, lora) accumulator as record_dispatch so the group row
        gains a ``stage_overlap_ratio`` column; rows that never ran the
        stage graph default both to 0 and read identically to before.
        No-op (and never raises) when ``SDTPU_PERF`` is off."""
        if not enabled():
            return
        try:
            key = (str(bucket), int(cadence), str(precision), str(lora))
            with self._lock:
                g = self._groups.get(key)
                if g is None:
                    # stage records may land before/without a dispatch
                    # record (finalize runs outside the device lock);
                    # seed the same accumulator record_dispatch builds
                    if len(self._groups) >= self.max_groups:
                        self._groups.popitem(last=False)
                        self._groups_evicted += 1
                    g = {"dispatches": 0, "requests": 0, "device_s": 0.0,
                         "flops": 0.0, "true_pixels": 0, "padded_pixels": 0,
                         "batch_raw": 0, "batch_run": 0, "masked_pixels": 0,
                         "true_tokens": 0, "padded_tokens": 0}
                    self._groups[key] = g
                else:
                    self._groups.move_to_end(key)
                g["stage_s"] = g.get("stage_s", 0.0) \
                    + max(0.0, float(stage_s))
                g["stage_overlap_s"] = g.get("stage_overlap_s", 0.0) \
                    + max(0.0, float(overlap_s))
        except Exception:  # noqa: BLE001 — telemetry must not fail dispatch
            pass

    def record_compile(self, kind: str, seconds: float,
                       source: str = "fresh_compile") -> None:
        """One compiled-stage build (``Engine._cached``); also feeds the
        per-kind Prometheus compile-latency histogram. ``source`` splits
        the accounting: ``fresh_compile`` is a real XLA build,
        ``aot_load`` is an artifact deserialize (serving/aot.py) — the
        two land in separate accumulators and separate Prometheus
        families so MFU/ledger analysis never mistakes a 200ms hydration
        for a compile."""
        if not enabled():
            return
        try:
            aot = str(source) == "aot_load"
            with self._lock:
                table = self._aot_loads if aot else self._compiles
                c = table.setdefault(
                    str(kind), {"count": 0, "total_s": 0.0, "max_s": 0.0,
                                "last_s": 0.0})
                c["count"] += 1
                c["total_s"] += max(0.0, float(seconds))
                c["max_s"] = max(c["max_s"], float(seconds))
                c["last_s"] = float(seconds)
            from stable_diffusion_webui_distributed_tpu.obs import (
                prometheus as obs_prom,
            )

            if aot:
                obs_prom.observe_aot_load(str(kind), float(seconds))
            else:
                obs_prom.observe_compile(str(kind), float(seconds))
        except Exception:  # noqa: BLE001 — telemetry must not fail compiles
            pass

    def record_slo(self, *, tenant: str, cls: str, slo_s: float,
                   latency_s: float, ok: bool = True) -> None:
        """One fleet-gated request completion against its resolved SLO.
        ``met`` requires both success and on-time delivery — an errored
        request burns the same budget as a late one."""
        if not enabled():
            return
        try:
            met = bool(ok) and float(latency_s) <= float(slo_s)
            key = (str(tenant), str(cls))
            with self._lock:
                row = self._slo.get(key)
                if row is None:
                    if len(self._slo) >= self.max_groups:
                        self._slo.popitem(last=False)
                        self._slo_evicted += 1
                    row = {"total": 0, "met": 0, "slo_s": float(slo_s),
                           "window": deque(maxlen=SLO_WINDOW)}
                    self._slo[key] = row
                else:
                    self._slo.move_to_end(key)
                row["total"] += 1
                row["met"] += 1 if met else 0
                row["slo_s"] = float(slo_s)
                row["window"].append(1 if met else 0)
        except Exception:  # noqa: BLE001 — telemetry must not fail dispatch
            pass

    # -- derivation --------------------------------------------------------

    @staticmethod
    def _dispatch_entry(key: Tuple[str, int, str, str],
                        g: Dict[str, float], device_s: float,
                        flops: float, device_kind: Optional[str],
                        compiles_total: int) -> Dict[str, Any]:
        # static: the caller (record_dispatch, under _lock) passes the
        # guarded values in, so this stays pure derivation; computes the
        # flight-recorder snapshot for THIS dispatch (instant values, not
        # the group's running sums)
        peak = peak_flops_for(device_kind or "", key[2])
        mfu = None
        if peak and device_s > 0:
            mfu = float(flops) / float(device_s) / peak
        true_px = g["true_pixels"]
        padded_px = g["padded_pixels"]
        return {
            "bucket": key[0], "cadence": key[1], "precision": key[2],
            "lora": key[3],
            "device_s": round(float(device_s), 6),
            "flops": float(flops),
            "mfu": mfu,
            "padding_ratio": (padded_px / true_px) if true_px else None,
            "compiles_total": int(compiles_total),
        }

    @staticmethod
    def _group_row(key: Tuple[str, int, str, str], g: Dict[str, float],
                   device_kind: Optional[str]) -> Dict[str, Any]:
        # static for the same reason as _dispatch_entry (LK001 discipline)
        peak = peak_flops_for(device_kind or "", key[2])
        mfu = None
        if peak and g["device_s"] > 0:
            mfu = g["flops"] / g["device_s"] / peak
        true_px, padded_px = g["true_pixels"], g["padded_pixels"]
        ratio = (padded_px / true_px) if true_px else None
        # ragged split (defaulted 0 so pre-ragged rows read identically):
        # masked pixels are resident-but-not-attended — subtracting them
        # gives the padding you actually pay attention FLOPs for
        masked_px = int(g.get("masked_pixels", 0))
        true_tok = int(g.get("true_tokens", 0))
        padded_tok = int(g.get("padded_tokens", 0))
        # stage-graph split (defaulted 0.0 so pre-stage-graph rows read
        # identically): fraction of encode/decode/merge host seconds that
        # ran inside another group's denoise window
        stage_s = float(g.get("stage_s", 0.0))
        stage_ov = float(g.get("stage_overlap_s", 0.0))
        return {
            "bucket": key[0], "cadence": key[1], "precision": key[2],
            "lora": key[3],
            "dispatches": int(g["dispatches"]),
            "requests": int(g["requests"]),
            "device_s": g["device_s"],
            "flops": g["flops"],
            "mfu": mfu,
            "padding_ratio": ratio,
            "padding_waste": (1.0 - true_px / padded_px) if padded_px
            else None,
            "batch_raw": int(g["batch_raw"]),
            "batch_run": int(g["batch_run"]),
            "masked_pixels": masked_px,
            "compute_padding_ratio": ((padded_px - masked_px) / true_px)
            if true_px else None,
            "token_padding_ratio": (padded_tok / true_tok)
            if true_tok else None,
            "stage_overlap_ratio": (stage_ov / stage_s) if stage_s
            else 0.0,
            # device-memory watermark (defaulted None: CPU rows and
            # pre-telemetry rows read identically — never fabricated)
            "hbm_peak_bytes": g.get("hbm_peak_bytes"),
            "hbm_bytes_in_use": g.get("hbm_bytes_in_use"),
            "live_buffers": g.get("live_buffers"),
        }

    def _slo_row(self, key: Tuple[str, str],
                 row: Dict[str, Any]) -> Dict[str, Any]:
        window = list(row["window"])
        misses = window.count(0)
        budget = 1.0 - self.slo_target
        burn = (misses / len(window)) / budget if window and budget > 0 \
            else 0.0
        return {
            "tenant": key[0], "class": key[1], "slo_s": row["slo_s"],
            "total": row["total"], "met": row["met"],
            "attainment": row["met"] / row["total"] if row["total"] else None,
            "window": len(window), "window_misses": misses,
            "burn_rate": burn,
        }

    # -- readers -----------------------------------------------------------

    def last_dispatch(self) -> Optional[Dict[str, Any]]:
        """The most recent dispatch's perf snapshot (flight recorder)."""
        with self._lock:
            return dict(self._last_dispatch) if self._last_dispatch else None

    def summary(self) -> Dict[str, Any]:
        """The ``/internal/perf`` body."""
        with self._lock:
            groups = [self._group_row(k, g, self._device_kind)
                      for k, g in self._groups.items()]
            slo = [self._slo_row(k, r) for k, r in self._slo.items()]
            compiles = {k: dict(c) for k, c in self._compiles.items()}
            aot_loads = {k: dict(c) for k, c in self._aot_loads.items()}
            evicted, slo_evicted = self._groups_evicted, self._slo_evicted
            device_kind = self._device_kind or ""
        # hit rate over the stage materializations this ledger saw:
        # loads / (loads + fresh compiles); None until either happens
        n_loads = sum(int(c["count"]) for c in aot_loads.values())
        n_fresh = sum(int(c["count"]) for c in compiles.values())
        out = {
            "enabled": enabled(),
            "device_kind": device_kind,
            "peak_flops_bf16": peak_flops_for(device_kind, "bf16"),
            "groups": groups,
            "groups_evicted": evicted,
            "compiles": compiles,
            "aot_loads": aot_loads,
            "aot_hit_rate": (n_loads / (n_loads + n_fresh)
                             if (n_loads + n_fresh) else None),
            "slo": slo,
            "slo_evicted": slo_evicted,
            "slo_target": self.slo_target,
        }
        try:
            # caching tier (SDTPU_CACHE): hit/miss/bytes per layer ride
            # along in the perf body so one scrape answers "is the cache
            # pulling its weight"; {"enabled": False} when gated off
            from stable_diffusion_webui_distributed_tpu import cache
            out["cache"] = (cache.summary() if cache.enabled()
                            else {"enabled": False})
        except Exception:  # noqa: BLE001 — perf body stays best-effort
            out["cache"] = {"enabled": False}
        return out

    def clear(self) -> None:
        with self._lock:
            self._groups.clear()
            self._compiles.clear()
            self._aot_loads.clear()
            self._slo.clear()
            self._groups_evicted = 0
            self._slo_evicted = 0
            self._last_dispatch = None
            self._device_kind = None


#: Process-wide ledger (mirrors METRICS / STATS / TRACER).
LEDGER = PerfLedger()


# -- executable census -------------------------------------------------------

def census_from_keys(keys: Iterable[Tuple],
                     step_cache_budget: int = STEP_CACHE_BUDGET,
                     precision_budget: int = PRECISION_BUDGET,
                     lora_budget: int = LORA_BUDGET
                     ) -> Dict[str, Any]:
    """Group compiled-stage cache keys by shape bucket and check the
    chunk-executable budget. Chunk keys are ``("chunk", sampler, steps,
    w, h, batch, ..., lora_sig, step_cache, precision)``
    (pipeline/engine.py) — everything between the kind and the last three
    axes identifies the bucket; the trailing axes are the budgeted
    variants. The lora_sig axis ("" adapterless, ``"lora:rXsY"`` per
    traced ladder cell) is recognized by its string shape, so older key
    layouts (no lora axis) census exactly as before. The lora allowance
    is PER CELL, not per adapter — any number of adapter combos share a
    cell's executables, which is the recompile-free serving contract."""
    buckets: "OrderedDict[Tuple, Dict[str, Any]]" = OrderedDict()
    other = 0
    total_chunks = 0
    for k in keys:
        if not (isinstance(k, tuple) and len(k) >= 8 and k[0] == "chunk"):
            other += 1
            continue
        total_chunks += 1
        lora_v = ""
        ident = k[1:-2]
        if isinstance(k[-3], str) and (k[-3] == ""
                                       or k[-3].startswith("lora:")):
            lora_v = k[-3]
            ident = k[1:-3]
        b = buckets.get(ident)
        if b is None:
            b = {
                "bucket": f"{k[1]}/{k[2]}st {k[3]}x{k[4]} b{k[5]}",
                "executables": 0,
                "step_cache_variants": set(),
                "precision_variants": set(),
                "lora_variants": set(),
            }
            buckets[ident] = b
        b["executables"] += 1
        b["step_cache_variants"].add(k[-2])
        b["precision_variants"].add(str(k[-1]))
        b["lora_variants"].add(lora_v)
    rows: List[Dict[str, Any]] = []
    over: List[str] = []
    for b in buckets.values():
        sc, prec = b["step_cache_variants"], b["precision_variants"]
        n_lora = len([v for v in b["lora_variants"] if v])
        over_budget = (len(sc) > step_cache_budget
                       or len(prec) > precision_budget
                       or n_lora > lora_budget
                       or b["executables"] > step_cache_budget
                       * precision_budget * (1 + n_lora))
        rows.append({
            "bucket": b["bucket"],
            "executables": b["executables"],
            "step_cache_variants": len(sc),
            "precisions": sorted(prec),
            "lora_variants": n_lora,
            "over_budget": over_budget,
        })
        if over_budget:
            over.append(b["bucket"])
    return {
        "buckets": rows,
        "chunk_executables": total_chunks,
        "other_executables": other,
        "budget": {"step_cache": step_cache_budget,
                   "precision": precision_budget,
                   "lora": lora_budget,
                   "per_bucket": step_cache_budget * precision_budget},
        "over_budget": over,
        "alarm": bool(over),
    }


def executables_census(engine: Any) -> Dict[str, Any]:
    """Live census over an engine's compiled-stage cache (the
    ``/internal/executables`` body). Pure read — no compiles, no device
    work."""
    return census_from_keys(engine.executable_keys())

"""Hang watchdog: stall detection for dispatches and remote jobs.

The scheduler already *predicts* how long a job should take (the paper's
benchmark/ETA loop, scheduler/eta.py); nothing watches whether reality
agrees. A wedged remote worker or a device dispatch stuck in a collective
just sits there until the 3600s HTTP timeout. This module arms a small
daemon timer around any operation with a known ETA: if the operation has
not disarmed the timer after ``SDTPU_WATCHDOG_FACTOR`` x ETA seconds, the
watchdog

- captures a full thread-stack dump into the flight recorder
  (:mod:`.flightrec`) so the hang site is diagnosable post-mortem,
- bumps ``sdtpu_watchdog_stalls_total`` (:mod:`.prometheus`),
- journals a ``watchdog_stall`` event (:mod:`.journal`, when on), and
- invokes the caller's ``on_stall`` hook — ``World.execute`` uses it to
  abandon the stalled job thread so the slice falls into the existing
  ``_requeue_failed`` path.

Gated off by default: ``SDTPU_WATCHDOG_FACTOR`` <= 0 (the default 0)
means :func:`arm` returns ``None`` and nothing is spawned, keeping the
default serving path byte-identical. The arm/disarm shape mirrors
``WorkerNode._start_interrupt_watchdog``.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Callable, Optional

from ..runtime.config import env_float
from ..runtime.daemon import StoppableDaemon


def factor() -> float:
    """Stall threshold as a multiple of the operation's ETA; <= 0 = off.
    Re-read per call so tests can flip the env var."""
    return env_float("SDTPU_WATCHDOG_FACTOR", 0.0) or 0.0


def enabled() -> bool:
    return factor() > 0.0


def dump_stacks(max_frames: int = 40) -> str:
    """Format every live thread's stack (named, most frames first)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for tid, frame in sorted(sys._current_frames().items()):
        name = names.get(tid, "?")
        stack = "".join(traceback.format_stack(frame)[-max_frames:])
        chunks.append(f"Thread {name} (ident={tid}):\n{stack}")
    return "\n".join(chunks)


def arm(request_id: str, name: str, eta_s: Optional[float],
        on_stall: Optional[Callable[[], None]] = None,
        ) -> Optional[StoppableDaemon]:
    """Start watching one operation; returns the disarm handle, or
    ``None`` when the watchdog is off or no ETA is known. The caller
    MUST :func:`disarm` the returned handle from a ``finally`` block."""
    k = factor()
    if k <= 0.0 or not eta_s or eta_s <= 0.0:
        return None
    deadline_s = k * float(eta_s)

    def fire() -> None:
        _record_stall(request_id, name, float(eta_s), deadline_s)
        if on_stall is not None:
            try:
                on_stall()
            except Exception:
                pass

    timer = StoppableDaemon.one_shot(f"watchdog-{name}", deadline_s, fire)
    timer.start()
    return timer


def disarm(timer: Optional[StoppableDaemon]) -> None:
    if timer is not None:
        timer.halt()  # signal only: disarm runs on request hot paths


def _record_stall(request_id: str, name: str, eta_s: float,
                  waited_s: float) -> None:
    from . import flightrec, journal
    from . import prometheus as prom
    from ..runtime.logging import get_logger

    stacks = dump_stacks()
    prom.count_watchdog_stall(name)
    if journal.enabled():
        journal.emit("watchdog_stall", request_id or "", name=name,
                     eta_s=eta_s, waited_s=waited_s)
    get_logger().warning(
        "watchdog: %s stalled past %.2fs (%.2gx ETA %.2fs), request '%s'",
        name, waited_s, factor(), eta_s, request_id)
    flightrec.RECORDER.record(
        request_id or "", "watchdog_stall",
        f"{name} exceeded {factor():g}x ETA ({eta_s:.2f}s ETA, waited "
        f"{waited_s:.2f}s); thread stacks:\n{stacks}",
        events=[], duration_s=waited_s)

"""Push control plane (SDTPU_PUSH): streaming worker deltas.

The federation prober (obs/federation.py) learns about remote workers
by *polling* their REST API — the reference's shape, and the wrong one
for pod-scale serving: staleness is bounded below by the poll cadence,
a full scrape re-ships the whole TSDB document every tick, and worker
journal events never leave the worker at all. This module inverts the
flow:

- **Worker side** (:class:`DeltaBuffer`): a cursor-indexed bounded
  buffer fed from the worker's *existing* telemetry — journal events,
  TSDB samples of the federated series, and worker-counter totals.
  Every entry gets a monotonically increasing cursor; ``GET
  /internal/deltas?cursor=N`` (server/api.py) long-polls and returns
  everything after N plus ``next_cursor``, so a reconnecting consumer
  resumes exactly where it left off — no loss, no duplicates. Past
  ``SDTPU_PUSH_CURSOR_BUF`` retained entries the oldest is evicted
  (slow-consumer backpressure): evictions are counted, journaled as
  ``push_buffer_evicted``, and surface as ``lost`` in any response
  whose cursor predates the retained window.
- **Master side** (:class:`DeltaSubscriber`, one per worker, each on a
  ``runtime/daemon.py`` StoppableDaemon): long-polls the worker's delta
  endpoint with reconnect + exponential backoff, resumes from its
  cursor after a disconnect, and writes the digested entries into the
  *same* ``worker:<label>/...`` + ``fleet/...`` TSDB series the poll
  prober fills — the alert rules and the autoscaler's fleet signal are
  source-agnostic. Journal entries stream into the fleet timeline
  (obs/fleetlog.py) with the RTT-midpoint clock offset
  (obs/stitch.py) attached. A worker that answers 404 (predates the
  endpoint, or runs with the gate off) demotes its subscriber to the
  poll path (``push_fallback`` journaled) using the prober's own fetch
  + digest — push is an upgrade, never a requirement.

Staleness keeps the poll prober's semantics: the anchor is the fetch
RTT midpoint, the deadline is :func:`federation.stale_after_s`, so the
``worker_metrics_stale`` alert fires identically under either plane —
only the anchor moves more often under push.

Gated off by default: with ``SDTPU_PUSH`` unset no source registers,
``/internal/deltas`` answers 404, :func:`tick` is a no-op, no daemon
starts, and the serving path is byte-identical to the poll-only build
(hash-pinned in tests/test_push.py).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..runtime.config import env_flag, env_float, env_int
from ..runtime.daemon import StoppableDaemon
from . import federation, stitch

#: Long-poll slice: how long one /internal/deltas request may hold the
#: connection waiting for fresh entries before answering empty.
DEFAULT_WAIT_S = 0.25

#: Hard cap on entries per response (a reconnecting subscriber with an
#: ancient cursor pages through the buffer instead of one giant body).
_MAX_ENTRIES_PER_RESPONSE = 500

#: Reconnect backoff: base * 2**consecutive_failures, capped.
_BACKOFF_BASE_S = 0.05
_BACKOFF_MAX_S = 2.0


def enabled() -> bool:
    """Push gate — re-read per call so tests can flip the env var."""
    return env_flag("SDTPU_PUSH", False)


def cursor_buf() -> int:
    """Worker-side retained-entry depth (SDTPU_PUSH_CURSOR_BUF)."""
    return max(16, env_int("SDTPU_PUSH_CURSOR_BUF", 1024))


def wait_s() -> float:
    """Long-poll hold (SDTPU_PUSH_WAIT_S); the subscriber's fetches and
    the /internal/deltas default both resolve here."""
    return max(0.0, env_float("SDTPU_PUSH_WAIT_S", DEFAULT_WAIT_S))


# -- worker side -------------------------------------------------------------

class DeltaBuffer:
    """Cursor-indexed bounded buffer over the worker's local telemetry.

    Entries are dicts with a ``cursor`` plus a ``kind``: ``journal``
    (one journal event), ``sample`` (one TSDB sample of a federated
    series), or ``counter`` (a worker-counter total that changed).
    :meth:`ingest` pulls from the live sources; :meth:`collect` is the
    ``GET /internal/deltas`` body. Tests feed :meth:`publish` directly.
    """

    def __init__(self, capacity: Optional[int] = None,
                 clock=time.monotonic) -> None:
        self._clock = clock
        self._capacity = capacity
        self._lock = threading.Lock()
        self._entries: Deque[Dict[str, Any]] = deque()  # guarded-by: _lock
        self._next = 1                                 # guarded-by: _lock
        self._evicted = 0                              # guarded-by: _lock
        # source positions (last journal seq / per-series sample time /
        # counter totals already shipped)               guarded-by: _lock
        self._journal_seq = -1
        self._series_pos: Dict[str, float] = {}
        self._counter_last: Dict[str, float] = {}

    def capacity(self) -> int:
        return self._capacity if self._capacity is not None else cursor_buf()

    def publish(self, kind: str, payload: Dict[str, Any]) -> int:
        """Append one entry (assigning its cursor); returns how many
        old entries were evicted to make room."""
        cap = self.capacity()
        with self._lock:
            entry = dict(payload)
            entry["cursor"] = self._next
            entry["kind"] = kind
            self._next += 1
            self._entries.append(entry)
            evicted = 0
            while len(self._entries) > cap:
                self._entries.popleft()
                evicted += 1
            self._evicted += evicted
        return evicted

    # -- source ingestion --------------------------------------------------

    def ingest(self, now: Optional[float] = None) -> int:
        """Pull everything new from the journal, the federated TSDB
        series, and the worker counters; returns how many entries
        landed. Evictions forced by the pass are journaled once (the
        ``push_buffer_evicted`` closed-vocabulary event) so a slow
        consumer's loss is in the decision trail, not just a counter."""
        appended = 0
        evicted = 0
        for kind, payload in self._gather(now):
            evicted += self.publish(kind, payload)
            appended += 1
        if evicted:
            self._journal_eviction(evicted)
        return appended

    def _gather(self, now: Optional[float]) -> List[
            Tuple[str, Dict[str, Any]]]:
        """Snapshot the sources and diff them against the shipped
        positions (positions advance under the lock; the snapshots are
        taken outside it — sources have their own locks, LK004)."""
        out: List[Tuple[str, Dict[str, Any]]] = []
        events: List[Dict[str, Any]] = []
        try:
            from . import journal as obs_journal

            if obs_journal.enabled():
                events = obs_journal.JOURNAL.snapshot()["events"]
        except Exception:  # noqa: BLE001 — telemetry stays passive
            events = []
        samples: Dict[str, List[Tuple[float, float]]] = {}
        totals: Dict[str, float] = {}
        try:
            from . import tsdb as obs_tsdb

            if obs_tsdb.enabled():
                for name in federation._REMOTE_SERIES:
                    samples[name] = obs_tsdb.STORE.window(name, 0)
        except Exception:  # noqa: BLE001
            samples = {}
        try:
            from . import prometheus as obs_prom

            totals = {
                "requests_total":
                    obs_prom.WORKER_COUNTERS["requests"].total(),
                "failures_total":
                    obs_prom.WORKER_COUNTERS["failures"].total(),
            }
        except Exception:  # noqa: BLE001
            totals = {}
        with self._lock:
            for ev in events:
                seq = ev.get("seq", -1)
                if seq > self._journal_seq:
                    self._journal_seq = seq
                    out.append(("journal", {"event": dict(ev)}))
            for name, ring in samples.items():
                pos = self._series_pos.get(name)
                for t, v in ring:
                    if pos is None or t > pos:
                        out.append(("sample", {"name": name,
                                               "t": t, "v": v}))
                        self._series_pos[name] = t
                        pos = t
            for name, total in totals.items():
                last = self._counter_last.get(name)
                if last is None and not total:
                    # a zero initial total carries no signal; don't
                    # spend a cursor on it
                    self._counter_last[name] = total
                    continue
                if last != total:
                    self._counter_last[name] = total
                    out.append(("counter", {"name": name, "total": total}))
        return out

    @staticmethod
    def _journal_eviction(n: int) -> None:
        try:
            from . import journal as obs_journal

            if obs_journal.enabled():
                obs_journal.emit("push_buffer_evicted", "push-buffer",
                                 evicted=n)
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass

    # -- the endpoint body -------------------------------------------------

    def collect(self, cursor: int, hold_s: float = 0.0,
                max_entries: int = _MAX_ENTRIES_PER_RESPONSE,
                ) -> Dict[str, Any]:
        """The ``GET /internal/deltas?cursor=N`` document: every entry
        after ``cursor`` (bounded), the buffer's ``next_cursor``, how
        many entries the consumer's cursor can no longer reach
        (``lost`` — evicted before it fetched), and a ``clock_us``
        sample for the subscriber's RTT-midpoint clock correction.
        Long-polls up to ``hold_s`` when nothing is pending."""
        cursor = max(0, int(cursor))
        deadline = self._clock() + max(0.0, hold_s)
        while True:
            self.ingest()
            with self._lock:
                entries = [dict(e) for e in self._entries
                           if e["cursor"] > cursor][:max_entries]
                next_cursor = self._next - 1
                evicted_total = self._evicted
                oldest = self._entries[0]["cursor"] if self._entries \
                    else None
            if entries or self._clock() >= deadline:
                break
            # idle long-poll slice: re-ingest on a short cadence (no
            # lock held across the sleep)
            time.sleep(min(0.02, max(0.001, deadline - self._clock())))
        if oldest is not None:
            lost = max(0, oldest - cursor - 1)
        else:
            lost = max(0, next_cursor - cursor)
        return {
            "enabled": enabled(),
            "next_cursor": next_cursor,
            "evicted_total": evicted_total,
            "lost": lost,
            "clock_us": self._clock() * 1e6,
            "entries": entries,
        }

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"retained": len(self._entries),
                    "next_cursor": self._next - 1,
                    "evicted_total": self._evicted}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._next = 1
            self._evicted = 0
            self._journal_seq = -1
            self._series_pos.clear()
            self._counter_last.clear()


#: Process-wide buffer behind GET /internal/deltas.
BUFFER = DeltaBuffer()


def serve_deltas(cursor: int = 0,
                 hold_s: Optional[float] = None) -> Dict[str, Any]:
    """Module-level endpoint body; the API layer 404s with the gate off
    (so a push-preferring master falls back to polling this node)."""
    if hold_s is None:
        hold_s = wait_s()
    return BUFFER.collect(cursor, hold_s=hold_s)


# -- master side -------------------------------------------------------------

class _HTTPStatusError(Exception):
    """Wraps a non-2xx delta fetch with its status code."""

    def __init__(self, status: int, detail: str) -> None:
        super().__init__(detail)
        self.status = status


def _subscribable(worker: Any) -> bool:
    """A worker the push plane can stream from: its backend exposes a
    test/bench fetch seam (``push_fetch``) or anything the federation
    prober could poll — 404 demotion covers the rest."""
    backend = getattr(worker, "backend", None)
    if backend is None:
        return False
    if callable(getattr(backend, "push_fetch", None)):
        return True
    return federation._pollable(worker)


class DeltaSubscriber:
    """One worker's delta stream -> the local TSDB + fleet timeline.

    ``poll_once`` is one fetch/apply cycle (tests and the bench drive it
    directly with explicit clocks); :meth:`start`/:meth:`stop` run it on
    a StoppableDaemon whose period stretches with the reconnect backoff.
    After a 404 the subscriber *falls back to polling* this worker with
    the federation prober's own fetch + digest — same series, higher
    staleness.
    """

    def __init__(self, label: str, backend: Any, store=None,
                 clock=time.monotonic, manager: Optional[Any] = None,
                 ) -> None:
        self.label = str(label)
        self.backend = backend
        self._store = store
        self._clock = clock
        self._manager = manager
        self._lock = threading.Lock()
        self.mode = "push"                             # guarded-by: _lock
        self.cursor = 0                                # guarded-by: _lock
        self._failures_row = 0       # consecutive; guarded-by: _lock
        self._st: Dict[str, Any] = {                   # guarded-by: _lock
            "first_seen": None, "last_ok": None, "rtt_s": None,
            "last_error": None, "polls": 0, "failures": 0}
        self._applied = 0                              # guarded-by: _lock
        self._duplicates = 0                           # guarded-by: _lock
        self._lost = 0                                 # guarded-by: _lock
        self._fallbacks = 0                            # guarded-by: _lock
        self._offset_s: Optional[float] = None         # guarded-by: _lock
        self._counters: Dict[str, float] = {}          # guarded-by: _lock
        self._row: Dict[str, float] = {}               # guarded-by: _lock
        self._daemon = StoppableDaemon(
            f"sdtpu-push-{self.label}", self._daemon_tick, self._period)

    def store(self):
        if self._store is not None:
            return self._store
        from . import tsdb as obs_tsdb

        return obs_tsdb.STORE

    # -- daemon plumbing ---------------------------------------------------

    def _period(self) -> float:
        with self._lock:
            failures = self._failures_row
        if failures:
            return min(_BACKOFF_MAX_S, _BACKOFF_BASE_S * (2 ** failures))
        # the long-poll hold paces the loop; the period only bounds the
        # idle re-check latency
        return max(0.01, _BACKOFF_BASE_S)

    def _daemon_tick(self) -> None:
        try:
            self.poll_once()
        except Exception:  # noqa: BLE001 — the stream must survive
            pass

    def start(self) -> None:
        self._daemon.start()

    def stop(self, timeout_s: float = 2.0) -> bool:
        return self._daemon.stop(timeout_s=timeout_s)

    def alive(self) -> bool:
        return self._daemon.alive()

    # -- fetch -------------------------------------------------------------

    def _fetch(self, cursor: int) -> Tuple[Dict[str, Any], float, float]:
        """(doc, t0, t1): one bracketed delta fetch. ``push_fetch`` is
        the in-process seam tests/bench use; the HTTP path carries the
        obs-plane timeout. Raises :class:`_HTTPStatusError` with the
        status on a non-2xx answer (404 = fall back to polling)."""
        t0 = self._clock()
        fetcher = getattr(self.backend, "push_fetch", None)
        if callable(fetcher):
            doc = fetcher(cursor)
        else:
            timeout = max(stitch.http_timeout_s(), wait_s() + 0.5)
            scheme = "https" if getattr(self.backend, "tls", False) \
                else "http"
            base = f"{scheme}://{self.backend.address}:{self.backend.port}"
            url = f"{base}/internal/deltas?cursor={int(cursor)}"
            try:
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    doc = json.loads(resp.read().decode("utf-8", "replace"))
            except urllib.error.HTTPError as e:
                raise _HTTPStatusError(e.code, str(e)) from e
        return doc, t0, self._clock()

    # -- one cycle ---------------------------------------------------------

    def poll_once(self, now: Optional[float] = None) -> int:
        """One fetch/apply cycle; returns how many entries applied (or
        TSDB samples landed, on the poll-fallback path). Never raises
        out of a fetch failure — the failure is bookkept and the series
        records staleness growth instead."""
        if now is None:
            now = self._clock()
        with self._lock:
            if self._st["first_seen"] is None:
                self._st["first_seen"] = now
            self._st["polls"] += 1
            mode = self.mode
            cursor = self.cursor
        if mode == "poll":
            return self._poll_fallback(now)
        try:
            doc, t0, t1 = self._fetch(cursor)
        except _HTTPStatusError as e:
            if e.status == 404:
                self._demote(now, str(e))
                return self._poll_fallback(now)
            self._note_failure(now, str(e))
            return 0
        except Exception as e:  # noqa: BLE001 — per-node fault isolation
            self._note_failure(now, f"{type(e).__name__}: {e}")
            return 0
        return self._apply(doc, t0, t1, now)

    def _demote(self, now: float, detail: str) -> None:
        """404: the worker predates /internal/deltas (or runs with the
        gate off) — journal once and poll it from here on."""
        with self._lock:
            if self.mode == "poll":
                return
            self.mode = "poll"
            self._fallbacks += 1
        try:
            from . import journal as obs_journal

            if obs_journal.enabled():
                obs_journal.emit("push_fallback", f"push-{self.label}",
                                 worker=self.label, detail=detail)
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass

    def _note_failure(self, now: float, detail: str) -> None:
        with self._lock:
            self._failures_row += 1
            self._st["failures"] += 1
            self._st["last_error"] = detail
            anchor = self._st["last_ok"] if self._st["last_ok"] is not None \
                else self._st["first_seen"]
        staleness = max(0.0, now - anchor)
        self.store().record(f"worker:{self.label}/staleness_s",
                            staleness, t=now)
        try:
            from . import journal as obs_journal

            if obs_journal.enabled():
                obs_journal.emit("federation_poll_failed",
                                 f"federation-{self.label}",
                                 worker=self.label, transport="push",
                                 error=detail)
        except Exception:  # noqa: BLE001 — telemetry stays passive
            pass
        self._after_cycle(now)

    def _apply(self, doc: Dict[str, Any], t0: float, t1: float,
               now: float) -> int:
        """Digest one delta document into the poll prober's series +
        the fleet timeline. Duplicate entries (cursor <= ours — a retry
        that raced its own response) are dropped; a reported ``lost``
        (evicted before we fetched) is accumulated for the bench gate."""
        store = self.store()
        rtt = max(0.0, t1 - t0)
        offset_us, _rtt_us = stitch.clock_offset_us(
            doc, t0 * 1e6, t1 * 1e6)
        offset_s = offset_us / 1e6
        entries = doc.get("entries") or []
        journal_events: List[Dict[str, Any]] = []
        applied = 0
        with self._lock:
            self._failures_row = 0
            self._st["last_ok"] = t0 + rtt / 2.0
            self._st["rtt_s"] = rtt
            self._st["last_error"] = None
            self._offset_s = offset_s
            self._lost += max(0, int(doc.get("lost") or 0))
            cursor = self.cursor
            for e in entries:
                c = int(e.get("cursor") or 0)
                if c <= cursor:
                    self._duplicates += 1
                    continue
                cursor = c
                applied += 1
                kind = e.get("kind")
                if kind == "counter":
                    name = str(e.get("name"))
                    try:
                        self._counters[name] = float(e.get("total"))
                    except (TypeError, ValueError):
                        pass
                elif kind == "journal":
                    ev = e.get("event")
                    if isinstance(ev, dict):
                        journal_events.append(ev)
            self.cursor = cursor
            self._applied += applied
            counters = dict(self._counters)
            row = self._row
            requests = counters.get("requests_total", 0.0)
            failures = counters.get("failures_total", 0.0)
            row["requests_total"] = requests
            row["failures_total"] = failures
            row["error_rate"] = failures / requests if requests > 0 else 0.0
            anchor = self._st["last_ok"]
        # series writes off-lock (store has its own lock; LK004)
        staleness = max(0.0, now - anchor)
        store.record(f"worker:{self.label}/staleness_s", staleness, t=now)
        store.record(f"worker:{self.label}/poll_rtt_s", rtt, t=now)
        sample_rows: Dict[str, float] = {}
        for e in entries:
            if e.get("kind") != "sample":
                continue
            try:
                t_remote, v = float(e.get("t")), float(e.get("v"))
            except (TypeError, ValueError):
                continue
            name = str(e.get("name"))
            # place the remote sample on the master clock, never in the
            # master's future (an offset estimate can overshoot)
            t_local = min(now, t_remote + offset_s)
            store.record(f"worker:{self.label}/{name}", v, t=t_local)
            sample_rows[name] = v
        with self._lock:
            for name, v in sample_rows.items():
                self._row[name] = v
            self._row.setdefault("queue_wait_p95_s", 0.0)
            row = dict(self._row)
        # prober parity: every row key lands each cycle (a consumer of
        # the series never sees a key-by-key patchwork); samples from
        # this batch already sit on their corrected remote timestamps
        for key, value in row.items():
            if key in sample_rows:
                continue
            store.record(f"worker:{self.label}/{key}", value, t=now)
        if journal_events:
            try:
                from . import fleetlog

                fleetlog.ingest(self.label, journal_events,
                                offset_s=offset_s)
            except Exception:  # noqa: BLE001 — timeline stays passive
                pass
        self._after_cycle(now)
        return applied

    def _poll_fallback(self, now: float) -> int:
        """The demoted path: one federation-prober-style scrape of this
        worker, recorded into the same series."""
        store = self.store()
        try:
            metrics_text, doc, t0, t1 = federation.fetch_documents(
                self.backend, clock=self._clock)
        except Exception as e:  # noqa: BLE001 — per-node fault isolation
            self._note_failure(now, f"{type(e).__name__}: {e}")
            return 0
        rtt = max(0.0, t1 - t0)
        row = federation.FederationProber._digest(metrics_text, doc)
        row["poll_rtt_s"] = rtt
        with self._lock:
            self._failures_row = 0
            self._st["last_ok"] = t0 + rtt / 2.0
            self._st["rtt_s"] = rtt
            self._st["last_error"] = None
            self._row = dict(row)
            anchor = self._st["last_ok"]
        staleness = max(0.0, now - anchor)
        store.record(f"worker:{self.label}/staleness_s", staleness, t=now)
        landed = 1
        for key, value in row.items():
            store.record(f"worker:{self.label}/{key}", value, t=now)
            landed += 1
        self._after_cycle(now)
        return landed

    def _after_cycle(self, now: float) -> None:
        if self._manager is not None:
            try:
                self._manager.record_fleet(now)
            except Exception:  # noqa: BLE001 — aggregation stays passive
                pass

    # -- views -------------------------------------------------------------

    def staleness_s(self, now: Optional[float] = None) -> float:
        if now is None:
            now = self._clock()
        with self._lock:
            anchor = self._st["last_ok"] if self._st["last_ok"] is not None \
                else (self._st["first_seen"]
                      if self._st["first_seen"] is not None else now)
        return max(0.0, now - anchor)

    def status(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            st = dict(self._st)
            out = {
                "mode": self.mode,
                "cursor": self.cursor,
                "applied": self._applied,
                "duplicates": self._duplicates,
                "lost": self._lost,
                "fallbacks": self._fallbacks,
                "polls": st["polls"],
                "failures": st["failures"],
                "rtt_s": st["rtt_s"],
                "last_error": st["last_error"],
                "offset_s": self._offset_s,
            }
        out["staleness_s"] = self.staleness_s(now)
        out["stale"] = out["staleness_s"] >= federation.stale_after_s()
        out["daemon"] = self.alive()
        return out


class PushManager:
    """The fleet of subscribers + the ``fleet/...`` aggregate writer.

    One subscriber per pollable worker of the registered source (same
    contract as the federation prober: a World or iterable).
    :meth:`tick` is the deterministic test/bench entry point;
    :meth:`start`/:meth:`stop` run every subscriber's daemon.
    """

    def __init__(self, store=None, clock=time.monotonic) -> None:
        self._store = store
        self._clock = clock
        self._lock = threading.Lock()
        self._source: Any = None                       # guarded-by: _lock
        self._subs: Dict[str, DeltaSubscriber] = {}    # guarded-by: _lock
        self._ticks = 0                                # guarded-by: _lock
        self._started = False                          # guarded-by: _lock

    def store(self):
        if self._store is not None:
            return self._store
        from . import tsdb as obs_tsdb

        return obs_tsdb.STORE

    def set_source(self, source: Any) -> None:
        with self._lock:
            self._source = source

    def source(self) -> Any:
        with self._lock:
            return self._source

    def _sync_subscribers(self) -> List[DeltaSubscriber]:
        """Create/retire subscribers to mirror the source's pollable
        workers; returns the live list. New subscribers start their
        daemon iff the manager is in started state."""
        source = self.source()
        workers = [w for w in stitch._workers_of(source or [])
                   if _subscribable(w)]
        live: List[DeltaSubscriber] = []
        to_start: List[DeltaSubscriber] = []
        to_stop: List[DeltaSubscriber] = []
        with self._lock:
            seen = set()
            for w in workers:
                label = str(getattr(w, "label", "?"))
                seen.add(label)
                sub = self._subs.get(label)
                if sub is None or sub.backend is not getattr(
                        w, "backend", None):
                    if sub is not None:
                        to_stop.append(sub)
                    sub = DeltaSubscriber(label, w.backend,
                                          store=self._store,
                                          clock=self._clock, manager=self)
                    self._subs[label] = sub
                    if self._started:
                        to_start.append(sub)
                live.append(sub)
            for label in list(self._subs):
                if label not in seen:
                    to_stop.append(self._subs.pop(label))
        for sub in to_stop:
            sub.stop()
        for sub in to_start:
            sub.start()
        return live

    def tick(self, now: Optional[float] = None) -> int:
        """One synchronous cycle over every subscriber; returns how
        many entries/samples applied. No-op (0) with the gate off."""
        if not enabled():
            return 0
        if now is None:
            now = self._clock()
        applied = 0
        for sub in self._sync_subscribers():
            applied += sub.poll_once(now)
        with self._lock:
            self._ticks += 1
        self.record_fleet(now)
        return applied

    def record_fleet(self, now: Optional[float] = None) -> None:
        """The ``fleet/...`` aggregates, from the subscribers' latest
        state — the poll prober's exact shape, so the fleet-scope alert
        rules and the autoscaler signal are plane-agnostic."""
        if now is None:
            now = self._clock()
        with self._lock:
            subs = list(self._subs.values())
        if not subs:
            return
        store = self.store()
        deadline = federation.stale_after_s()
        stale_count = 0
        error_rates: List[float] = []
        p95s: List[float] = []
        failures = 0
        for sub in subs:
            if sub.staleness_s(now) >= deadline:
                stale_count += 1
            with sub._lock:
                row = dict(sub._row)
                failures += sub._st["failures"]
                had_ok = sub._st["last_ok"] is not None
            if not had_ok:
                # never reached: its share of the fleet error rate is 1.0
                error_rates.append(1.0)
                continue
            error_rates.append(row.get("error_rate", 0.0))
            p95s.append(row.get("queue_wait_p95_s", 0.0))
        local_p95 = 0.0
        try:
            from . import prometheus as obs_prom

            local_p95 = obs_prom.fleet_queue_wait_p95()
        except Exception:  # noqa: BLE001 — aggregation stays passive
            pass
        for name, value in (
                ("fleet/queue_wait_p95_s", max([local_p95] + p95s)),
                ("fleet/error_rate",
                 sum(error_rates) / len(error_rates) if error_rates
                 else 0.0),
                ("fleet/worker_stale_count", float(stale_count)),
                ("fleet/poll_failures_total", float(failures))):
            store.record(name, value, t=now)

    def start(self) -> bool:
        """Start every subscriber's daemon (idempotent); False with the
        gate off."""
        if not enabled():
            return False
        with self._lock:
            self._started = True
        for sub in self._sync_subscribers():
            sub.start()
        return True

    def stop(self) -> None:
        with self._lock:
            self._started = False
            subs = list(self._subs.values())
        for sub in subs:
            sub.stop()

    def summary(self) -> Dict[str, Any]:
        """The ``GET /internal/push`` document."""
        with self._lock:
            subs = dict(self._subs)
            ticks = self._ticks
        workers = {label: sub.status() for label, sub in subs.items()}
        return {
            "enabled": enabled(),
            "cursor_buf": cursor_buf(),
            "wait_s": wait_s(),
            "ticks": ticks,
            "buffer": BUFFER.stats(),
            "event_loss": sum(w["lost"] for w in workers.values()),
            "duplicates": sum(w["duplicates"] for w in workers.values()),
            "workers": workers,
        }


#: Process-wide manager. A World registers itself as the source at
#: construction when the gate is on (scheduler/world.py); tests and
#: bench call :func:`set_source` / :func:`tick` directly.
PUSH = PushManager()


def set_source(source: Any) -> None:
    """Register the subscriber fleet's worker source."""
    PUSH.set_source(source)


def source() -> Any:
    return PUSH.source()


def tick(now: Optional[float] = None) -> int:
    """One gated subscriber sweep; 0 with SDTPU_PUSH off."""
    return PUSH.tick(now=now)


def start_daemons() -> bool:
    """Start the per-worker subscriber daemons; False with the gate
    off."""
    return PUSH.start()


def stop_daemons() -> None:
    PUSH.stop()


def reset() -> None:
    """Stop every daemon and rebuild the manager + the worker-side
    buffer (tests/bench between phases); source registration does not
    survive — a World re-registers at construction."""
    global PUSH
    PUSH.stop()
    PUSH = PushManager()
    BUFFER.clear()


def summary() -> Dict[str, Any]:
    """The ``GET /internal/push`` document (served even when off)."""
    return PUSH.summary()

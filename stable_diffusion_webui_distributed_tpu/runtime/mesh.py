"""Device mesh construction: the TPU substrate for the batch split.

Where the reference's "world" is a pool of HTTP hosts (one GPU each,
/root/reference/scripts/spartan/world.py:75-145), this framework's first
tier of parallelism is a ``jax.sharding.Mesh`` over local chips: the batch
axis is sharded over ``dp`` (XLA emits ICI collectives; no request fan-out,
no HTTP). The World scheduler (scheduler/) then balances *across* meshes —
slices/hosts — the way the reference balances across HTTP workers.

Axis names: ``dp`` (batch data-parallel), ``tp`` (tensor parallel within the
UNet/VAE), reserved ``sp`` (latent-token sequence parallel for very high
resolutions). ``--mesh "dp=4,tp=2"`` flag parsing lives here (the flag is
registered at runtime/flags.py:33-38).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

AXIS_ORDER = ("dp", "tp", "sp")


def enable_compilation_cache(
        cache_dir: Optional[str] = None) -> Optional[str]:
    """Persist XLA executables across process restarts (first SDXL compile
    costs ~minutes on TPU; a restarted node re-serves in seconds). The
    reference's workers pay webui's model-load on every restart with no
    equivalent escape hatch.

    Returns the active cache directory (None when enabling failed) so the
    serving warmup (serving/warmup.py) can report where its pre-built
    executables landed — warmup + this cache is what turns a restarted
    server's first request from compile cost into dispatch cost."""
    import os

    import jax

    from stable_diffusion_webui_distributed_tpu.runtime.config import env_str

    cache_dir = cache_dir or env_str(
        "SDTPU_XLA_CACHE", os.path.expanduser("~/.cache/sdtpu-xla"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception:  # noqa: BLE001 — cache is an optimization only
        return None


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> bool:
    """Join a multi-host JAX runtime over DCN (``jax.distributed``).

    Within a host, parallelism is the mesh's problem (ICI collectives);
    across hosts this makes every chip of every host visible to one global
    mesh — the DCN tier the reference approximates with its HTTP worker
    pool (SURVEY.md §2 distributed backend). No-ops (returning False) when
    no coordinator is configured, so single-host flows never pay it.
    Environment fallbacks: SDTPU_COORDINATOR, SDTPU_NUM_PROCESSES,
    SDTPU_PROCESS_ID (or the cloud auto-detection jax.distributed ships).
    """
    import jax

    from stable_diffusion_webui_distributed_tpu.runtime.config import (
        env_int, env_str,
    )

    coordinator = coordinator or env_str("SDTPU_COORDINATOR") or None
    if not coordinator:
        return False
    kwargs = {"coordinator_address": coordinator}
    num_processes = num_processes if num_processes is not None else \
        env_int("SDTPU_NUM_PROCESSES")
    process_id = process_id if process_id is not None else \
        env_int("SDTPU_PROCESS_ID")
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    jax.distributed.initialize(**kwargs)
    return True


def parse_mesh_spec(spec: Optional[str]) -> Dict[str, int]:
    """'dp=4,tp=2' -> {'dp': 4, 'tp': 2}. Empty/None -> {} (all devices on dp)."""
    if not spec:
        return {}
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad mesh axis '{part}' (want name=size)")
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in AXIS_ORDER:
            raise ValueError(f"unknown mesh axis '{name}' (known: {AXIS_ORDER})")
        out[name] = int(size)
        if out[name] <= 0:
            raise ValueError(f"mesh axis {name} must be positive")
    return out


def build_mesh(spec: Optional[str] = None, devices: Optional[Sequence] = None):
    """Construct a Mesh from a spec string over the given (or all) devices.

    Unspecified axes get size 1; if no axes are given, every device lands on
    ``dp`` — the TPU analogue of the reference's default equal batch split
    (world.py:111-115).
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    axes = parse_mesh_spec(spec)
    if not axes:
        axes = {"dp": len(devices)}
    sizes = [axes.get(a, 1) for a in AXIS_ORDER]
    total = int(np.prod(sizes))
    if total != len(devices):
        # Allow a spec that uses a subset (e.g. dp=4 of 8 devices).
        if total < len(devices) and len(devices) % total == 0:
            devices = devices[:total]
        else:
            raise ValueError(
                f"mesh spec {axes} needs {total} devices, have {len(devices)}"
            )
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, AXIS_ORDER)


def batch_sharding(mesh):
    """NamedSharding that splits axis 0 (the image batch) over ``dp``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("dp"))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def pad_batch(n: int, mesh) -> int:
    """Images to generate so the batch divides the dp axis: pad-and-drop,
    the TPU replacement for the reference's remainder round-robin
    (world.py:482-510)."""
    dp = mesh.shape["dp"]
    return ((n + dp - 1) // dp) * dp

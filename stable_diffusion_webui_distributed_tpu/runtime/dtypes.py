"""Dtype policy: bf16 compute, f32 accumulate where it matters.

TPU MXU natively multiplies bf16 with f32 accumulation; we keep params and
activations in bf16 and pin numerically sensitive pieces (sampler state,
sigmas, group-norm statistics, final VAE output) to f32.
"""

from __future__ import annotations

import dataclasses
import functools

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.dtype(jnp.float32)   # storage dtype of weights
    compute_dtype: jnp.dtype = jnp.dtype(jnp.bfloat16)  # matmul/conv dtype
    sampler_dtype: jnp.dtype = jnp.dtype(jnp.float32)   # latent/sigma math
    # "xla" | "flash" (Pallas online-softmax kernel for latent self-attn).
    # SDTPU_ATTENTION=flash flips the default TPU policy.
    attention_impl: str = "xla"
    # rematerialize transformer blocks: trades UNet FLOPs for HBM at large
    # batch/resolution (SDTPU_REMAT=1 flips the default TPU policy).
    use_remat: bool = False
    # Decoder conv dtype override (SDTPU_DECODE_DTYPE=bf16): runs the VAE
    # decoder's convs in bf16 while GroupNorm statistics and the final
    # conv_out stay f32 (models/vae.py). Halves decode HBM scratch — the
    # round-3 b8 1024² OOM was 16 GB of f32 conv temps — and halves
    # decode bytes fetched per dispatch under the pixel budget. Off by
    # default: banding risk is unvalidated without real weights
    # (README "numerical-parity status"); measure via sweep cell
    # c2-decodebf16 before promoting.
    decode_in_bf16: bool = False
    # Dynamic W8A8 int8 for the UNet transformer linears
    # (SDTPU_UNET_INT8=1; ops/quant.py). The int8 MXU path is the only
    # single-chip lever above the bf16 roofline (PERF.md round-5
    # analysis: 0.96 vs 0.48 img/s/chip ceiling on SDXL b8). Since the
    # serving-precision ladder (pipeline/precision.py) this flag sets
    # only the server's DEFAULT precision — a per-request ``precision``
    # override ("bf16"/"int8"/"int8+conv") always wins, and the engine
    # keeps one module variant per rung over the SAME param tree.
    # Quality is gated by the tier-1 floors (tests/test_quality_int8.py);
    # throughput by bench.py --int8 / sweep cells c2-int8/c4-int8.
    unet_int8: bool = False
    # ...and the same lever for the ResBlock/Down/Up convs
    # (SDTPU_UNET_INT8_CONV=1, the "int8+conv" rung) — configs #1/#3 are
    # conv-dominated, so int8 linears alone barely move them.
    unet_int8_conv: bool = False


def _env_choice(name: str, default: str, choices) -> str:
    from stable_diffusion_webui_distributed_tpu.runtime.config import env_parsed

    def parse(raw: str) -> str:
        value = raw.strip().lower()
        if value not in choices:
            raise ValueError(f"want one of {tuple(choices)}")
        return value

    return env_parsed(name, parse, default, "choice")


def _default_attention() -> str:
    return _env_choice("SDTPU_ATTENTION", "xla", ("xla", "flash"))


def _env_flag(name: str) -> bool:
    from stable_diffusion_webui_distributed_tpu.runtime.config import env_flag

    return env_flag(name, False)


def _default_param_dtype() -> jnp.dtype:
    """Weight storage dtype on TPU (SDTPU_PARAM_DTYPE=bf16|f32).

    bf16 storage halves HBM weight traffic per UNet call — the dominant
    byte stream at inference batch sizes — and halves resident model
    memory (SDXL base+refiner fit on one 16 GB v5e). Numerics stay f32
    where it matters: sigma/sampler math is pinned f32 by
    ``sampler_dtype`` and flax group norms compute statistics in f32.

    Default is bf16: measured on silicon (round-3 sweep, PERF.md) it
    wins config #1 27.2 ipm vs 22.4 ipm for f32 storage (+21%).
    """
    value = _env_choice("SDTPU_PARAM_DTYPE", "bf16",
                        ("bf16", "bfloat16", "f32", "float32", "fp32"))
    if value in ("bf16", "bfloat16"):
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(jnp.float32)


def _default_decode_bf16() -> bool:
    value = _env_choice("SDTPU_DECODE_DTYPE", "f32",
                        ("bf16", "bfloat16", "f32", "float32", "fp32"))
    return value in ("bf16", "bfloat16")


#: Default policy for real TPU runs.
TPU = Policy(param_dtype=_default_param_dtype(),
             attention_impl=_default_attention(),
             use_remat=_env_flag("SDTPU_REMAT"),
             decode_in_bf16=_default_decode_bf16(),
             unet_int8=_env_flag("SDTPU_UNET_INT8"),
             unet_int8_conv=_env_flag("SDTPU_UNET_INT8_CONV"))
#: Full-f32 policy for numerics tests on CPU.
F32 = Policy(compute_dtype=jnp.dtype(jnp.float32))


def _needs_cast(x, dtype):
    return (hasattr(x, "dtype")
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.dtype != dtype)


@functools.lru_cache(maxsize=None)
def _tree_cast(dtype):
    import jax

    return jax.jit(lambda t: jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _needs_cast(x, dtype) else x, t))


def cast_floating(tree, dtype):
    """Cast floating leaves of a pytree to ``dtype`` (params → bf16 etc.).

    Host (numpy) trees — freshly converted checkpoints — are cast leaf by
    leaf ON HOST: no XLA compile, and the device never holds the f32
    source alongside the downcast copy (for SDXL that transient would be
    ~15 GB, an OOM at load on a 16 GB v5e chip).

    Device trees are cast inside a single ``jit`` call: per-leaf
    ``astype`` would compile one tiny convert executable per unique leaf
    shape (hundreds for a UNet), which is minutes of compile time on a
    TPU backend; one jitted tree-cast is one compile, cached per target
    dtype so repeated casts of same-structure trees (e.g. VAE toggles)
    reuse the executable. Leaves already in ``dtype`` pass through
    untouched, so a no-op cast stays free.
    """
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    if not any(_needs_cast(x, dtype) for x in leaves):
        return tree
    if not any(isinstance(x, jax.Array) for x in leaves):
        import numpy as np

        return jax.tree_util.tree_map(
            lambda x: np.asarray(x).astype(dtype)
            if _needs_cast(x, dtype) else x, tree)
    return _tree_cast(jnp.dtype(dtype))(tree)

"""Dtype policy: bf16 compute, f32 accumulate where it matters.

TPU MXU natively multiplies bf16 with f32 accumulation; we keep params and
activations in bf16 and pin numerically sensitive pieces (sampler state,
sigmas, group-norm statistics, final VAE output) to f32.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.dtype(jnp.float32)   # storage dtype of weights
    compute_dtype: jnp.dtype = jnp.dtype(jnp.bfloat16)  # matmul/conv dtype
    sampler_dtype: jnp.dtype = jnp.dtype(jnp.float32)   # latent/sigma math
    # "xla" | "flash" (Pallas online-softmax kernel for latent self-attn).
    # SDTPU_ATTENTION=flash flips the default TPU policy.
    attention_impl: str = "xla"
    # rematerialize transformer blocks: trades UNet FLOPs for HBM at large
    # batch/resolution (SDTPU_REMAT=1 flips the default TPU policy).
    use_remat: bool = False


def _default_attention() -> str:
    import os

    value = os.environ.get("SDTPU_ATTENTION", "xla").strip().lower()
    if value not in ("xla", "flash"):
        import warnings

        warnings.warn(
            f"SDTPU_ATTENTION={value!r} is not one of ('xla', 'flash'); "
            "using 'xla'", stacklevel=2)
        return "xla"
    return value


def _env_flag(name: str) -> bool:
    import os

    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


#: Default policy for real TPU runs.
TPU = Policy(attention_impl=_default_attention(),
             use_remat=_env_flag("SDTPU_REMAT"))
#: Full-f32 policy for numerics tests on CPU.
F32 = Policy(compute_dtype=jnp.dtype(jnp.float32))


def cast_floating(tree, dtype):
    """Cast floating leaves of a pytree to ``dtype`` (params → bf16 etc.)."""
    import jax

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)

"""Tracing / profiling: jax.profiler capture + per-stage wall-clock stats.

The reference has no tracer — its only timing is ad-hoc wall clock feeding
the benchmark/ETA loop (SURVEY.md §5: response_time at worker.py:477-481 is
the de-facto profiler). Here that idea is kept (stage timings feed the
status surface) and real tracing is added: ``capture()`` wraps
``jax.profiler`` so a TensorBoard-loadable trace of the XLA execution can be
taken around any request.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict, deque
from typing import Deque, Dict, Iterator, Optional


class StageStats:
    """Thread-safe rolling wall-clock stats per pipeline stage."""

    def __init__(self, window: int = 64):
        self._window = window
        self._samples: Dict[str, Deque[float]] = defaultdict(
            lambda: deque(maxlen=window))
        self._lock = threading.Lock()

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._samples[stage].append(seconds)

    @contextlib.contextmanager
    def timer(self, stage: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self.record(stage, dur)
            _obs_stage(stage, dur, t0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """{stage: {count, mean, p50, last}} over the rolling window."""
        with self._lock:
            out = {}
            for stage, samples in self._samples.items():
                if not samples:
                    continue
                ordered = sorted(samples)
                out[stage] = {
                    "count": len(samples),
                    "mean": sum(samples) / len(samples),
                    "p50": ordered[len(ordered) // 2],
                    "last": samples[-1],
                }
            return out

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()


def _obs_stage(stage: str, seconds: float, t0: float) -> None:
    """Mirror one timed stage into the obs layer: a leaf span on the active
    request trace plus the matching latency histogram. Lazy import (trace
    is imported everywhere; obs pulls serving/metrics) and exception-proof:
    observability must never take a generation down."""
    try:
        from stable_diffusion_webui_distributed_tpu.obs import (
            spans as obs_spans,
        )

        obs_spans.stage_event(stage, seconds, t0)
    except Exception:  # noqa: BLE001 — pragma: no cover
        pass


#: Process-wide stats the engine and server share.
STATS = StageStats()


_trace_lock = threading.Lock()
_trace_dir: Optional[str] = None


def start_trace(log_dir: str) -> bool:
    """Begin a jax.profiler capture (TensorBoard format). Returns False if a
    capture is already running."""
    global _trace_dir
    import jax

    with _trace_lock:
        if _trace_dir is not None:
            return False
        jax.profiler.start_trace(log_dir)
        _trace_dir = log_dir
        return True


def stop_trace() -> Optional[str]:
    """End the running capture; returns its directory (None if none ran)."""
    global _trace_dir
    import jax

    with _trace_lock:
        if _trace_dir is None:
            return None
        jax.profiler.stop_trace()
        out, _trace_dir = _trace_dir, None
        return out


@contextlib.contextmanager
def capture(log_dir: str) -> Iterator[None]:
    """Trace the wrapped block. If another capture is already running, this
    becomes a no-op rather than hijacking (and stopping) it."""
    started = start_trace(log_dir)
    try:
        yield
    finally:
        if started:
            stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region visible in the profiler timeline (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield

"""Observability stack: branded console logging, rotating file log, GUI ring buffer.

Capability parity with the reference's observability layer
(/root/reference/scripts/spartan/shared.py:16-60): a single ``"distributed"``
logger fans out to (1) a Rich console handler with a branded prefix, (2) a
10 MB x 2 rotating file, and (3) an in-memory ring buffer that UIs poll for
live status. The ring buffer here is thread-safe (the reference's plain list
is mutated cross-thread without locks; we fix that).
"""

from __future__ import annotations

import collections
import logging
import logging.handlers
import os
import threading
from typing import Deque, List

LOGGER_NAME = "distributed"
#: Number of messages the GUI ring buffer retains (reference: shared.py:44 keeps 16).
RING_CAPACITY = 16
#: Per-request correlation index bounds (obs flight recorder): how many
#: recent request ids keep log lines, and how many lines each keeps.
REQUEST_INDEX_CAPACITY = 64
REQUEST_LINE_CAPACITY = 64

_lock = threading.Lock()
_configured = False


class RequestLogIndex:
    """Log lines grouped by obs request id.

    The flight recorder (obs/flightrec.py) attaches a dead request's own
    log lines to its span tree; this index is how those lines are found
    after the fact. Bounded two ways: the most recent
    ``REQUEST_INDEX_CAPACITY`` request ids, ``REQUEST_LINE_CAPACITY``
    lines each.
    """

    def __init__(self, max_requests: int = REQUEST_INDEX_CAPACITY,
                 max_lines: int = REQUEST_LINE_CAPACITY):
        self._max_requests = max_requests
        self._max_lines = max_lines
        self._lock = threading.Lock()
        self._lines: "collections.OrderedDict[str, Deque[str]]" = \
            collections.OrderedDict()  # guarded-by: _lock

    def note(self, request_id: str, line: str) -> None:
        with self._lock:
            buf = self._lines.get(request_id)
            if buf is None:
                buf = collections.deque(maxlen=self._max_lines)
                self._lines[request_id] = buf
                while len(self._lines) > self._max_requests:
                    self._lines.popitem(last=False)
            else:
                self._lines.move_to_end(request_id)
            buf.append(line)

    def lines(self, request_id: str) -> List[str]:
        with self._lock:
            return list(self._lines.get(request_id, ()))

    def clear(self) -> None:
        with self._lock:
            self._lines.clear()


_request_index = RequestLogIndex()


def lines_for_request(request_id: str) -> List[str]:
    """Log lines emitted while ``request_id``'s obs context was active.

    The context is a contextvar, so it does NOT cross a bare
    ``threading.Thread`` — any fan-out thread that should log under the
    request (the scheduler's job threads, ping sweeps) must be spawned
    through ``obs.spans.bind_current`` or its lines land here under ''.
    """
    return _request_index.lines(str(request_id))


class RequestIdFilter(logging.Filter):
    """Stamps every record with the active obs request id and mirrors the
    line into the per-request correlation index.

    Installed as a logger-level filter so every handler (console, file,
    ring) sees ``record.request_id``; '' outside any request context or
    when the obs layer is unavailable.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        rid = ""
        try:
            from stable_diffusion_webui_distributed_tpu.obs import spans

            rid = spans.current_request_id() or ""
        except Exception:  # noqa: BLE001 — logging must never fail
            rid = ""
        record.request_id = rid
        if rid:
            import time as _time

            stamp = _time.strftime("%H:%M:%S",
                                   _time.localtime(record.created))
            try:
                msg = record.getMessage()
            except Exception:  # noqa: BLE001
                msg = str(record.msg)
            _request_index.note(rid, f"{stamp} {record.levelname} {msg}")
        return True


_request_filter = RequestIdFilter()


class RingBufferHandler(logging.Handler):
    """In-memory ring buffer of formatted log lines for status UIs.

    Mirrors the reference's ``GuiHandler`` (shared.py:43-59) which keeps the
    last 16 messages for the Status tab textbox.
    """

    def __init__(self, capacity: int = RING_CAPACITY):
        super().__init__()
        self._buf: Deque[str] = collections.deque(maxlen=capacity)
        self._buf_lock = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = self.format(record)
        except Exception:  # pragma: no cover - formatting failure
            self.handleError(record)
            return
        with self._buf_lock:
            self._buf.append(msg)

    def dump(self) -> List[str]:
        """Return the buffered lines, oldest first."""
        with self._buf_lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._buf_lock:
            self._buf.clear()


_ring_handler = RingBufferHandler()


def get_ring_buffer() -> RingBufferHandler:
    """The process-wide ring buffer handler (for status endpoints/UIs)."""
    return _ring_handler


def configure(
    debug: bool = False,
    log_dir: str | None = None,
    use_rich: bool = True,
) -> logging.Logger:
    """Configure the 'distributed' logger. Idempotent.

    Parameters mirror the reference's ``--distributed-debug`` flag
    (shared.py:16) and its ``distributed.log`` rotating file (shared.py:33-36).
    """
    global _configured
    logger = logging.getLogger(LOGGER_NAME)
    with _lock:
        if _configured:
            logger.setLevel(logging.DEBUG if debug else logging.INFO)
            return logger

        logger.setLevel(logging.DEBUG if debug else logging.INFO)
        logger.propagate = False
        # request-id stamping + per-request correlation for the obs layer
        logger.addFilter(_request_filter)

        fmt = logging.Formatter("%(asctime)s %(levelname)s %(message)s", "%H:%M:%S")

        console: logging.Handler
        if use_rich:
            try:
                from rich.logging import RichHandler

                class BrandedRichHandler(RichHandler):
                    """Rich console handler with a branded prefix (shared.py:19-30).

                    The prefix is applied to a *copy* of the record so it
                    cannot leak into the file log or ring buffer, which share
                    the same logger (ADVICE r1).
                    """

                    def emit(self, record: logging.LogRecord) -> None:
                        import copy

                        branded = copy.copy(record)
                        branded.msg = f"[sdtpu] {record.msg}"
                        super().emit(branded)

                from rich.console import Console

                # stderr, NOT stdout: machine-parseable output (bench.py's
                # JSON line, CLI file listings) owns stdout
                console = BrandedRichHandler(
                    console=Console(stderr=True),
                    show_path=False, show_time=True)
            except Exception:  # pragma: no cover - rich unavailable
                console = logging.StreamHandler()
                console.setFormatter(fmt)
        else:
            console = logging.StreamHandler()
            console.setFormatter(fmt)
        logger.addHandler(console)

        if log_dir is None:
            from stable_diffusion_webui_distributed_tpu.runtime.config \
                import env_str

            log_dir = env_str("SDTPU_LOG_DIR", ".")
        try:
            file_handler = logging.handlers.RotatingFileHandler(
                os.path.join(log_dir, "distributed.log"),
                maxBytes=10 * 1024 * 1024,
                backupCount=1,
            )
            file_handler.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
            )
            logger.addHandler(file_handler)
        except OSError:  # pragma: no cover - unwritable dir
            pass

        _ring_handler.setFormatter(fmt)
        logger.addHandler(_ring_handler)

        _configured = True
        return logger


def get_logger() -> logging.Logger:
    """Return the framework logger, configuring defaults on first use."""
    if not _configured:
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            env_flag,
        )

        configure(debug=env_flag("SDTPU_DEBUG"))
    return logging.getLogger(LOGGER_NAME)

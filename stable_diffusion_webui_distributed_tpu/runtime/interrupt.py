"""Interrupt + progress plumbing for compiled denoise loops.

The reference interrupts in-flight work by polling a master-side flag every
0.5 s while the HTTP call runs and POSTing ``/interrupt`` to remotes
(/root/reference/scripts/spartan/worker.py:440-448, world.py:173-179). Under
XLA the denoise loop is a compiled ``lax.scan`` — the host can't reach into
it. We reproduce the same user-visible semantics by *chunking*: the sampler
loop runs ``chunk`` steps per device dispatch, and between dispatches the
host checks :class:`InterruptFlag` and reports progress. With step counts of
20-50 and chunks of 4-5 steps the check granularity on TPU is well under the
reference's 0.5 s poll.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional


class InterruptFlag:
    """Thread-safe interrupt latch shared by API server, UI, and executors."""

    def __init__(self) -> None:
        self._event = threading.Event()

    def interrupt(self) -> None:
        self._event.set()

    def clear(self) -> None:
        self._event.clear()

    @property
    def interrupted(self) -> bool:
        return self._event.is_set()


@dataclass
class Progress:
    """Live progress for the ``/sdapi/v1/progress`` endpoint (reference consumes
    webui's progress API; worker.py:192-203 lists the surface)."""

    job: str = ""
    sampling_step: int = 0
    sampling_steps: int = 0
    started_at: float = 0.0
    interrupted: bool = False

    @property
    def fraction(self) -> float:
        if self.sampling_steps <= 0:
            return 0.0
        return min(1.0, self.sampling_step / self.sampling_steps)

    def eta_seconds(self) -> Optional[float]:
        if self.sampling_step <= 0 or self.started_at <= 0:
            return None
        elapsed = time.time() - self.started_at
        rate = elapsed / self.sampling_step
        return rate * (self.sampling_steps - self.sampling_step)


class GenerationState:
    """Process-wide generation state: one interrupt flag + progress record.

    Equivalent role to webui's ``shared.state`` that the reference reads
    (worker.py:444-448) — the single rendezvous between UIs/API handlers and
    the executor.
    """

    def __init__(self) -> None:
        self.flag = InterruptFlag()
        self.progress = Progress()  # guarded-by: _lock
        self._listeners: List[Callable[[Progress], None]] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def begin(self, job: str, steps: int) -> None:
        """Start a phase's progress record. Does NOT clear the interrupt
        flag — a request may span several phases (base, refiner, hires) and
        an interrupt must survive phase boundaries; clear it at request
        scope with :meth:`begin_request`."""
        with self._lock:
            self.progress = Progress(
                job=job, sampling_steps=steps, started_at=time.time()
            )

    def begin_request(self) -> None:
        """New top-level request: reset the interrupt latch (webui clears
        ``state.interrupted`` the same way when a generation starts)."""
        self.flag.clear()

    def restore_interrupt(self, interrupted: bool) -> None:
        """Preemption resume (the engine's chunk-boundary yield path):
        reinstate the yielding request's saved view of the latch.  The
        latch is process-global and targets the visibly running job, so an
        interrupt raised while an interloper held the device belongs to
        the interloper and must not truncate the resumed request; one that
        landed just before the yield must survive the interloper's
        :meth:`begin_request`.  (An interrupt raised in the window where
        nobody is between ``begin_request`` and ``finish`` stays a no-op,
        same as the non-fleet idle case.)"""
        if interrupted:
            self.flag.interrupt()
        else:
            self.flag.clear()

    def step(self, completed_steps: int) -> None:
        # Snapshot under the lock, invoke listeners outside it: a listener
        # that logs or calls back into this state must not deadlock
        # (ring-buffer pattern; VERDICT r1 weak #6).
        with self._lock:
            self.progress.sampling_step = completed_steps
            self.progress.interrupted = self.flag.interrupted
            listeners = list(self._listeners)
            snapshot = dataclasses.replace(self.progress)
        for cb in listeners:
            cb(snapshot)

    def finish(self) -> None:
        with self._lock:
            self.progress.interrupted = self.flag.interrupted
            if not self.progress.interrupted:
                # only a completed run reports full step count; an
                # interrupted one keeps the step it actually reached
                self.progress.sampling_step = self.progress.sampling_steps
            listeners = list(self._listeners)
            snapshot = dataclasses.replace(self.progress)
        # terminal state must reach listeners too (same outside-lock rule)
        for cb in listeners:
            cb(snapshot)

    def add_listener(self, cb: Callable[[Progress], None]) -> None:
        with self._lock:
            self._listeners.append(cb)

    def progress_snapshot(self) -> Progress:
        """Locked copy for cross-thread readers (the HTTP progress
        endpoints): ``begin`` replaces the Progress object and ``step``
        mutates it on the executor thread, so a bare ``state.progress``
        read can see a torn update."""
        with self._lock:
            return dataclasses.replace(self.progress)


#: Default process-wide state (servers may create their own).
STATE = GenerationState()

"""Core runtime: config, flags, logging, RNG discipline, dtype policy, interrupt."""

"""Runtime lockset sanitizer (``SDTPU_LOCKSAN``, default off).

The static lock analysis (analysis/locks.py) computes an acquisition-order
digraph over ``Class.attr`` lock names. This module is the other half of
the contract: when ``SDTPU_LOCKSAN=1``, the ``threading.Lock`` /
``threading.RLock`` factories are replaced with wrappers that

- **name** each lock at creation by inspecting the creating frame: a lock
  born from ``self._lock = threading.Lock()`` inside ``WorkerNode.__init__``
  is named ``WorkerNode._lock`` — the same qualified name the static graph
  uses, so the two graphs diff cleanly;
- **record** every nested acquisition as an ordered edge (held → acquired)
  in a process-global edge set, per-thread via a thread-local held stack;
- implement the ``Condition`` protocol (``_release_save`` /
  ``_acquire_restore`` / ``_is_owned``) so ``cond.wait()`` correctly pops
  and re-pushes the held stack.

At teardown (tests/conftest.py wires this under ``SDTPU_LOCKSAN=1``),
:func:`divergence` compares the observed edges against the static graph:
an observed edge between two statically-known lock names with no static
path in that direction means the static model missed a real ordering —
the run fails rather than letting the model rot. Anonymous locks (no
``self.<attr> =`` creation site, stdlib internals) never participate.

Default off: importing this module patches nothing; ``install()`` is the
only entry point with side effects, and ``uninstall()`` restores the real
factories. The wrapper adds two dict lookups and a list append per
acquire — fine for tests, not meant for production serving.
"""

from __future__ import annotations

import linecache
import re
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_ATTR_ASSIGN = re.compile(r"self\s*\.\s*(\w+)\s*(?::[^=]+)?=")

_real_lock = threading.Lock
_real_rlock = threading.RLock

_installed = False
_edges: Set[Tuple[str, str]] = set()
_edges_guard = _real_lock()
_tls = threading.local()


def _held_stack() -> List["_SanLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _name_from_frame(depth: int = 2) -> Optional[str]:
    """``Class.attr`` for a ``self.<attr> = threading.Lock()`` creation
    site, else None (anonymous)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    obj = frame.f_locals.get("self")
    if obj is None:
        return None
    line = linecache.getline(frame.f_code.co_filename, frame.f_lineno)
    m = _ATTR_ASSIGN.search(line)
    if m is None:
        return None
    return f"{type(obj).__name__}.{m.group(1)}"


class _SanLock:
    """Order-recording wrapper around a real Lock/RLock."""

    def __init__(self, raw, name: Optional[str]):
        self._raw = raw
        self._san_name = name

    # -- bookkeeping ---------------------------------------------------------

    def _push(self) -> None:
        stack = _held_stack()
        if self._san_name is not None:
            new_edges = [
                (h._san_name, self._san_name) for h in stack
                if h._san_name is not None and h._san_name != self._san_name]
            if new_edges:
                with _edges_guard:
                    _edges.update(new_edges)
        stack.append(self)

    def _pop(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    # -- lock protocol -------------------------------------------------------

    def acquire(self, *args, **kwargs):
        got = self._raw.acquire(*args, **kwargs)
        if got:
            self._push()
        return got

    def release(self):
        self._pop()
        return self._raw.release()

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- Condition protocol (cond.wait releases and reacquires) -------------

    def _release_save(self):
        self._pop()
        if hasattr(self._raw, "_release_save"):
            return self._raw._release_save()
        self._raw.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._raw, "_acquire_restore"):
            self._raw._acquire_restore(state)
        else:
            self._raw.acquire()
        self._push()

    def _is_owned(self):
        if hasattr(self._raw, "_is_owned"):
            return self._raw._is_owned()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def __repr__(self):
        return f"<SanLock {self._san_name or 'anon'} {self._raw!r}>"


def _lock_factory():
    return _SanLock(_real_lock(), _name_from_frame())


def _rlock_factory(*args, **kwargs):
    return _SanLock(_real_rlock(*args, **kwargs), _name_from_frame())


def install() -> None:
    """Patch the threading lock factories (idempotent). ``Condition()``
    with no explicit lock picks the patch up too: CPython resolves
    ``RLock`` through the threading module globals at call time."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    with _edges_guard:
        _edges.clear()


def observed_edges() -> Set[Tuple[str, str]]:
    with _edges_guard:
        return set(_edges)


def static_graph(root: str) -> Dict[str, Set[str]]:
    """The package's static lock-order digraph (pure AST; no device)."""
    from ..analysis import callgraph, locks
    from ..analysis.core import walk_package
    modules = walk_package(root)
    return locks.lock_order_graph(modules, callgraph.build(modules))


def divergence(observed: Set[Tuple[str, str]],
               static: Dict[str, Set[str]]) -> List[Tuple[str, str]]:
    """Observed edges between statically-known locks that the static
    graph has no path for — the static model missed a real ordering
    (or the runtime inverted a modeled one)."""
    nodes: Set[str] = set(static)
    for vs in static.values():
        nodes |= vs

    def reachable(a: str, b: str) -> bool:
        frontier, seen = [a], {a}
        while frontier:
            cur = frontier.pop()
            if cur == b:
                return True
            for nxt in static.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    return sorted((a, b) for a, b in observed
                  if a in nodes and b in nodes and not reachable(a, b))

"""Runtime lockset sanitizer (``SDTPU_LOCKSAN``, default off).

The static lock analysis (analysis/locks.py + analysis/lockorder.py)
computes an acquisition-order digraph over ``Class.attr`` lock names.
This module is the other half of the contract: when ``SDTPU_LOCKSAN=1``,
the ``threading.Lock`` / ``threading.RLock`` / ``threading.Condition``
factories are replaced with wrappers that

- **name** each lock at creation by inspecting the creating frame: a lock
  born from ``self._lock = threading.Lock()`` inside ``WorkerNode.__init__``
  is named ``WorkerNode._lock`` — the same qualified name the static graph
  uses, so the two graphs diff cleanly;
- **record** every nested acquisition as an ordered edge (held → acquired)
  **per thread** (keyed by thread ident), plus a process-global union, via
  a thread-local held stack;
- implement the ``Condition`` protocol (``_release_save`` /
  ``_acquire_restore`` / ``_is_owned``) so ``cond.wait()`` correctly pops
  and re-pushes the held stack, and **detect** a ``Condition.wait``
  entered while an *unrelated* named lock is still held (the wait blocks
  with that lock pinned — a convoy, and with a second thread a deadlock);
- run **Goodlock-style cycle detection** over the union of all threads'
  edges (:func:`runtime_cycles`) — a cycle means two threads acquired the
  same locks in opposite orders at runtime, the deadlock precondition,
  even if the interleaving that deadlocks never fired in this run.

At teardown (tests/conftest.py wires this under ``SDTPU_LOCKSAN=1``),
:func:`divergence` compares the observed edges against the static graph:
an observed edge between two statically-known lock names with no static
path in that direction means the static model missed a real ordering —
the run fails rather than letting the model rot. Anonymous locks (no
``self.<attr> =`` creation site, stdlib internals) never participate.
``SDTPU_LOCKSAN_ORDER`` (default on) adds the runtime-cycle,
wait-while-holding, and annotation-exercise session checks on top.

The module is also the instrumentation seam for the deterministic
schedule explorer (sim/sched.py): :func:`set_scheduler` installs a
cooperative scheduler, and every lock acquire/release and condition
wait/notify on a scheduler-managed thread routes through it instead of
the raw primitive — those are exactly the yield points the explorer
serializes. With no scheduler installed (the default, including every
production and plain-test path) the branch is two ``None`` checks.

Default off: importing this module patches nothing; ``install()`` is the
only entry point with side effects, and ``uninstall()`` restores the real
factories. The wrapper adds two dict lookups and a list append per
acquire — fine for tests, not meant for production serving.
"""

from __future__ import annotations

import linecache
import re
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_ATTR_ASSIGN = re.compile(r"self\s*\.\s*(\w+)\s*(?::[^=]+)?=")

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition
#: Thread.start's code object, captured before anything (the explorer)
#: can patch it — _note_wait uses it to recognize the bootstrap
#: handshake wait on the child's _started event.
_THREAD_START_CODE = threading.Thread.start.__code__

_installed = False
#: union of every thread's observed (held, acquired) edges
_edges: Set[Tuple[str, str]] = set()
#: thread ident -> that thread's observed edges (Goodlock input)
_edges_per_thread: Dict[int, Set[Tuple[str, str]]] = {}
#: (held-names, waiting-on) pairs for cond.wait entered with extra locks
_wait_violations: Set[Tuple[Tuple[str, ...], str, str]] = set()
_edges_guard = _real_lock()
_tls = threading.local()

#: the cooperative schedule explorer (sim/sched.py), or None. Never set
#: outside an explorer run; every hot-path check is ``_sched is None``.
_sched = None


def set_scheduler(sched) -> None:
    """Install (or with ``None`` remove) the cooperative scheduler that
    lock/condition operations on managed threads route through."""
    global _sched
    _sched = sched


def scheduler():
    return _sched


def _active_sched():
    s = _sched
    if s is not None and s.managed():
        return s
    return None


def _held_stack() -> List["_SanLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _name_from_frame(depth: int = 2) -> Optional[str]:
    """``Class.attr`` for a ``self.<attr> = threading.Lock()`` creation
    site, else None (anonymous)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    obj = frame.f_locals.get("self")
    if obj is None:
        return None
    line = linecache.getline(frame.f_code.co_filename, frame.f_lineno)
    m = _ATTR_ASSIGN.search(line)
    if m is None:
        return None
    return f"{type(obj).__name__}.{m.group(1)}"


def _note_wait(lock: "_SanLock") -> None:
    """Record a ``Condition.wait`` entered while other named locks are
    held: the wait releases *its own* lock but keeps the rest pinned
    for the whole sleep — a convoy, and (if the notifier needs one of
    them) a deadlock.

    One wait is exempt: ``Thread.start``'s bootstrap handshake on the
    child's ``_started`` event. The interpreter's ``_bootstrap_inner``
    sets that event *before* any user code runs on the child, so no
    held lock can ever block the waker — flagging it would force every
    "spawn a worker under my state lock" site into contortions for a
    deadlock that cannot happen."""
    held = [h._san_name for h in _held_stack()
            if h is not lock and h._san_name is not None
            and h._san_name != lock._san_name]
    if not held:
        return
    f = sys._getframe(1)
    while f is not None:
        if f.f_code is _THREAD_START_CODE:
            return
        f = f.f_back
    entry = (tuple(sorted(set(held))), lock._san_name or "<anon>",
             threading.current_thread().name)
    with _edges_guard:
        _wait_violations.add(entry)


class _SanLock:
    """Order-recording wrapper around a real Lock/RLock."""

    def __init__(self, raw, name: Optional[str]):
        self._raw = raw
        self._san_name = name

    # -- bookkeeping ---------------------------------------------------------

    def _push(self) -> None:
        stack = _held_stack()
        if self._san_name is not None:
            new_edges = [
                (h._san_name, self._san_name) for h in stack
                if h._san_name is not None and h._san_name != self._san_name]
            if new_edges:
                ident = threading.get_ident()
                with _edges_guard:
                    _edges.update(new_edges)
                    _edges_per_thread.setdefault(ident, set()).update(
                        new_edges)
        stack.append(self)

    def _pop(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking=True, timeout=-1):
        s = _active_sched()
        if s is not None:
            got = s.lock_acquire(self, blocking, timeout)
        else:
            got = self._raw.acquire(blocking, timeout)
        if got:
            self._push()
        return got

    def release(self):
        self._pop()
        s = _active_sched()
        if s is not None:
            return s.lock_release(self)
        return self._raw.release()

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- Condition protocol (cond.wait releases and reacquires) -------------

    def _release_save(self):
        _note_wait(self)
        self._pop()
        if hasattr(self._raw, "_release_save"):
            return self._raw._release_save()
        self._raw.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._raw, "_acquire_restore"):
            self._raw._acquire_restore(state)
        else:
            self._raw.acquire()
        self._push()

    def _is_owned(self):
        if hasattr(self._raw, "_is_owned"):
            return self._raw._is_owned()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def __repr__(self):
        return f"<SanLock {self._san_name or 'anon'} {self._raw!r}>"


class _SanCondition:
    """Condition wrapper: pure delegation to a real ``threading.Condition``
    normally (the real Condition drives the wrapped lock's
    ``_release_save``/``_acquire_restore``, so edge and wait bookkeeping
    happen exactly as before) — but on a scheduler-managed thread,
    ``wait``/``notify`` become cooperative yield points so the explorer
    can serialize them deterministically instead of sleeping real time."""

    def __init__(self, lock=None):
        if lock is None:
            lock = _rlock_factory()
        self._san_lock = lock if isinstance(lock, _SanLock) else None
        self._real = _real_condition(lock)
        #: cooperative waiters: per-waiter one-shot flags ([False] cells)
        self._coop_waiters: List[List[bool]] = []

    # -- lock passthrough ----------------------------------------------------

    def acquire(self, *args, **kwargs):
        return self._real.acquire(*args, **kwargs)

    def release(self):
        return self._real.release()

    def __enter__(self):
        self._real.__enter__()
        return self

    def __exit__(self, *exc):
        return self._real.__exit__(*exc)

    def _is_owned(self):
        return self._real._is_owned()

    # -- wait/notify ---------------------------------------------------------

    def wait(self, timeout=None):
        s = _active_sched()
        if s is not None and self._san_lock is not None:
            _note_wait(self._san_lock)
            return s.cond_wait(self, timeout)
        return self._real.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        s = _active_sched()
        if s is not None and self._san_lock is not None:
            result = predicate()
            while not result:
                if not self.wait(timeout):
                    return predicate()
                result = predicate()
            return result
        return self._real.wait_for(predicate, timeout)

    def notify(self, n=1):
        if _sched is not None and self._coop_waiters:
            woken = 0
            while self._coop_waiters and woken < n:
                self._coop_waiters.pop(0)[0] = True
                woken += 1
            if woken >= n:
                return
            n -= woken
        return self._real.notify(n)

    def notify_all(self):
        if _sched is not None and self._coop_waiters:
            for cell in self._coop_waiters:
                cell[0] = True
            del self._coop_waiters[:]
        return self._real.notify_all()

    notifyAll = notify_all

    def __repr__(self):
        return f"<SanCondition {self._real!r}>"


def _lock_factory():
    return _SanLock(_real_lock(), _name_from_frame())


def _rlock_factory(*args, **kwargs):
    return _SanLock(_real_rlock(*args, **kwargs), _name_from_frame())


def _cond_factory(lock=None):
    return _SanCondition(lock)


def install() -> None:
    """Patch the threading factories (idempotent). ``Condition()`` with
    no explicit lock picks the RLock patch up too: CPython resolves
    ``RLock`` through the threading module globals at call time — and
    ``Event``/``Barrier`` built after install resolve ``Condition`` the
    same way, so their waits are cooperative under the explorer."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _cond_factory
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    threading.Condition = _real_condition
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    with _edges_guard:
        _edges.clear()
        _edges_per_thread.clear()
        _wait_violations.clear()


def observed_edges() -> Set[Tuple[str, str]]:
    with _edges_guard:
        return set(_edges)


def edges_by_thread() -> Dict[int, Set[Tuple[str, str]]]:
    with _edges_guard:
        return {k: set(v) for k, v in _edges_per_thread.items()}


def wait_violations() -> List[Tuple[Tuple[str, ...], str, str]]:
    """Sorted (held-names, waiting-on, thread-name) records for every
    ``Condition.wait`` entered while holding an unrelated named lock."""
    with _edges_guard:
        return sorted(_wait_violations)


def runtime_cycles() -> List[List[str]]:
    """Goodlock-style check: cycles in the union of all threads' observed
    acquisition edges. A cycle means opposite-order acquisitions really
    executed — a deadlock waiting for the right interleaving — even when
    this run happened not to interleave them fatally."""
    edges = observed_edges()
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[str]] = []
    seen: Set[frozenset] = set()

    def dfs(node: str, stack: List[str], on_stack: Set[str],
            visited: Set[str]) -> None:
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    cycles.append(cyc)
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return cycles


def static_graph(root: str) -> Dict[str, Set[str]]:
    """The package's static lock-order digraph (pure AST; no device).
    Annotation-aware: a ``# sdtpu-lint: lockorder a<b`` in the package
    removes the contradicted reverse edge from this graph, so a runtime
    acquisition in the annotated-away direction is a divergence."""
    from ..analysis import callgraph, locks
    from ..analysis.core import walk_package
    modules = walk_package(root)
    return locks.lock_order_graph(modules, callgraph.build(modules))


def declared_orders(root: str) -> Set[Tuple[str, str]]:
    """The package's ``lockorder a<b`` annotation pairs. The session gate
    requires each to be exercised at runtime (observed as an edge) —
    an annotation no test demonstrates is not allowed to suppress."""
    from ..analysis import locks
    from ..analysis.core import walk_package
    return {(a, b) for a, b, _path, _line
            in locks.declared_orders(walk_package(root))}


def divergence(observed: Set[Tuple[str, str]],
               static: Dict[str, Set[str]]) -> List[Tuple[str, str]]:
    """Observed edges between statically-known locks that the static
    graph has no path for — the static model missed a real ordering
    (or the runtime inverted a modeled one)."""
    nodes: Set[str] = set(static)
    for vs in static.values():
        nodes |= vs

    def reachable(a: str, b: str) -> bool:
        frontier, seen = [a], {a}
        while frontier:
            cur = frontier.pop()
            if cur == b:
                return True
            for nxt in static.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    return sorted((a, b) for a, b in observed
                  if a in nodes and b in nodes and not reachable(a, b))

"""Config schema + persistence.

Capability parity with the reference's config system
(/root/reference/scripts/spartan/pmodels.py:4-46 and
/root/reference/scripts/spartan/world.py:616-722): a pydantic-validated JSON
file holding the worker registry (here: TPU slices / serving backends), each
worker's benchmark calibration (avg images-per-minute, ETA error history,
pixel cap), the shared benchmark payload, and scheduler settings
(``job_timeout``, enable flags, complementary production, step scaling).
Includes legacy-format migration and corrupt-file quarantine
(world.py:632-659 semantics).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from pydantic import BaseModel, Field, field_validator


def _logger():
    """Lazy logger lookup: importing this module must not configure logging
    or create distributed.log (file-writing side effects on import are
    hostile for a library — ADVICE r1)."""
    from stable_diffusion_webui_distributed_tpu.runtime.logging import get_logger

    return get_logger()

# -- environment knobs ------------------------------------------------------
#
# Every SDTPU_* environment read in the package goes through these helpers:
# one warn-and-default policy instead of per-module try/except copies, and
# one place the static analyzer (analysis/envrules.py, rule EV001) sanctions
# for raw ``os.environ`` access. A malformed value never crashes startup —
# it warns once and falls back, matching the config loader's quarantine
# philosophy above.
#
# Step-cache knobs (pipeline/stepcache.py; README "TPU policy knobs"):
#
# - ``SDTPU_DEEPCACHE`` (int, default 1 = off): deep-feature refresh
#   cadence. At N > 1 the UNet's deep blocks (below models/unet.py
#   CACHE_SPLIT, plus the mid block) run once every N steps; in between,
#   only the shallow down blocks + up path run against the cached deep
#   feature. Values quantize DOWN onto stepcache.CADENCE_LADDER
#   (1/2/3/4/6/8) before influencing anything compile-shaped (RC001);
#   per-request override: ``override_settings.deepcache``.
# - ``SDTPU_CFG_CUTOFF`` (float sigma, default 0 = off): below this
#   sigma the CFG uncond half is dropped and the UNet runs cond-only
#   rows. Mapped host-side onto the built sigma ladder and carried as a
#   traced step index; per-request: ``override_settings.cfg_cutoff``.
# - ``SDTPU_FLOPS_METRICS`` (flag, default on): price each dispatched
#   denoise schedule with XLA cost_analysis and expose UNet
#   FLOPs-per-image in DispatchMetrics / ``/internal/status``. ``0``
#   skips the accounting (it costs one abstract lowering per new eval
#   shape).
#
# Defaults keep both levers off: generation stays byte-identical to the
# plain executable unless a deployment opts into the FLOP/quality trade.
#
# Precision knobs (pipeline/precision.py; README "Precision modes"):
#
# - ``SDTPU_UNET_INT8`` / ``SDTPU_UNET_INT8_CONV`` (flags, default off):
#   the server's DEFAULT serving precision ("int8" / "int8+conv").
#   Defaults only — every request resolves its own precision through the
#   3-rung ladder (``override_settings.precision`` or the payload
#   ``precision`` field wins), so these flags never pin a deployment to
#   one rung.
# - ``SDTPU_WARMUP_PRECISIONS`` (comma list, default "" = policy default
#   only): extra precision rungs the AOT warmup sweep pre-builds per
#   bucket (serving/warmup.py) — precision is a static compile-key axis.
#
# Traced-LoRA knobs (models/lora.py; README "Recompile-free LoRA"):
#
# - ``SDTPU_LORA_TRACED`` (flag, default off): serve LoRA adapters as
#   TRACED jit arguments instead of host-merging them into the param
#   tree. Adapter up/down factors are padded onto a static
#   (rank-bucket, slot-count) ladder and applied as ``W x + s·up(down x)``
#   at each Dense site, so switching adapters changes only array
#   CONTENTS — zero recompiles, zero cache purges (embed/result/prefix
#   keys fold the set's content address instead of a model epoch). Off
#   (the default), the merged path runs byte-identical to the pre-knob
#   build; adaptive samplers and un-bucketable sets fall back to it
#   even when on.
# - ``SDTPU_LORA_RANKS`` (comma int list, default "8,16,32,64"): the
#   rank-bucket ladder. Adapter ranks pad UP onto it; each distinct
#   bucket is one executable variant per shape bucket.
# - ``SDTPU_LORA_SLOTS`` (comma int list, default "1,2,4"): the
#   adapter-slot ladder — how many simultaneous adapters a traced set
#   can stack per request before falling back to the merge path.
# - ``SDTPU_LORA_CACHE_MB`` (float MB, default 256): byte cap on the
#   registry's loaded-adapter LRU (pipeline/registry.py); entries are
#   mtime-validated so an adapter edited on disk reloads instead of
#   serving stale.
# - ``SDTPU_WARMUP_LORA`` (comma ``rXsY`` list, default "" = none):
#   traced-LoRA ladder cells the AOT warmup sweep pre-builds with
#   all-zero stand-in sets (serving/warmup.py) — every real adapter
#   bucketed into a warmed cell shares its executables.
#
# Ragged-dispatch knobs (serving/bucketer.py, ops/ragged_attention.py;
# README "Ragged dispatch"):
#
# - ``SDTPU_RAGGED`` (flag, default off): true-length batching. On,
#   coalescable txt2img requests match a bucket on WIDTH only and run at
#   the tallest ladder height for that width; each batch row carries its
#   true latent-row count and true conditioning-token counts as TRACED
#   int32 vectors, the attention kernels mask the padded tail, and the
#   serving layer crops top-aligned. Heterogeneous heights thereby share
#   ONE chunk executable per width class instead of one per ladder rung.
#   Off (the default), the classic area-ladder path runs byte-identical
#   to the unragged build (hash-pinned in tests/test_ragged.py).
# - ``SDTPU_RAGGED_LADDER`` (comma WxH list, default "" = the regular
#   bucket ladder): an explicitly coarse shape list ragged matching
#   scans instead — the knob that collapses a fine classic ladder down
#   to one bucket per width class without touching classic traffic.
#
# Observability knobs (obs/ package; README "Observability"):
#
# - ``SDTPU_OBS`` (flag, default on): per-request span tracing. Spans are
#   host-side perf_counter intervals — never a device sync — so they stay
#   on by default; ``0`` turns :func:`obs.spans.span` into a no-op.
# - ``SDTPU_OBS_MAX_REQUESTS`` (int, default 256): finished request
#   traces retained for ``/internal/trace.json`` (bounded store; oldest
#   evicted first).
# - ``SDTPU_OBS_FLIGHTREC`` (int, default 16): failed/interrupted/slow
#   request entries the flight recorder keeps (``/internal/flightrec``).
# - ``SDTPU_OBS_SLOW_S`` (float seconds, default 30): e2e latency above
#   which a request is flight-recorded as a slow outlier; ``0`` disables
#   slow capture (errors and interrupts are always recorded).
#
# Fleet-scheduler knobs (fleet/ package; README "Fleet scheduling"):
#
# - ``SDTPU_FLEET`` (flag, default off): master switch for the multi-tenant
#   tier — weighted-fair device gate, per-tenant quotas, ETA-SLO admission
#   and chunk-boundary preemption. Off keeps the dispatcher's plain
#   exec-lock path byte-identical to the pre-fleet build. The config field
#   ``fleet_enabled`` sets the same switch; the env var wins.
# - ``SDTPU_FLEET_CLASSES`` (``name:weight`` list, default
#   ``interactive:8,batch:2,best_effort:1``): WFQ weight per priority
#   class. Unknown names define extra classes scheduled like ``batch``.
# - ``SDTPU_SLO_INTERACTIVE_S`` (float seconds, default 30): completion
#   SLO the admission controller enforces for ``interactive`` requests;
#   0 disables SLO admission. Per-request ``slo_s`` overrides it.
# - ``SDTPU_QUOTA_IPM`` (float images/minute, default 0 = unlimited):
#   per-tenant token-bucket refill rate; ``SDTPU_QUOTA_BURST`` (float,
#   default 8) is the bucket depth. Exhausted tenants get 429 +
#   Retry-After.
# - ``SDTPU_FLEET_AGING_S`` (float seconds, default 10): waiters older
#   than this are served oldest-first regardless of fair-queue tags
#   (starvation bound).
# - ``SDTPU_FLEET_QUANTUM_S`` (float seconds, default 0.25): minimum
#   device tenure before a preemptible job may be asked to yield.
# - ``SDTPU_FLEET_FEWSTEP`` (int, default 12): step budget the deepest
#   admission degrade rung clamps to before rejecting; 0 disables the
#   few-step rung.
# - ``SDTPU_AUTOSCALE_UP_S`` / ``SDTPU_AUTOSCALE_DOWN_S`` /
#   ``SDTPU_AUTOSCALE_COOLDOWN_S`` (floats, defaults 5 / 0.5 / 60):
#   slice autoscale thresholds — scale a slice group up when the worst
#   per-class queue-wait p95 crosses UP_S, down when it falls below
#   DOWN_S, with at most one decision per slice per cooldown
#   (fleet/slices.py; decision engine + hooks only, no provisioning).
# - ``SDTPU_AUTOSCALE_AUDIT`` (int, default 256): autoscale decision
#   audit-ring capacity behind ``/internal/autoscale`` — every retained
#   decision with its wall-clock timestamp (fleet/slices.py).
# - ``SDTPU_PERF`` (flag, default off): the perf ledger (obs/perf.py).
#   On, every device dispatch reports host-observed seconds + accounted
#   FLOPs into per-(bucket, cadence, precision) MFU / padding-waste
#   groups served at ``/internal/perf`` and as ``sdtpu_perf_*``
#   Prometheus families; compile builds and fleet SLO outcomes feed the
#   same ledger. Off (the default), every record call is a no-op and
#   the dispatch path is byte-identical to the uninstrumented build.
# - ``SDTPU_PERF_GROUPS`` (int, default 64): bounded ledger width —
#   distinct (bucket, cadence, precision) rows and distinct (tenant,
#   class) SLO rows each; least-recently-touched rows are evicted (and
#   counted) so adversarial tenant names cannot grow the ledger.
# - ``SDTPU_PERF_PEAK_FLOPS`` (float FLOP/s, default 0 = auto): MFU
#   denominator override. 0 resolves the chip's bf16 peak from the
#   built-in table (int8 counts double); unknown hardware (CPU dev
#   boxes) reports MFU null rather than inventing a denominator.
# - ``SDTPU_PERF_SLO_TARGET`` (float, default 0.95): SLO attainment
#   target behind the burn-rate gauge — burn 1.0 means consuming the
#   (1 - target) error budget exactly.
# - ``SDTPU_JOURNAL`` (flag, default off): the request lifecycle journal
#   (obs/journal.py). On, every request's journey (received -> admitted/
#   throttled -> bucketed -> coalesced -> dispatched -> decoded ->
#   merged -> completed/failed, plus scheduler-tier plan/requeue events)
#   is recorded with monotonic timestamps, causal parent seqs and
#   payload fingerprints, served at ``GET /internal/journal`` and
#   replayable with ``tools/replay.py``. Off (the default), every emit
#   returns before touching the buffer and the serving path is
#   byte-identical to the unjournaled build.
# - ``SDTPU_JOURNAL_MAX`` (int, default 4096): journal ring capacity in
#   events; oldest events are dropped first (the ring never blocks or
#   grows unbounded).
# - ``SDTPU_JOURNAL_SINK`` (path, default '' = off): JSONL spill file
#   for ring-evicted journal events — each event the ring drops is
#   appended as one JSON line (best-effort; write errors are swallowed),
#   so ring + sink stay a complete record on runs longer than the ring.
#   ``tools/replay.py`` and ``sim/workload.py`` load sink files directly.
# - ``SDTPU_SIM`` (flag, default off): the scenario engine (sim/).
#   When 1, chaos fault plans may be armed into the CHAOS_HOOK seams
#   (scheduler/worker.py, scheduler/world.py, serving/dispatcher.py) and
#   scenario runs are scored/recorded at ``/internal/sim``. Off (the
#   default), sim.chaos.arm refuses, every hook stays None, and the
#   serving/scheduler paths are byte-identical to the ungated build
#   (hash-pinned in tests/test_sim.py).
# - ``SDTPU_SIM_SEED`` (int, default 0): default seed for workload
#   generation and chaos plans in ``bench.py --scenarios`` — one seed
#   reproduces the whole scenario matrix byte-for-byte.
# - ``SDTPU_HEARTBEAT_S`` (float seconds, default 0 = off): worker
#   heartbeat prober period — a daemon sweep of ``ping_workers`` so an
#   UNAVAILABLE remote recovers to IDLE (and its health window updates)
#   without an operator ping (scheduler/world.py start_heartbeat).
# - ``SDTPU_WATCHDOG_FACTOR`` (float, default 0 = off): hang watchdog
#   multiple — a dispatch or remote job still running past FACTOR x its
#   predicted ETA gets a thread-stack dump into the flight recorder, a
#   ``sdtpu_watchdog_stalls_total`` bump, and (remote jobs) a nudge into
#   the requeue path (obs/watchdog.py). Only armed where an ETA exists
#   (benchmarked calibration); 0 never arms and the join path is
#   byte-identical to the unwatched build.
# - ``SDTPU_LOCKSAN`` (flag, default off): runtime lockset sanitizer
#   (runtime/locksan.py). When 1, tests/conftest.py wraps the
#   ``threading`` lock factories to record observed lock-acquisition
#   order and diffs it against the static LK005 lock-order graph at
#   session end; any ordering the static model missed fails the run.
#   Off by default: nothing is patched and the lock path is
#   byte-identical to stock threading. Test harness only — never set in
#   production serving.
# - ``SDTPU_LOCKSAN_ORDER`` (flag, default ON when SDTPU_LOCKSAN=1):
#   the ordering layer of the session gate (tests/conftest.py). Adds
#   three checks on top of the divergence diff: Goodlock-style cycle
#   detection over the union of per-thread observed acquisition edges
#   (opposite orders that really executed fail the run even when this
#   schedule happened not to deadlock), ``Condition.wait`` entered
#   while holding an unrelated lock, and ``lockorder a<b`` annotations
#   the suite never exercised (an undemonstrated order may not suppress
#   LK005). Set 0 to drop back to the divergence diff alone while
#   debugging.
# - ``SDTPU_SCHED_SEEDS`` (int, default 64): seeds per subsystem
#   harness for the deterministic schedule explorer sweep in
#   ``bench.py --ledger`` (sim/sched.py + sim/harnesses.py). Each seed
#   is one PCT-style priority interleaving; the ledger's
#   ``schedule_explorer_seeds`` counts the clean ones. Same seed, same
#   trace — raise it for a deeper prowl, never for determinism.
# - ``SDTPU_CACHE`` (flag, default off): million-user caching tier
#   (cache/). When 1, three layers arm over one bounded LRU store:
#   content-addressed embedding dedupe over the CLIP text tower
#   (keyed on prompt text + clip_skip + model/tower fingerprint),
#   seed-keyed result dedupe with single-flight leader election
#   (byte-exact payload repeats return cached images before bucketing,
#   never consuming a dispatch slot or feeding queue-wait/ETA
#   accounting), and denoise prefix sharing (requests identical up to
#   step k resume from a mid-denoise carry captured at a step-cache
#   chunk boundary). Off: nothing is cached and every path is
#   byte-identical to the ungated build.
# - ``SDTPU_CACHE_EMBED_MB`` (float MB, default 64): embed-cache byte
#   cap. Oldest conditioning entries evict LRU past it.
# - ``SDTPU_CACHE_RESULT_MB`` (float MB, default 256): result-dedupe
#   byte cap over cached images + infotexts.
# - ``SDTPU_CACHE_PREFIX_MB`` (float MB, default 128): prefix-latent
#   byte cap; each entry holds one full sampler carry (latents +
#   multistep history).
# - ``SDTPU_CACHE_PREFIX_MIN_STEPS`` (int, default 4): shallowest
#   denoise step a prefix may be captured or resumed at — captures
#   shallower than this are noise-dominated and not worth the bytes.
# - ``SDTPU_JOURNAL_SINK_MAX_MB`` (float MB, default 0 = unbounded):
#   size cap on the journal sink file. When the next spilled line would
#   push the sink past the cap it rotates once via ``os.replace`` to
#   ``<sink>.1`` (the previous ``.1`` is discarded — at most two files
#   ever exist), so a long-running serving box keeps a bounded, recent
#   tail. ``tools/replay.py`` loads the rotated pair as one contiguous
#   stream; ``sink_status()`` reports bytes written and rotations.
# - ``SDTPU_TSDB`` (flag, default off): in-process metric history
#   (obs/tsdb.py) — a bounded ring buffer per series, sampled from the
#   registered Prometheus families plus derived series (rank-
#   interpolated queue-wait/e2e p95, per-tenant SLO burn, device-memory
#   watermarks), served at ``GET /internal/tsdb`` and queried by the
#   alert engine. Off (the default), no daemon starts, ``tick()`` is a
#   no-op, and the serving path is byte-identical to the unsampled
#   build (hash-pinned in tests/test_tsdb.py).
# - ``SDTPU_TSDB_INTERVAL_S`` (float seconds, default 1.0, floor 0.01):
#   sampling daemon cadence.
# - ``SDTPU_TSDB_POINTS`` (int, default 512, floor 8): per-series ring
#   depth; with the default 1s cadence that is ~8.5 minutes of history.
# - ``SDTPU_ALERTS`` (flag, default off): the alert engine
#   (obs/alerts.py) over the TSDB — multi-window multi-burn-rate SLO
#   alerts, EWMA z-score anomaly detectors (queue wait, compile rate,
#   error rate) and deterministic increase detectors (worker flap,
#   watchdog stall) run through a pending/firing/resolved state machine.
#   Transitions journal as ``alert_firing``/``alert_resolved``, export
#   ``sdtpu_alert_state``/``sdtpu_alerts_total``, land firing flight-
#   recorder entries, and feed the autoscaler's scale-up signal.
#   Needs ``SDTPU_TSDB=1`` for data; off, ``evaluate()`` returns
#   immediately and nothing changes.
# - ``SDTPU_ALERT_TIMESCALE`` (float, default 1.0): multiplier on every
#   rule's wall-clock windows so scenario runs compress the 5m/1h/6h
#   SLO windows into seconds (``0.01`` -> 3s/36s/216s) without touching
#   thresholds — ``bench.py --alerts`` validates with it.
# - ``SDTPU_FEDERATION`` (flag, default off): fleet-federated metrics
#   (obs/federation.py) — the master-side prober scrapes every HTTP
#   worker's ``/internal/metrics`` + ``/internal/tsdb`` on the TSDB
#   sampler's cadence, records ``worker:<label>/...`` series plus
#   ``fleet/...`` aggregates (worst-of-fleet queue-wait p95, mean error
#   rate, stale-worker count), serves ``GET /internal/fleet``, and arms
#   the ``worker_metrics_stale`` / ``fleet_error_rate`` alert rules and
#   the autoscaler's fleet-wide scale signal. Off (the default) no
#   source registers, ``tick()`` is a no-op, and the serving path is
#   byte-identical (hash-pinned in tests/test_federation.py).
# - ``SDTPU_TSDB_DIR`` (path, default unset): TSDB durability — the
#   sampling daemon snapshots every ring to
#   ``<dir>/tsdb_snapshot.json`` every 10 ticks and at shutdown
#   (tmp + ``os.replace``, crash-safe), and a (re)start merges the
#   on-disk history back in (future-stamped samples from a prior boot
#   are dropped), so ``quantile_over_time`` windows survive restarts.
#   Corrupt or truncated snapshots load as nothing, never an error.
# - ``SDTPU_NOTIFY_URL`` (url, default unset): alert notification
#   delivery (obs/notify.py) — every alert firing/resolved transition
#   is queued (bounded) and POSTed as JSON to this webhook by a drain
#   thread with retry + exponential backoff; outcomes count into
#   ``sdtpu_notify_total{outcome}`` and journal as ``notify_sent`` /
#   ``notify_failed``. Unset (the default) the queue is never touched
#   and no thread starts.
# - ``SDTPU_NOTIFY_DEDUP_S`` (float seconds, default 60): identical
#   (channel, rule, event) transitions inside this window are dropped
#   (outcome ``deduped``) so a flapping rule cannot page-storm.
# - ``SDTPU_NOTIFY_ROUTES`` (default unset): severity-routed delivery —
#   comma-separated ``key=url`` entries where ``key`` is a severity
#   (``page``/``warn``/``info``) or a tenant-scoped override
#   (``tenant:severity``). Resolution precedence: tenant:severity ->
#   severity -> the ``SDTPU_NOTIFY_URL`` default channel -> drop.
#   Each channel gets its own bounded queue and per-channel outcome
#   counts (``sdtpu_notify_total{channel,outcome}``); malformed
#   entries are skipped. ``bench.py --obsplane`` validates the routing
#   matrix (page and warn never cross channels).
# - ``SDTPU_PUSH`` (flag, default off): the push control plane
#   (obs/push.py) — workers buffer their journal events, federated
#   TSDB samples and counter totals behind cursor-indexed ``GET
#   /internal/deltas`` long-polls; the master runs one DeltaSubscriber
#   daemon per worker that resumes from its cursor after a disconnect
#   (no loss, no duplicates) and writes the *same*
#   ``worker:<label>/...`` + ``fleet/...`` series the poll prober
#   fills, so alert rules and the autoscaler are plane-agnostic.
#   Streamed journal events merge into the fleet timeline
#   (obs/fleetlog.py, ``GET /internal/fleet/timeline``) with RTT-
#   midpoint clock offsets. A worker answering 404 demotes its
#   subscriber to the poll path (``push_fallback`` journaled) — push
#   is an upgrade, never a requirement. Off (the default)
#   ``/internal/deltas`` answers 404, no source registers, no daemon
#   starts, and the serving path is byte-identical to the poll-only
#   build (pinned to the same golden in tests/test_push.py).
# - ``SDTPU_PUSH_CURSOR_BUF`` (int, default 1024, floor 16): worker-
#   side retained-entry depth; past it the oldest entries are evicted
#   (counted, journaled as ``push_buffer_evicted``, and reported as
#   ``lost`` to any consumer whose cursor predates the window).
# - ``SDTPU_PUSH_WAIT_S`` (float seconds, default 0.25, floor 0): how
#   long one ``/internal/deltas`` request may hold the connection
#   waiting for fresh entries before answering empty.
# - ``SDTPU_OBS_HTTP_TIMEOUT_S`` (float seconds, floor 0.05): the one
#   obs-plane outbound HTTP timeout — trace stitching, federation
#   polls, webhook delivery, and the HTTP backend's control-plane
#   probes all resolve through ``obs/stitch.py:http_timeout_s`` so a
#   hung worker costs one bounded timeout, never a stalled sweep.
#   Unset, each call site keeps its historical default (stitch 5.0,
#   backend probes 3.0).
#
# Stage-graph executor knobs (parallel/stage_graph.py, pipeline/engine.py,
# serving/dispatcher.py; README "Stage-graph execution"):
#
# - ``SDTPU_STAGE_GRAPH`` (flag, default off): the stage-graph executor.
#   On, every dispatch group becomes an explicit Encode -> Denoise ->
#   Decode (dispatcher groups: -> Merge) node graph whose stages dispatch
#   async, with host materialization deferred through a depth-limited
#   runner — group *i*'s VAE fetch and group *i+1*'s CLIP encode overlap
#   group *i+1*'s denoise on the host timeline — and eligible ControlNet
#   requests evaluate the tower one sigma-step ahead of the UNet in its
#   own executable. Host pacing only: images/seeds/infotexts are
#   byte-identical to the serial path (the seed contract keys every draw
#   by global image index; hash-pinned in tests/test_stagegraph.py).
#   Off (the default) nothing changes — the serial path is gate-off
#   golden-pinned.
# - ``SDTPU_STAGE_DEPTH`` (int, default 1): graphs the runner keeps in
#   flight before flushing the oldest. 1 reproduces the classic
#   decode-trails-one-group schedule; deeper widens host overlap at the
#   cost of more live latent batches.
# - ``SDTPU_STAGE_CN_DEVICES`` (int, default 0 = off): carve this many
#   devices (preferring devices OUTSIDE the engine's mesh) into a
#   ControlNet mesh slice; stage-ahead residuals evaluate there and hop
#   back to the UNet mesh as stage inputs. 0 keeps residuals on the
#   engine mesh; values that would swallow every device fall back to 0.
#
# AOT artifact / warm-pool knobs (serving/aot.py, fleet/pool.py;
# README "AOT artifacts & warm pools"):
#
# - ``SDTPU_AOT`` (flag, default off): AOT executable artifacts. On,
#   every ``Engine._cached`` cell becomes a load-before-build
#   dispatcher: the first call per concrete signature deserializes the
#   stage's compiled executable from the artifact store instead of
#   tracing + compiling, and a fresh compile (store miss) serializes
#   its result back. Cells are keyed by the existing compile key + call
#   signature + a jax/jaxlib/platform/device/topology fingerprint; a
#   fingerprint mismatch or damaged artifact falls back to a fresh
#   compile (journaled ``aot_fallback``) — never a wrong executable,
#   never a crash. Off (the default) ``Engine._cached`` takes its
#   pre-existing path byte-identically (golden-pinned in
#   tests/test_aot.py).
# - ``SDTPU_AOT_DIR`` (path, default ``~/.cache/sdtpu-aot``): artifact
#   store root — a JSON manifest plus content-addressed ``*.aotx``
#   files (inspect/verify with ``tools/aot_report.py``). Re-read per
#   store access so tests and bench phases can repoint it.
# - ``SDTPU_POOL`` (flag, default off): the warm engine pool
#   (fleet/pool.py). On, a dispatcher constructed with ``pool=`` checks
#   each execution out to the least-loaded ready resident; autoscale
#   decisions attached via ``WarmPool.attach_autoscale`` spawn/retire
#   residents for real and upgrade their ``/internal/autoscale`` audit
#   entries from ``no_executor`` to ``executed``/``failed``. Off, the
#   dispatcher runs every request on its own engine, unchanged.
# - ``SDTPU_POOL_SIZE`` (int, default 2): the pool's target ready
#   resident count — ``heal()`` spawns back up to it after a chaos
#   kill or a crash.
# - ``SDTPU_POOL_COOLDOWN_S`` (float seconds, default 0): minimum wall
#   time between autoscale-driven spawn/retire executions; a decision
#   landing inside the window records ``failed``/``cooldown`` in the
#   audit ring instead of thrashing capacity.


def read_env(name: str, default: str = "") -> str:
    """The package's only sanctioned raw environment read (EV001)."""
    return os.environ.get(name, default)


def env_str(name: str, default: str = "") -> str:
    val = read_env(name, "").strip()
    return val if val else default


def env_flag(name: str, default: bool = False) -> bool:
    """'' -> default; '0'/'false'/'off'/'no' -> False; anything else -> True."""
    raw = read_env(name, "").strip().lower()
    if raw == "":
        return default
    return raw not in ("0", "false", "off", "no")


def env_parsed(name: str, parse, default, what: str = "value"):
    """Warn-and-default parse of an env var: unset -> default, unparseable
    -> UserWarning + default. ``parse`` gets the raw string and may raise
    ValueError/TypeError to reject it. ``warnings`` (not the logger) is the
    channel: a bad knob is an operator-facing config mistake, and it must
    surface even before logging is configured."""
    raw = read_env(name, "")
    if raw.strip() == "":
        return default
    try:
        return parse(raw)
    except (ValueError, TypeError) as e:
        import warnings

        warnings.warn(f"{name}={raw!r} is not a valid {what} ({e}); "
                      f"using default {default!r}", stacklevel=3)
        return default


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    return env_parsed(name, lambda raw: int(raw.strip()), default, "int")


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    return env_parsed(name, lambda raw: float(raw.strip()), default, "float")


#: Benchmark protocol constants (reference: shared.py:63-64).
WARMUP_SAMPLES = 2
RECORDED_SAMPLES = 3


class BenchmarkPayload(BaseModel):
    """The fixed calibration workload (reference: shared.py:67-77, pmodels.py:4-10)."""

    prompt: str = "A herd of cows grazing at the bottom of a sunny valley"
    negative_prompt: str = ""
    steps: int = 20
    width: int = 512
    height: int = 512
    batch_size: int = 1
    sampler_name: str = "Euler a"


class WorkerModel(BaseModel):
    """Per-worker (per-slice) persisted state (reference: pmodels.py:12-34).

    In the TPU build a "worker" is a generation backend: the in-process mesh
    slice (master), another slice of the same pod, or a remote host reachable
    over the sdapi-compatible control plane. Calibration fields survive
    restarts so scheduling stays warm (world.py:705-722 semantics).
    """

    address: str = "localhost"
    port: int = 7860
    avg_ipm: Optional[float] = None  # images per minute; None = not benchmarked
    master: bool = False
    # ETA mean-percent-error history, most recent last (worker.py:483-490).
    eta_percent_error: List[float] = Field(default_factory=list)
    user: Optional[str] = None
    password: Optional[str] = None
    tls: bool = False
    disabled: bool = False
    # Maximum width*height*batch this worker will accept; 0 = uncapped
    # (reference: world.py:62-72 pixel-cap guard in Job.add_work; the
    # reference's -1 "no limit" sentinel is normalized to 0 on load).
    pixel_cap: int = 0
    # Pin this worker to a specific checkpoint: model sync sends this name
    # instead of the fleet's current model (reference ui.py:161-171 exposes
    # it per worker; persisted here so the pin survives restarts).
    model_override: Optional[str] = None
    # TPU-native extension: which local devices this backend drives
    # (empty = all visible devices; remote workers leave it empty).
    device_ids: List[int] = Field(default_factory=list)

    @field_validator("pixel_cap")
    @classmethod
    def _normalize_pixel_cap(cls, v: int) -> int:
        # Reference-era configs carry pixel_cap: -1 for "no limit"
        # (pmodels.py:34); any non-positive value means uncapped here.
        return 0 if v <= 0 else v


class ConfigModel(BaseModel):
    """Root config (reference: pmodels.py:36-46)."""

    workers: List[Dict[str, WorkerModel]] = Field(default_factory=list)
    benchmark_payload: BenchmarkPayload = Field(default_factory=BenchmarkPayload)
    # Seconds of predicted stall we tolerate before deferring a worker's
    # images to faster peers (reference: pmodels.py:42, default 3).
    job_timeout: int = 3
    enabled: bool = True
    # img2img tab enabled by default, matching the reference (pmodels.py:44).
    enabled_i2i: bool = True
    # Let slow (deferred) workers produce "bonus" images in their slack time
    # (reference optimize_jobs step 4, world.py:519-543).
    complement_production: bool = True
    # If a complementary worker can't fit one image in the slack window,
    # give it one image at reduced step count (world.py:547-557).
    step_scaling: bool = False
    # Master schedules only remotes, producing no images itself
    # (reference "thin-client mode", world.py:109-110 analogue).
    thin_client_mode: bool = False
    # TPU-native additions (absent from the reference's schema):
    model_dir: str = "models"
    default_model: str = ""
    mesh_axes: Dict[str, int] = Field(default_factory=dict)  # e.g. {"dp": 4, "tp": 2}
    # -- serving-layer knobs (serving/ package) ---------------------------
    # Shape-bucket ladder: comma list of WxH resolutions requests are
    # padded UP to before execution, so the engine compiles at most one
    # chunk executable per (bucket, batch) instead of one per unique
    # request shape. Images are center-cropped back to the requested size.
    # Env SDTPU_BUCKET_LADDER overrides; malformed values warn and fall
    # back to "512x512,640x640,768x768,1024x1024".
    bucket_ladder: str = ""
    # Batch ladder: comma list of device batch sizes the coalescer pads
    # merged batches up to (pad-and-drop). Env SDTPU_BATCH_LADDER
    # overrides; default "1,2,4,8".
    batch_ladder: str = ""
    # Coalesce window (seconds): how long the first request of a
    # compatible group waits for concurrent requests to merge into its
    # device batch. 0 disables waiting (requests still merge while the
    # engine is busy with a previous batch). Env SDTPU_COALESCE_WINDOW
    # overrides; default 0.05.
    coalesce_window: Optional[float] = None
    # Multi-tenant fleet tier (fleet/ package): priority classes, quotas,
    # SLO admission and preemption. None = off unless SDTPU_FLEET says
    # otherwise (the env var always wins; see the knob block above).
    fleet_enabled: Optional[bool] = None


def default_config_path() -> str:
    return env_str("SDTPU_CONFIG", "distributed-config.json")


def load_config(path: Optional[str] = None) -> ConfigModel:
    """Read + validate the JSON config; migrate or quarantine unreadable files.

    Mirrors the reference's ``World.config`` (world.py:616-659): a missing
    file yields defaults, a legacy ``workers.json``-style list is migrated,
    and a corrupt file is renamed aside rather than crashing startup.
    """
    path = path or default_config_path()
    if not os.path.exists(path):
        _logger().debug("config %s not found, using defaults", path)
        return ConfigModel()
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        return _quarantine(path, "corrupt", e)

    try:
        if isinstance(raw, list):
            # Legacy format: bare list of worker dicts (world.py:632-649).
            _logger().info("migrating legacy worker-list config %s", path)
            workers = []
            for entry in raw:
                label = entry.pop("label", entry.get("address", "worker"))
                workers.append({label: WorkerModel(**entry)})
            return ConfigModel(workers=workers)
        return ConfigModel(**raw)
    except Exception as e:
        return _quarantine(path, "invalid", e)


def _quarantine(path: str, kind: str, err: Exception) -> ConfigModel:
    """Rename a bad config aside rather than crashing startup (world.py:655-659)."""
    quarantine = f"{path}.{kind}-{int(time.time())}"
    _logger().warning("config %s %s (%s); moving to %s", path, kind, err, quarantine)
    try:
        os.replace(path, quarantine)
    except OSError:
        pass
    return ConfigModel()


def save_config(cfg: ConfigModel, path: Optional[str] = None) -> None:
    """Atomically persist the config (reference: world.py:705-722)."""
    path = path or default_config_path()
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(cfg.model_dump(), f, indent=2)
    os.replace(tmp, path)
    _logger().debug("config saved to %s", path)

"""CLI flag registry (capability parity with /root/reference/preload.py:6-38).

The reference registers ``--distributed-*`` flags on the webui argparser at
preload time. Here the framework owns its own parser; ``add_flags`` can also
be called on an external parser to embed the framework in a host app.
"""

from __future__ import annotations

import argparse


def add_flags(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    group = parser.add_argument_group("distributed")
    parser.add_argument(
        "--distributed-config",
        type=str,
        default=None,
        help="path of the distributed config file (reference: preload.py:31-37)",
    )
    group.add_argument(
        "--distributed-debug",
        action="store_true",
        help="verbose logging + debug-only controls (reference: preload.py:27)",
    )
    group.add_argument(
        "--distributed-skip-verify-remotes",
        action="store_true",
        help="disable TLS certificate verification for remote workers "
        "(reference: preload.py:19-23)",
    )
    group.add_argument(
        "--thin-client",
        action="store_true",
        help="exclude the local engine from planning: coordinate remotes "
        "only (reference thin-client mode, world.py:411-412,564-594)",
    )
    # TPU-native flags (no reference equivalent):
    group.add_argument(
        "--mesh",
        type=str,
        default=None,
        help='mesh axis spec, e.g. "dp=4,tp=2" (default: all devices on dp)',
    )
    group.add_argument(
        "--model-dir", type=str, default=None, help="checkpoint directory"
    )
    group.add_argument("--listen", type=str, default="127.0.0.1", help="API bind host")
    group.add_argument("--port", type=int, default=7860, help="API bind port")
    return parser


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="sdtpu")
    return add_flags(parser)

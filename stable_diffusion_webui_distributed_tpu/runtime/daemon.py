"""StoppableDaemon: the one daemon-loop base for the package.

Before this module existed the package grew five hand-rolled daemon
loops (TSDB sampler, federation prober, notifier drain, heartbeat
prober, watchdog timer), each re-deriving the same start/stop/join
protocol — and one of them shipped the ``Thread._stop`` shadowing bug
(PR 14): subclassing ``threading.Thread`` and naming your stop event
``_stop`` silently breaks ``join()``, because ``Thread.join`` calls a
*private* ``self._stop()``. The lint rule TH001
(analysis/threadrules.py) now flags raw ``threading.Thread(daemon=True)``
loops outside this module, so the footgun class is closed for good.

Design notes:

- **Composition, not inheritance.** A StoppableDaemon *owns* a plain
  ``threading.Thread``; it never subclasses it, so no attribute can
  shadow a Thread private.
- **Uniform lifecycle.** ``start()`` is idempotent and restart-safe,
  ``stop()`` signals + joins + reports, ``alive()`` is the one liveness
  probe. ``stop()`` of a never-started daemon is a no-op.
- **Tick injection.** ``tick()`` runs one iteration inline on the
  caller's thread — tests and bench drive deterministic clocks without
  the thread ever starting (the same pattern obs/tsdb.py established).
- **Wakeable waits.** The inter-tick pause waits on an Event, so
  ``wake()`` (e.g. the notifier's enqueue path) and ``stop()`` both cut
  a sleep short instead of paying the full period.
- **One-shot timers.** ``StoppableDaemon.one_shot`` covers the
  watchdog arm/disarm pattern: fire ``tick`` once after ``delay_s``
  unless stopped first; ``stop()`` before expiry cancels the firing.

The loop itself never swallows tick exceptions — a tick that can fail
must guard itself (the TSDB tick already does); a daemon dying loudly
beats one spinning on a poisoned state.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Union

__all__ = ["StoppableDaemon"]


class StoppableDaemon:
    """A restartable periodic (or one-shot) background loop.

    ``tick`` is the loop body; ``period_s`` the inter-tick pause (a
    float, or a zero-arg callable re-read every iteration so knob
    changes land without a restart). ``immediate=True`` ticks before
    the first pause (samplers); ``immediate=False`` pauses first
    (heartbeats — nothing to probe at t=0).
    """

    def __init__(self, name: str, tick: Callable[[], object],
                 period_s: Union[float, Callable[[], float]], *,
                 immediate: bool = True) -> None:
        self.name = name
        self._tick = tick
        self._period_s = period_s
        self._immediate = immediate
        self._one_shot = False
        self._halt = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    @classmethod
    def one_shot(cls, name: str, delay_s: float,
                 fire: Callable[[], object]) -> "StoppableDaemon":
        """A timer: run ``fire`` once after ``delay_s`` unless ``stop()``
        lands first (the watchdog arm/disarm pattern)."""
        d = cls(name, fire, delay_s, immediate=False)
        d._one_shot = True
        return d

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> bool:
        """Start the loop thread (idempotent; restart-safe after a
        ``stop()``). Returns True when a thread is running on exit."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._halt.clear()
            self._wake.clear()
            self._thread = threading.Thread(
                target=self._run, name=self.name, daemon=True)
            self._thread.start()
            return True

    def stop(self, timeout_s: float = 2.0) -> bool:
        """Signal the loop to exit and join it. Returns True when the
        thread is gone (or never ran) within the timeout."""
        with self._lock:
            thread = self._thread
        self._halt.set()
        self._wake.set()
        if thread is None:
            return True
        thread.join(timeout=timeout_s)
        gone = not thread.is_alive()
        if gone:
            with self._lock:
                if self._thread is thread:
                    self._thread = None
        return gone

    def halt(self) -> None:
        """Signal the loop to exit without joining. The only legal way
        for a tick to cancel its own loop (``stop()`` would self-join);
        also right for hot paths that must not block on the join."""
        self._halt.set()
        self._wake.set()

    def alive(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def stopped(self) -> bool:
        """True once ``stop()`` has been signalled (a one-shot reads
        this as "was I cancelled?")."""
        return self._halt.is_set()

    # -- tick plumbing -------------------------------------------------------

    def tick(self) -> object:
        """Run one loop body inline on the caller's thread (deterministic
        clock injection for tests/bench). Independent of ``start()``."""
        return self._tick()

    def wake(self) -> None:
        """Cut the current inter-tick pause short."""
        self._wake.set()

    def _period(self) -> float:
        p = self._period_s
        return float(p() if callable(p) else p)

    def _pause(self, seconds: float) -> None:
        """Wait out the period; ``wake()``/``stop()`` end it early."""
        self._wake.wait(seconds)
        self._wake.clear()

    def _run(self) -> None:
        if self._one_shot:
            self._pause(self._period())
            if not self._halt.is_set():
                self._tick()
            return
        if self._immediate and not self._halt.is_set():
            self._tick()
        while not self._halt.is_set():
            self._pause(self._period())
            if self._halt.is_set():
                break
            self._tick()

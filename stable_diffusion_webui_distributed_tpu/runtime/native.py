"""Native runtime components: build-on-first-use C++ via ctypes.

The reference keeps all of its own code in Python and leans on each
worker's CUDA substrate for performance (SURVEY.md §2: zero native code in
the repo). Here the serving path has real host-side work — PNG encoding of
finished images — done natively (native/png_encoder.cpp, zlib) with a
silent PIL fallback when no toolchain is available. The library is
compiled once per machine into ``native/build/`` and memoized.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def _build_library() -> Optional[str]:
    src = os.path.join(_native_dir(), "png_encoder.cpp")
    if not os.path.exists(src):
        return None
    build_dir = os.path.join(_native_dir(), "build")
    out = os.path.join(build_dir, "libsdtpu_png.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(build_dir, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", src, "-lz", "-o", out]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        from stable_diffusion_webui_distributed_tpu.runtime.logging import (
            get_logger,
        )

        get_logger().debug("native png encoder build failed: %s",
                           proc.stderr.decode(errors="replace")[:400])
        return None
    return out


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        path = _build_library()
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.sdtpu_encode_png.restype = ctypes.c_long
            lib.sdtpu_encode_png.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_char_p, ctypes.c_long,
            ]
            _lib = lib
        except OSError:
            _lib_failed = True
        return _lib


def warm_up(background: bool = True) -> None:
    """Build/load the native library ahead of the first request so the
    compile (up to ~2 min cold) never lands on the serving path."""
    if background:
        threading.Thread(target=_get_lib, name="native-warmup",
                         daemon=True).start()
    else:
        _get_lib()


def encode_png(img: np.ndarray, compression_level: int = 6
               ) -> Optional[bytes]:
    """(H, W, 3|4) uint8 -> PNG bytes via the native encoder, or None when
    the native path is unavailable (caller falls back to PIL)."""
    lib = _get_lib()
    if lib is None:
        return None
    if img.dtype != np.uint8 or img.ndim != 3 or img.shape[2] not in (3, 4):
        return None
    img = np.ascontiguousarray(img)
    h, w, c = img.shape
    cap = w * h * (c + 1) + 4096
    buf = ctypes.create_string_buffer(cap)
    n = lib.sdtpu_encode_png(
        img.ctypes.data_as(ctypes.c_char_p), w, h, c, compression_level,
        buf, cap)
    if n < 0:  # undersized buffer: retry at the reported size
        cap = -n
        buf = ctypes.create_string_buffer(cap)
        n = lib.sdtpu_encode_png(
            img.ctypes.data_as(ctypes.c_char_p), w, h, c, compression_level,
            buf, cap)
    if n <= 0:
        return None
    return buf.raw[:n]

"""Seed discipline.

The reference preserves per-image seed continuity across workers by offsetting
each job's starting seed by the number of images assigned before it
(/root/reference/scripts/distributed.py:297-305: ``seed += prior_images`` when
``subseed_strength == 0``, else ``subseed += prior_images``). We reproduce the
same *user-visible contract* — image ``i`` of a batch depends only on
``(seed + i)`` — with JAX PRNG keys: image ``i``'s initial latent noise is
``normal(key(seed + i))``, so any contiguous sub-batch [lo, hi) of a request
can be generated on any shard/slice and produce bitwise-identical latents.

Subseed (variation seed) support mirrors webui semantics exactly
(distributed.py:297-305): the *main* seed advances with the image index only
when ``subseed_strength == 0``; with strength > 0 the base seed is fixed for
every image of the request and only the subseed advances, so a variation
batch explores the neighbourhood of ONE base noise. The init noise is
``slerp(strength, noise(seed [+ i if strength==0]), noise(subseed + i))``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def key_for_image(seed, image_index) -> jax.Array:
    """PRNG key for image ``image_index`` of a request seeded with ``seed``.

    Accepts traced values: seeds stay *data*, not compile-time constants, so
    one compiled pipeline serves every seed.
    """
    seed = jnp.asarray(seed, jnp.uint32)
    idx = jnp.asarray(image_index, jnp.uint32)
    return _key_from_seed(seed + idx)


def _key_from_seed(seed: jax.Array) -> jax.Array:
    # jax.random.PRNGKey is not traceable pre-0.4; key_from_seed via fold_in is.
    base = jax.random.key(0)
    return jax.random.fold_in(base, seed.astype(jnp.uint32))


def noise_for_image(
    seed,
    subseed,
    subseed_strength,
    image_index,
    shape: Sequence[int],
    dtype=jnp.float32,
) -> jax.Array:
    """Initial latent noise for one image, with variation-seed blending.

    With ``subseed_strength == 0`` this is exactly ``N(key(seed+i))``. With
    strength > 0 the base seed does NOT advance with the image index — only
    the subseed does (reference: distributed.py:297-305, mirroring webui's
    ``all_seeds``/``all_subseeds`` arithmetic) — so every image of a
    variation batch perturbs the same base noise.
    """
    strength = jnp.asarray(subseed_strength, dtype)
    idx = jnp.asarray(image_index, jnp.uint32)
    main_idx = jnp.where(strength > 0, jnp.uint32(0), idx)
    main = jax.random.normal(key_for_image(seed, main_idx), shape, dtype)

    def blended(_):
        sub = jax.random.normal(key_for_image(subseed, idx), shape, dtype)
        return slerp(strength, main, sub)

    return jax.lax.cond(strength > 0, blended, lambda _: main, operand=None)


def batch_noise(
    seed,
    subseed,
    subseed_strength,
    start_index,
    batch_size: int,
    shape: Sequence[int],
    dtype=jnp.float32,
    seed_resize: Optional[Tuple[int, int]] = None,
    pin_index: bool = False,
) -> jax.Array:
    """Noise for a contiguous sub-batch starting at global image ``start_index``.

    ``pin_index=True`` gives EVERY image index-0 noise (same-seed batches:
    webui's prompt matrix pins all_seeds so prompts compare at one seed).

    This is the sharding-safe primitive: a job assigned images
    [start, start+batch) calls this and gets latents identical to a
    single-host run — seed-exact gallery merging for free.

    ``seed_resize=(from_h, from_w)`` (latent units) reproduces webui's
    seed-resize: noise (including any variation blend) is drawn at the
    "from" resolution and pasted centered into the target latent — the
    uncovered border stays zero, exactly webui's quirk — so one seed keeps
    its composition across aspect-ratio changes.

    Jitted (seeds/strength/start are data; batch/shape/resize/pin key the
    executable): the eager vmap-of-cond form cost ~1.9 s of host tracing
    per request and, on TPU, dispatched each tiny op through the relay
    (~50 ms/op, PERF.md "relay lessons"). One compiled call per
    (batch, shape) bucket instead.
    """
    # cast seeds on the host: webui seeds span the full uint32 range, which
    # overflows jit's default int32 argument conversion
    return _batch_noise_jit(
        jnp.asarray(seed, jnp.uint32), jnp.asarray(subseed, jnp.uint32),
        subseed_strength, jnp.asarray(start_index, jnp.uint32),
        int(batch_size), tuple(shape), jnp.dtype(dtype),
        tuple(seed_resize) if seed_resize is not None else None,
        bool(pin_index))


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8))
def _batch_noise_jit(seed, subseed, subseed_strength, start_index,
                     batch_size, shape, dtype, seed_resize, pin_index):
    idx = jnp.arange(batch_size, dtype=jnp.uint32) + start_index
    if pin_index:
        idx = jnp.zeros_like(idx)
    if seed_resize is None:
        return jax.vmap(
            lambda i: noise_for_image(seed, subseed, subseed_strength, i, shape, dtype)
        )(idx)

    fh, fw = seed_resize
    from_shape = (fh, fw) + tuple(shape[2:])
    noise = jax.vmap(
        lambda i: noise_for_image(seed, subseed, subseed_strength, i,
                                  from_shape, dtype)
    )(idx)
    return _paste_centered(noise, (batch_size,) + tuple(shape), dtype)


def batch_keys(seed, start_index, batch_size: int,
               pin_index: bool = False) -> jax.Array:
    """Per-image PRNG keys for images [start, start+batch) — the jitted
    companion of :func:`batch_noise` for sampler-noise keys (same eager-
    dispatch concern; ``pin_index`` fixes every key to image 0 for
    variation/same-seed batches)."""
    return _batch_keys_jit(jnp.asarray(seed, jnp.uint32),
                           jnp.asarray(start_index, jnp.uint32),
                           int(batch_size), bool(pin_index))


@functools.partial(jax.jit, static_argnums=(2, 3))
def _batch_keys_jit(seed, start_index, batch_size, pin_index):
    idx = jnp.arange(batch_size, dtype=jnp.uint32) + start_index
    if pin_index:
        idx = jnp.zeros_like(idx)
    return jax.vmap(lambda i: key_for_image(seed, i))(idx)


def _paste_centered(noise: jax.Array, target_shape: Sequence[int],
                    dtype) -> jax.Array:
    """Center-paste (B, fh, fw, C) noise into zeros of (B, H, W, C) —
    cropping when the source is larger (webui create_random_tensors)."""
    _, fh, fw, _ = noise.shape
    _, H, W, _ = target_shape
    dy, dx = (H - fh) // 2, (W - fw) // 2
    ty, sy = max(0, dy), max(0, -dy)
    tx, sx = max(0, dx), max(0, -dx)
    h, w = min(fh, H), min(fw, W)
    out = jnp.zeros(target_shape, dtype)
    return out.at[:, ty:ty + h, tx:tx + w].set(
        noise[:, sy:sy + h, sx:sx + w])


def slerp(t: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """Spherical linear interpolation between noise tensors (webui semantics)."""
    a_flat = a.reshape(-1)
    b_flat = b.reshape(-1)
    a_norm = a_flat / (jnp.linalg.norm(a_flat) + 1e-12)
    b_norm = b_flat / (jnp.linalg.norm(b_flat) + 1e-12)
    dot = jnp.clip(jnp.dot(a_norm, b_norm), -1.0, 1.0)
    theta = jnp.arccos(dot)
    sin_theta = jnp.sin(theta)

    def lerp(_):
        return (1.0 - t) * a + t * b

    def true_slerp(_):
        wa = jnp.sin((1.0 - t) * theta) / sin_theta
        wb = jnp.sin(t * theta) / sin_theta
        return wa * a + wb * b

    return jax.lax.cond(jnp.abs(sin_theta) < 1e-6, lerp, true_slerp, operand=None)

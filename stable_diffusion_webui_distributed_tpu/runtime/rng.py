"""Seed discipline.

The reference preserves per-image seed continuity across workers by offsetting
each job's starting seed by the number of images assigned before it
(/root/reference/scripts/distributed.py:297-305: ``seed += prior_images`` when
``subseed_strength == 0``, else ``subseed += prior_images``). We reproduce the
same *user-visible contract* — image ``i`` of a batch depends only on
``(seed + i)`` — with JAX PRNG keys: image ``i``'s initial latent noise is
``normal(key(seed + i))``, so any contiguous sub-batch [lo, hi) of a request
can be generated on any shard/slice and produce bitwise-identical latents.

Subseed (variation seed) support mirrors webui semantics exactly
(distributed.py:297-305): the *main* seed advances with the image index only
when ``subseed_strength == 0``; with strength > 0 the base seed is fixed for
every image of the request and only the subseed advances, so a variation
batch explores the neighbourhood of ONE base noise. The init noise is
``slerp(strength, noise(seed [+ i if strength==0]), noise(subseed + i))``.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def key_for_image(seed, image_index) -> jax.Array:
    """PRNG key for image ``image_index`` of a request seeded with ``seed``.

    Accepts traced values: seeds stay *data*, not compile-time constants, so
    one compiled pipeline serves every seed.
    """
    seed = jnp.asarray(seed, jnp.uint32)
    idx = jnp.asarray(image_index, jnp.uint32)
    return _key_from_seed(seed + idx)


def _key_from_seed(seed: jax.Array) -> jax.Array:
    # jax.random.PRNGKey is not traceable pre-0.4; key_from_seed via fold_in is.
    base = jax.random.key(0)
    return jax.random.fold_in(base, seed.astype(jnp.uint32))


def noise_for_image(
    seed,
    subseed,
    subseed_strength,
    image_index,
    shape: Sequence[int],
    dtype=jnp.float32,
) -> jax.Array:
    """Initial latent noise for one image, with variation-seed blending.

    With ``subseed_strength == 0`` this is exactly ``N(key(seed+i))``. With
    strength > 0 the base seed does NOT advance with the image index — only
    the subseed does (reference: distributed.py:297-305, mirroring webui's
    ``all_seeds``/``all_subseeds`` arithmetic) — so every image of a
    variation batch perturbs the same base noise.
    """
    strength = jnp.asarray(subseed_strength, dtype)
    idx = jnp.asarray(image_index, jnp.uint32)
    main_idx = jnp.where(strength > 0, jnp.uint32(0), idx)
    main = jax.random.normal(key_for_image(seed, main_idx), shape, dtype)

    def blended(_):
        sub = jax.random.normal(key_for_image(subseed, idx), shape, dtype)
        return slerp(strength, main, sub)

    return jax.lax.cond(strength > 0, blended, lambda _: main, operand=None)


def batch_noise(
    seed,
    subseed,
    subseed_strength,
    start_index,
    batch_size: int,
    shape: Sequence[int],
    dtype=jnp.float32,
) -> jax.Array:
    """Noise for a contiguous sub-batch starting at global image ``start_index``.

    This is the sharding-safe primitive: a job assigned images
    [start, start+batch) calls this and gets latents identical to a
    single-host run — seed-exact gallery merging for free.
    """
    idx = jnp.arange(batch_size, dtype=jnp.uint32) + jnp.asarray(start_index, jnp.uint32)
    return jax.vmap(
        lambda i: noise_for_image(seed, subseed, subseed_strength, i, shape, dtype)
    )(idx)


def slerp(t: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """Spherical linear interpolation between noise tensors (webui semantics)."""
    a_flat = a.reshape(-1)
    b_flat = b.reshape(-1)
    a_norm = a_flat / (jnp.linalg.norm(a_flat) + 1e-12)
    b_norm = b_flat / (jnp.linalg.norm(b_flat) + 1e-12)
    dot = jnp.clip(jnp.dot(a_norm, b_norm), -1.0, 1.0)
    theta = jnp.arccos(dot)
    sin_theta = jnp.sin(theta)

    def lerp(_):
        return (1.0 - t) * a + t * b

    def true_slerp(_):
        wa = jnp.sin((1.0 - t) * theta) / sin_theta
        wb = jnp.sin(t * theta) / sin_theta
        return wa * a + wb * b

    return jax.lax.cond(jnp.abs(sin_theta) < 1e-6, lerp, true_slerp, operand=None)

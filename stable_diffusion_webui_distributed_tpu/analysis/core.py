"""sdtpu-lint core: file walking, AST indexing, and shared resolution helpers.

Everything here is pure-AST (``ast`` + ``tokenize`` only): the analyzer must
run inside tier-1 on a CPU-only box with no JAX device and no imports of the
code under analysis. Rule modules (purity / recompile / envrules / locks)
consume the ``ModuleInfo`` index built here and emit ``Finding`` records.

Conventions recognized in source comments (see ANALYSIS.md):

- ``# guarded-by: <lockname>`` on a ``self.<attr> = ...`` line (or the line
  above it) declares that attribute protected by ``self.<lockname>``.
- ``# sdtpu-lint: traced`` on a ``def`` line (or the line above) marks a
  function as traced-by-JAX even though the jit/scan call site lives in
  another module (e.g. sampler step functions scanned by the engine).
- ``# sdtpu-lint: jitted(static=4)`` on a factory ``def`` marks its return
  value as a jitted callable with the given static argument positions, so
  call sites through a local alias are checked for recompile hazards.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

PACKAGE = "stable_diffusion_webui_distributed_tpu"

#: Rule identifiers (documented in ANALYSIS.md).
RULES = {
    "TP001": "host nondeterminism inside a traced function",
    "TP002": "Python-level branch on a tracer value",
    "TP003": "mutation of closed-over Python state inside a traced function",
    "RC001": "request/env-derived value in a static jit argument",
    "RC002": "traced function closes over a request/env-derived scalar",
    "RC003": "raw precision read outside pipeline/precision.py resolution",
    "EV001": "raw os.environ read outside runtime/config.py",
    "OB001": "time.time() used for a duration on a serving/pipeline/obs path",
    "OB002": "ad-hoc Prometheus metric name outside the central registry",
    "OB003": "journal event literal outside the registered event set",
    "OB004": "alert-rule registration outside the obs/alerts.py registry",
    "OB005": "outbound network call in obs/ outside "
             "federation/notify/stitch",
    "LK001": "guarded attribute accessed without holding its lock",
    "LK002": "guarded-by annotation names an unknown lock",
    "LK003": "lock-acquisition-order inversion",
    "LK004": "blocking device/network/time call while holding a lock",
    "LK005": "lock-order cycle reachable from thread entry points "
             "(potential deadlock)",
    "AT001": "check-then-act across a re-acquired lock "
             "(atomicity violation)",
    "TH001": "raw daemon Thread loop outside runtime/daemon.py",
    "DN001": "donated buffer used after the donating jit call",
    "TP004": "tracer escapes the traced function into self/global state",
    "FL001": "unguarded mutable container in a lock-bearing fleet class",
    "AL001": "allowlist entry expired",
    "AL002": "allowlist entry matched no finding",
    "CA001": "payload hashing or cache-key construction outside "
             "cache/keys.py",
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    symbol: str  # dotted qualname of the enclosing scope, or "<module>"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.symbol}] {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


@dataclass
class FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    qualname: str
    cls: Optional[str]  # immediately-enclosing class name, if any
    parent_qual: str  # qualname of the enclosing scope ("" for module level)


@dataclass
class ModuleInfo:
    path: str  # repo-relative posix path
    tree: ast.Module
    source: str
    comments: Dict[int, str] = field(default_factory=dict)  # line -> text
    aliases: Dict[str, str] = field(default_factory=dict)  # name -> dotted
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)  # qualname -> info
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)

    # -- comment conventions -------------------------------------------------

    def marker(self, line: int, prefix: str) -> Optional[str]:
        """Return the comment payload for ``prefix`` on ``line`` or on a
        standalone comment line directly above (a trailing comment on the
        previous statement's line does NOT attach here)."""
        text = self.comments.get(line, "")
        if prefix in text:
            return text.split(prefix, 1)[1].strip()
        text = self.comments.get(line - 1, "")
        if prefix in text:
            lines = self.source.splitlines()
            if 0 < line - 1 <= len(lines) and \
                    lines[line - 2].lstrip().startswith("#"):
                return text.split(prefix, 1)[1].strip()
        return None

    # -- name resolution -----------------------------------------------------

    def dotted(self, node: ast.AST) -> Optional[Tuple[str, bool]]:
        """Flatten Name/Attribute chains to a canonical dotted path using the
        module's import aliases. Returns (path, resolved) where ``resolved``
        is True when the head name is a known import binding."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = parts[0]
        if head in self.aliases:
            return ".".join([self.aliases[head]] + parts[1:]), True
        return ".".join(parts), False

    def call_name(self, call: ast.Call) -> Tuple[str, bool]:
        got = self.dotted(call.func)
        return got if got is not None else ("", False)


def _collect_comments(source: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string.lstrip("#").strip()
    except tokenize.TokenError:
        pass
    return out


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map every import binding (module-level or nested) to its canonical
    dotted origin: ``import numpy as np`` -> np: numpy; ``from jax import
    random as jrandom`` -> jrandom: jax.random."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                out[bound] = target
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _index_scopes(mod: ModuleInfo) -> None:
    def visit(node: ast.AST, scope: List[str], cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(scope + [child.name])
                mod.funcs[qual] = FuncInfo(child, qual, cls, ".".join(scope))
                visit(child, scope + [child.name], None)
            elif isinstance(child, ast.ClassDef):
                qual = ".".join(scope + [child.name])
                mod.classes[qual] = child
                visit(child, scope + [child.name], child.name)
            else:
                visit(child, scope, cls)

    visit(mod.tree, [], None)


def load_module(abs_path: str, rel_path: str) -> Optional[ModuleInfo]:
    try:
        with open(abs_path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel_path)
    except (OSError, SyntaxError):
        return None
    mod = ModuleInfo(path=rel_path.replace(os.sep, "/"), tree=tree,
                     source=source, comments=_collect_comments(source),
                     aliases=_collect_aliases(tree))
    _index_scopes(mod)
    return mod


def walk_package(root: str, paths: Optional[Iterable[str]] = None
                 ) -> List[ModuleInfo]:
    """Load every .py file under ``root`` (or the explicit ``paths``, which
    may be files or directories, absolute or root-relative)."""
    files: List[Tuple[str, str]] = []
    if paths:
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(ap):
                for dirpath, _dirs, names in os.walk(ap):
                    for n in sorted(names):
                        if n.endswith(".py"):
                            fp = os.path.join(dirpath, n)
                            files.append((fp, os.path.relpath(fp, root)))
            elif ap.endswith(".py"):
                files.append((ap, os.path.relpath(ap, root)))
    else:
        pkg = os.path.join(root, PACKAGE)
        for dirpath, _dirs, names in os.walk(pkg):
            for n in sorted(names):
                if n.endswith(".py"):
                    fp = os.path.join(dirpath, n)
                    files.append((fp, os.path.relpath(fp, root)))
    mods = []
    for abs_path, rel in files:
        mod = load_module(abs_path, rel)
        if mod is not None:
            mods.append(mod)
    return mods


def func_locals(fn: ast.AST) -> set:
    """Parameter and locally-bound names of a function body (no recursion
    into nested defs — their scopes are separate)."""
    names = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)

    def scan(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                names.add(child.name)
                continue  # separate scope
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Name) and isinstance(child.ctx,
                                                          (ast.Store, ast.Del)):
                names.add(child.id)
            elif isinstance(child, (ast.Global, ast.Nonlocal)):
                pass  # declared names are NOT locals
            scan(child)

    body = getattr(fn, "body", None)
    if isinstance(body, list):
        for st in body:
            scan(st)
    elif body is not None:  # Lambda
        scan(fn)
    return names


def declared_nonlocal(fn: ast.AST) -> set:
    """Names declared ``global``/``nonlocal`` directly in this function body
    (not in nested defs)."""
    out = set()

    def scan(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, (ast.Global, ast.Nonlocal)):
                out.update(child.names)
            scan(child)

    for st in getattr(fn, "body", []) or []:
        scan(st)
    return out

"""LK005: whole-program lock-order / deadlock analysis rooted at thread
entry points.

LK003 (analysis/locks.py) reports any cycle in the package's lock-
acquisition digraph. This pass is the stronger, evidence-carrying form
the concurrency tier gates on: it walks the call graph from every
**thread entry point** — functions handed to ``threading.Thread(target=
...)`` (including nested closures and ``self.method`` references),
``run`` methods of ``threading.Thread`` subclasses, tick/fire callables
handed to ``runtime/daemon.py``'s StoppableDaemon, and HTTP handler
methods (``do_GET``/``do_POST``/...; each request runs on its own
server thread) — and reports a cycle only when every conflicting
acquisition is actually reachable from some entry, **with the
acquisition path for each direction in the finding**: which entry, by
which call chain, takes lock B while holding lock A, and which entry
does the reverse. That is the evidence a reviewer needs to judge a
deadlock report without re-deriving the graph by hand.

Two findings families:

- ``potential deadlock`` — a cycle in the entry-rooted acquisition
  graph, with both (all) acquisition paths spelled out.
- ``stale lockorder annotation`` — a ``# sdtpu-lint: lockorder a<b``
  that suppresses no contradicted edge. Annotations are the escape
  hatch for static-name collapse (two instances of one class ordered by
  identity at runtime); a stale one is rot and gets flagged, the same
  anti-rot discipline as AL002.

Honest limits: entry detection resolves ``target=``/``tick=``/``fire=``
references through the same conservative machinery as the rest of the
analyzer — an entry it cannot resolve contributes nothing, so the pass
under-reports rather than guessing. Cycles among locks touched only
from unresolved entries are still caught by LK003 (unrooted, no path
evidence).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import callgraph, locks
from .core import Finding, FuncInfo, ModuleInfo

#: HTTP-handler method names: each runs on its own server thread
_HANDLER_NAMES = {"do_GET", "do_POST", "do_PUT", "do_DELETE", "do_PATCH",
                  "do_HEAD"}


def _name_target(mod: ModuleInfo, info: FuncInfo, name: str
                 ) -> Optional[str]:
    """Resolve a bare-name thread target (nested def / sibling /
    module-level function) to its in-module qualname."""
    scope = info.qualname
    while True:
        cand = f"{scope}.{name}" if scope else name
        if cand in mod.funcs:
            return cand
        if "." not in scope:
            break
        scope = scope.rsplit(".", 1)[0]
    return name if name in mod.funcs else None


def _attr_target(mod: ModuleInfo, info: FuncInfo, prog: callgraph.Program,
                 node: ast.Attribute,
                 local: Dict[str, str]) -> Optional[str]:
    """Resolve an ``obj.method`` thread target to an in-module qualname
    via the object's inferred class."""
    base_t = prog.expr_type(mod, info, node.value, local)
    if base_t is None:
        return None
    for qual, fi in mod.funcs.items():
        if fi.cls == base_t and qual.split(".")[-1] == node.attr:
            return qual
    return None


def _callable_arg(call: ast.Call, kw: str, pos: int) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def entry_points(modules: List[ModuleInfo], prog: callgraph.Program
                 ) -> Dict[str, str]:
    """Dotted qualname -> human label for every thread entry point."""
    entries: Dict[str, str] = {}

    def add(mod: ModuleInfo, qual: Optional[str], label: str) -> None:
        if qual is not None and qual in mod.funcs:
            entries.setdefault(
                f"{callgraph.module_name(mod.path)}.{qual}", label)

    for mod in modules:
        # threading.Thread subclasses: run() is the entry
        for clsqual, cls in mod.classes.items():
            for base in cls.bases:
                got = mod.dotted(base)
                if got is not None and got[0].endswith("threading.Thread"):
                    add(mod, f"{clsqual}.run", f"{cls.name}.run (Thread "
                                               f"subclass)")
            for qual, fi in mod.funcs.items():
                if fi.cls == cls.name and \
                        qual.split(".")[-1] in _HANDLER_NAMES:
                    add(mod, qual, f"{qual} (HTTP handler thread)")
        for qual, info in mod.funcs.items():
            if not isinstance(info.node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local = prog.local_types(mod, info)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                name, _res = mod.call_name(node)
                tail = name.split(".")[-1]
                target: Optional[ast.AST] = None
                label = ""
                if name.endswith("threading.Thread") or name == "Thread":
                    target = _callable_arg(node, "target", -1)
                    label = "Thread target"
                elif tail == "StoppableDaemon":
                    target = _callable_arg(node, "tick", 1)
                    label = "StoppableDaemon tick"
                elif tail == "one_shot":
                    target = _callable_arg(node, "fire", 2)
                    label = "StoppableDaemon one-shot"
                if target is None:
                    continue
                if isinstance(target, ast.Name):
                    add(mod, _name_target(mod, info, target.id),
                        f"{label} from {qual}")
                elif isinstance(target, ast.Attribute):
                    add(mod, _attr_target(mod, info, prog, target, local),
                        f"{label} from {qual}")
    return entries


def _reach(entries: Dict[str, str], prog: callgraph.Program
           ) -> Dict[str, Tuple[str, Optional[str]]]:
    """BFS the call graph from every entry: qualname -> (entry, parent)."""
    reach: Dict[str, Tuple[str, Optional[str]]] = {}
    frontier: List[str] = []
    for e in sorted(entries):
        if e not in reach:
            reach[e] = (e, None)
            frontier.append(e)
    while frontier:
        cur = frontier.pop(0)
        entry = reach[cur][0]
        for tgt in sorted(prog.callees(cur)):
            if tgt not in reach:
                reach[tgt] = (entry, cur)
                frontier.append(tgt)
    return reach


def _chain(reach: Dict[str, Tuple[str, Optional[str]]], qual: str
           ) -> str:
    parts = [qual]
    seen = {qual}
    while True:
        parent = reach[parts[0]][1]
        if parent is None or parent in seen:
            break
        parts.insert(0, parent)
        seen.add(parent)
    return " -> ".join(parts)


def check(modules: List[ModuleInfo],
          prog: Optional[callgraph.Program] = None,
          base: Optional[locks.LockAnalysis] = None) -> List[Finding]:
    if prog is None:
        prog = callgraph.build(modules)
    if base is None:
        base = locks.analyze(modules, prog)
    findings: List[Finding] = []

    # stale annotations: declared orders that suppressed nothing
    for a, b, path, line in base.declared:
        if (a, b) not in base.suppressed:
            findings.append(Finding(
                "LK005", path, line, "<module>",
                f"lockorder annotation '{a}<{b}' contradicts no derived "
                f"edge — stale; remove it (annotations may only suppress "
                f"a real static inversion that a test exercises)"))

    entries = entry_points(modules, prog)
    if not entries:
        return findings
    reach = _reach(entries, prog)

    # cycles where every conflicting acquisition is entry-reachable
    edges = base.edges
    seen_cycles: Set[frozenset] = set()

    def path_of(a: str, b: str) -> Optional[str]:
        src = base.edge_src.get((a, b))
        if src is None:
            return None
        path, line, _sym, qual = src
        if qual not in reach:
            return None
        entry = reach[qual][0]
        return (f"[{entries[entry]}] {_chain(reach, qual)} acquires "
                f"{b} while holding {a} at {path}:{line}")

    def report(cyc: List[str]) -> None:
        pairs = list(zip(cyc, cyc[1:]))
        paths = [path_of(a, b) for a, b in pairs]
        if any(p is None for p in paths):
            return  # some direction unreachable from entries: LK003 only
        src = base.edge_src[pairs[-1]]
        evidence = "; ".join(f"path {i + 1}: {p}"
                             for i, p in enumerate(paths))
        findings.append(Finding(
            "LK005", src[0], src[1], src[2],
            "potential deadlock: " + " -> ".join(cyc) + "; " + evidence +
            " — acquire in one global order (or, only for an order a "
            "test exercises, annotate '# sdtpu-lint: lockorder a<b')"))

    def dfs(node: str, stack: List[str], on_stack: Set[str],
            visited: Set[str]) -> None:
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(edges.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    report(cyc)
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(edges):
        if node not in visited:
            dfs(node, [], set(), visited)
    return findings

"""EV001: raw environment reads outside runtime/config.py.

Scattered ``os.environ.get(...)`` sites each grow their own parse/fallback
logic (three warn-and-default copies existed before this analyzer landed).
All env knobs go through the ``env_*`` helpers in runtime/config.py: one
warn-and-default policy, one grep-able inventory of every SDTPU_* knob, and
one place the recompile rules treat as an env taint source.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleInfo

#: The only module allowed to touch os.environ.
SANCTIONED = ("runtime/config.py",)


def _enclosing_symbol(mod: ModuleInfo, line: int) -> str:
    best = "<module>"
    best_span = None
    for qual, info in mod.funcs.items():
        node = info.node
        start = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", start)
        if start <= line <= end:
            span = end - start
            if best_span is None or span < best_span:
                best, best_span = qual, span
    return best


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if mod.path.endswith(SANCTIONED):
            continue
        for node in ast.walk(mod.tree):
            hit = None
            if isinstance(node, ast.Attribute):
                got = mod.dotted(node)
                if got is not None and got[1] and got[0] == "os.environ":
                    hit = "os.environ"
            elif isinstance(node, ast.Call):
                name, resolved = mod.call_name(node)
                if resolved and name == "os.getenv":
                    hit = "os.getenv"
            if hit is not None:
                line = node.lineno
                findings.append(Finding(
                    "EV001", mod.path, line, _enclosing_symbol(mod, line),
                    f"raw {hit} read; use the env_* helpers in "
                    f"runtime/config.py (warn-and-default policy lives "
                    f"there)"))
    return findings

"""AT001: interprocedural check-then-act atomicity-violation detection.

The lock rules (LK001-LK005) verify that guarded state is only touched
with the right lock held. That is necessary but not sufficient: the
quota-refund and preempt-latch bugs were both *atomicity* violations —
every individual access held the lock, but a value read under one
critical section leaked into a decision or a write made under a
**re-acquired** critical section, and the world had moved in between::

    with self._lock:
        bal = self._balance[t]     # read under session 1
    if bal < cost:                 # decision on the (now stale) read
        return False
    with self._lock:
        self._balance[t] = bal - cost   # write under session 2: races

This pass tracks, per function, which locals carry a guarded-field read
and from which lock *session* (each ``with lock:`` block is a distinct
session). A write to a guarded field under a later session of the same
lock fires when

- the written value is computed from a read taken under an earlier
  session of that lock on the same object (stale-value write), or
- a branch dominating the write tested such a stale read and the write
  touches the *same* field (check-then-act via control flow).

It is interprocedural through locked accessors: a method that returns a
guarded field under its own lock taints its call result, and a method
that writes a guarded field from a parameter under its own lock is a
guarded write — so ``x = obj.used(); ...; obj.set_used(x + n)`` fires
just like the inline form.

Suppression (the sanctioned fix shape): re-validating the field inside
the second critical section — reading it fresh in a dominating test
under the *current* session, or computing the new value from a fresh
read — silences the finding.

Honest limits: sessions are numbered per ``with`` statement, so a loop
re-entering one ``with`` twice is a single session (a stale carry
across iterations of the same block is missed); container mutations via
method calls (``.append``/``.pop``) are not writes; coupled-field
evidence requires the written value to carry the stale read (branch-
only coupling across *different* fields is not reported, by design —
it drowned real findings in false positives on the quota paths).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from . import callgraph
from .core import Finding, FuncInfo, ModuleInfo
from .locks import ClassLocks, _collect_classes

__all__ = ["check"]


@dataclass(frozen=True)
class _Taint:
    obj: str        # dotted base expression ("self", "acct", "self.quota")
    field: str      # guarded attribute name
    lock: str       # qualified "Class.attr" lock
    session: int    # acquisition session the read happened under
    line: int       # read site


def _dotted_str(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


# -- locked accessor summaries ------------------------------------------------

def _accessor_summaries(modules: List[ModuleInfo], prog: callgraph.Program,
                        classes: Dict[str, ClassLocks]
                        ) -> Tuple[Dict[Tuple[str, str], Tuple[str, str]],
                                   Dict[Tuple[str, str], Tuple[str, str]]]:
    """(reads, writes): ``(Class, method) -> (lock, field)`` for methods
    that return / assign a guarded field under their own lock."""
    reads: Dict[Tuple[str, str], Tuple[str, str]] = {}
    writes: Dict[Tuple[str, str], Tuple[str, str]] = {}
    for mod in modules:
        for qual, info in mod.funcs.items():
            cls = classes.get(info.cls or "")
            if cls is None or not isinstance(info.node, ast.FunctionDef):
                continue
            name = info.node.name
            if name == "__init__":
                continue
            params = {a.arg for a in info.node.args.args[1:]}
            for node in ast.walk(info.node):
                if not isinstance(node, ast.With):
                    continue
                lock = None
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute) and \
                            isinstance(ctx.value, ast.Name) and \
                            ctx.value.id == "self" and \
                            ctx.attr in cls.locks:
                        lock = f"{info.cls}.{ctx.attr}"
                if lock is None:
                    continue
                for st in ast.walk(node):
                    if isinstance(st, ast.Return) and st.value is not None:
                        for sub in ast.walk(st.value):
                            if isinstance(sub, ast.Attribute) and \
                                    isinstance(sub.value, ast.Name) and \
                                    sub.value.id == "self" and \
                                    sub.attr in cls.guarded and \
                                    cls.guarded[sub.attr][0] == \
                                    lock.split(".")[1]:
                                reads.setdefault((info.cls, name),
                                                 (lock, sub.attr))
                    if isinstance(st, ast.Assign):
                        tgt = st.targets[0] if len(st.targets) == 1 else None
                        attr = _written_attr(tgt)
                        if attr is None:
                            continue
                        base, fieldname = attr
                        if base != "self" or fieldname not in cls.guarded \
                                or cls.guarded[fieldname][0] != \
                                lock.split(".")[1]:
                            continue
                        names = {n.id for n in ast.walk(st.value)
                                 if isinstance(n, ast.Name)}
                        if names & params:
                            writes.setdefault((info.cls, name),
                                              (lock, fieldname))
    return reads, writes


def _written_attr(target: Optional[ast.AST]
                  ) -> Optional[Tuple[str, str]]:
    """(base-dotted, field) for an attribute or container-slot write
    target (``self.f = ...`` / ``self.f[k] = ...``)."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute):
        base = _dotted_str(target.value)
        if base is not None:
            return base, target.attr
    return None


# -- per-function traversal ---------------------------------------------------

class _AtomScan:
    def __init__(self, mod: ModuleInfo, info: FuncInfo, qual: str,
                 prog: callgraph.Program, classes: Dict[str, ClassLocks],
                 reads: Dict[Tuple[str, str], Tuple[str, str]],
                 writes: Dict[Tuple[str, str], Tuple[str, str]]):
        self.mod = mod
        self.info = info
        self.qual = qual
        self.prog = prog
        self.classes = classes
        self.acc_reads = reads
        self.acc_writes = writes
        self.local_types = prog.local_types(mod, info)
        self.findings: List[Finding] = []
        self.taints: Dict[str, _Taint] = {}
        self._session = 0
        #: (lock, session) -> fields read fresh in a dominating test
        self._validated: Dict[Tuple[str, int], Set[str]] = {}
        self._reported: Set[int] = set()

    # -- resolution ----------------------------------------------------------

    def _guard_of(self, node: ast.Attribute
                  ) -> Optional[Tuple[str, str, str]]:
        """(obj, field, lock) when ``node`` reads/writes a guarded
        attribute of a known class."""
        owner = self.prog.expr_type(self.mod, self.info, node.value,
                                    self.local_types)
        if owner is None:
            return None
        cl = self.classes.get(owner)
        if cl is None or node.attr not in cl.guarded:
            return None
        base = _dotted_str(node.value)
        if base is None:
            return None
        lockname, _line = cl.guarded[node.attr]
        return base, node.attr, f"{owner}.{lockname}"

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute):
            owner = self.prog.expr_type(self.mod, self.info, expr.value,
                                        self.local_types)
            if owner is not None:
                cl = self.classes.get(owner)
                if cl is not None and expr.attr in cl.locks:
                    return f"{owner}.{expr.attr}"
        return None

    def _guarded_reads(self, expr: ast.AST, held: Dict[str, int]
                       ) -> List[_Taint]:
        out = []
        for sub in ast.walk(expr):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.ctx, ast.Load):
                got = self._guard_of(sub)
                if got is not None and got[2] in held:
                    out.append(_Taint(got[0], got[1], got[2],
                                      held[got[2]], sub.lineno))
        return out

    def _stale_refs(self, expr: ast.AST, held: Dict[str, int]
                    ) -> List[_Taint]:
        """Taints referenced by ``expr`` that came from a lock session
        other than the current one (or from a locked accessor call)."""
        out = []
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                t = self.taints.get(sub.id)
                if t is not None and held.get(t.lock) != t.session:
                    out.append(t)
        return out

    # -- traversal -----------------------------------------------------------

    def run(self) -> None:
        self._body(getattr(self.info.node, "body", []), {}, ())

    def _body(self, stmts: List[ast.stmt], held: Dict[str, int],
              btaints: Tuple[_Taint, ...]) -> None:
        for st in stmts:
            self._stmt(st, held, btaints)

    def _stmt(self, st: ast.stmt, held: Dict[str, int],
              btaints: Tuple[_Taint, ...]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate thread/scope: sessions don't carry over
        if isinstance(st, (ast.With, ast.AsyncWith)):
            newly = dict(held)
            for item in st.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self._session += 1
                    newly[lock] = self._session
            self._body(st.body, newly, btaints)
            return
        if isinstance(st, ast.Try):
            self._body(st.body, held, btaints)
            for h in st.handlers:
                self._body(h.body, held, btaints)
            self._body(st.orelse, held, btaints)
            self._body(st.finalbody, held, btaints)
            return
        if isinstance(st, (ast.If, ast.While)):
            # fresh reads in the test re-validate for the current session
            for t in self._guarded_reads(st.test, held):
                self._validated.setdefault(
                    (t.lock, t.session), set()).add(t.field)
            extra = tuple(self._stale_refs(st.test, held))
            self._check_calls(st.test, held, btaints)
            self._body(st.body, held, btaints + extra)
            self._body(st.orelse, held, btaints + extra)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._body(st.body, held, btaints)
            self._body(st.orelse, held, btaints)
            return
        if isinstance(st, ast.Assign):
            self._assign(st, held, btaints)
            return
        if isinstance(st, ast.AugAssign):
            # the in-place read happens at write time under the current
            # session — fresh by construction, never check-then-act
            return
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                self._setter_call(node, held)

    def _check_calls(self, expr: ast.AST, held: Dict[str, int],
                     btaints: Tuple[_Taint, ...]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._setter_call(node, held)

    def _assign(self, st: ast.Assign, held: Dict[str, int],
                btaints: Tuple[_Taint, ...]) -> None:
        # 1) guarded-field writes under a (re-)acquired lock
        for target in st.targets:
            self._check_write(target, st.value, held, btaints, st.lineno)
        for node in ast.walk(st.value):
            if isinstance(node, ast.Call):
                self._setter_call(node, held)
        # 2) taint bookkeeping for name targets
        if len(st.targets) == 1 and isinstance(st.targets[0], ast.Name):
            name = st.targets[0].id
            reads = self._guarded_reads(st.value, held)
            if reads:
                self.taints[name] = reads[0]
                return
            acc = self._accessor_read(st.value)
            if acc is not None:
                self.taints[name] = acc
                return
            carried = self._stale_refs(st.value, held)
            fresh = [self.taints[n.id] for n in ast.walk(st.value)
                     if isinstance(n, ast.Name) and n.id in self.taints]
            if fresh:
                self.taints[name] = fresh[0]
            else:
                self.taints.pop(name, None)
            del carried

    def _accessor_read(self, expr: ast.AST) -> Optional[_Taint]:
        """``x = obj.used()`` through a locked read accessor taints x
        with a fresh pseudo-session (always distinct from any with-
        session in this function)."""
        if not (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)):
            return None
        owner = self.prog.expr_type(self.mod, self.info, expr.func.value,
                                    self.local_types)
        if owner is None:
            return None
        summary = self.acc_reads.get((owner, expr.func.attr))
        if summary is None:
            return None
        base = _dotted_str(expr.func.value)
        if base is None:
            return None
        lock, fieldname = summary
        self._session += 1
        return _Taint(base, fieldname, lock, self._session, expr.lineno)

    def _check_write(self, target: ast.AST, value: ast.AST,
                     held: Dict[str, int], btaints: Tuple[_Taint, ...],
                     line: int) -> None:
        got = _written_attr(target)
        if got is None:
            return
        base, fieldname = got
        if isinstance(target, ast.Subscript):
            attr_node = target.value
        else:
            attr_node = target
        guard = self._guard_of(attr_node) if \
            isinstance(attr_node, ast.Attribute) else None
        if guard is None:
            return
        _obj, _field, lock = guard
        session = held.get(lock)
        if session is None:
            return  # unlocked write is LK001's finding, not ours
        if fieldname in self._validated.get((lock, session), set()):
            return  # re-validated inside this critical section
        fresh_fields = {t.field for t in self._guarded_reads(value, held)
                        if t.lock == lock and t.session == session
                        and t.obj == base}
        if fieldname in fresh_fields:
            return  # value recomputed from a fresh read
        stale = [t for t in self._stale_refs(value, held)
                 if t.lock == lock and t.obj == base]
        for t in stale:
            self._report(line, t, fieldname, lock, via="value")
            return
        for t in btaints:
            if t.lock == lock and t.obj == base and t.field == fieldname \
                    and t.session != session:
                self._report(line, t, fieldname, lock, via="branch")
                return

    def _setter_call(self, call: ast.Call, held: Dict[str, int]) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        owner = self.prog.expr_type(self.mod, self.info, call.func.value,
                                    self.local_types)
        if owner is None:
            return
        summary = self.acc_writes.get((owner, call.func.attr))
        if summary is None:
            return
        base = _dotted_str(call.func.value)
        if base is None:
            return
        lock, fieldname = summary
        for arg in list(call.args) + [k.value for k in call.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and sub.id in self.taints:
                    t = self.taints[sub.id]
                    if t.lock == lock and t.obj == base:
                        self._report(call.lineno, t, fieldname, lock,
                                     via="accessor")
                        return

    def _symbol(self) -> str:
        if self.info.cls:
            return f"{self.info.cls}.{self.info.node.name}"  # type: ignore[attr-defined]
        return self.info.qualname

    def _report(self, line: int, taint: _Taint, fieldname: str,
                lock: str, via: str) -> None:
        if line in self._reported:
            return
        self._reported.add(line)
        what = {"value": "is written back",
                "branch": "gates this write",
                "accessor": "flows into a locked write accessor"}[via]
        same = taint.field == fieldname
        coupled = "" if same else \
            f" (coupled field '{fieldname}' under the same lock)"
        self.findings.append(Finding(
            "AT001", self.mod.path, line, self._symbol(),
            f"check-then-act: '{taint.obj}.{taint.field}' read under "
            f"{lock} at line {taint.line} {what} under a re-acquired "
            f"{lock}{coupled} — the value may be stale; do the read, "
            f"check, and write in one critical section (or re-validate "
            f"the field inside this one)"))


def check(modules: List[ModuleInfo],
          prog: Optional[callgraph.Program] = None) -> List[Finding]:
    if prog is None:
        prog = callgraph.build(modules)
    classes = _collect_classes(modules)
    reads, writes = _accessor_summaries(modules, prog, classes)
    findings: List[Finding] = []
    for mod in modules:
        dotted = callgraph.module_name(mod.path)
        for qual, info in mod.funcs.items():
            if not isinstance(info.node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if info.parent_qual and info.parent_qual in mod.funcs:
                continue  # nested defs run on their own thread/time
            if info.cls and info.node.name == "__init__":
                continue  # construction is single-threaded
            scan = _AtomScan(mod, info, f"{dotted}.{qual}", prog,
                             classes, reads, writes)
            scan.run()
            findings.extend(scan.findings)
    return findings

"""TH001: raw daemon Thread loops must live on runtime/daemon.py.

Five subsystems grew the same hand-rolled shape — ``threading.Thread(
target=..., daemon=True)`` around a ``while not halt:`` loop, with a
private ``_halt`` Event and ad-hoc stop/join conventions. Each copy is
a fresh chance at the classic footguns: forgetting to clear the halt
flag on restart, joining without a timeout, or (worst) naming the flag
``_stop`` and shadowing ``threading.Thread._stop``, which ``join()``
calls internally — a latent hang that only fires on interpreter
shutdown ordering. ``runtime/daemon.py``'s StoppableDaemon is the one
blessed implementation (composition over Thread, uniform
start/stop/join, tick injection for tests); this rule keeps new loops
from growing off it.

Flags:

- a ``threading.Thread(..., daemon=True)`` construction whose resolved
  ``target`` contains a ``while`` loop (a worker *loop*, not a one-off
  background task — single-shot helpers stay legal);
- a ``threading.Thread`` subclass whose ``run()`` contains a ``while``
  loop, daemon or not (subclassing Thread is how the ``_stop`` shadow
  happens).

``runtime/daemon.py`` itself is exempt — it is the implementation.
Honest limit: a target the resolver cannot follow (dynamic dispatch,
``functools.partial``) is not flagged; the rule under-reports rather
than guessing.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from . import callgraph
from .core import Finding, ModuleInfo
from .lockorder import _attr_target, _callable_arg, _name_target

__all__ = ["check"]

_EXEMPT = "runtime/daemon.py"


def _has_while(node: ast.AST) -> bool:
    return any(isinstance(n, ast.While) for n in ast.walk(node))


def _daemon_true(call: ast.Call) -> bool:
    for k in call.keywords:
        if k.arg == "daemon":
            return isinstance(k.value, ast.Constant) and \
                k.value.value is True
    return False


def check(modules: List[ModuleInfo],
          prog: Optional[callgraph.Program] = None) -> List[Finding]:
    if prog is None:
        prog = callgraph.build(modules)
    findings: List[Finding] = []
    for mod in modules:
        if mod.path.endswith(_EXEMPT):
            continue
        # Thread subclasses with a run() loop
        for clsqual, cls in mod.classes.items():
            if not any((got := mod.dotted(base)) is not None and
                       got[0].endswith("threading.Thread")
                       for base in cls.bases):
                continue
            run_info = mod.funcs.get(f"{clsqual}.run")
            if run_info is not None and _has_while(run_info.node):
                findings.append(Finding(
                    "TH001", mod.path, cls.lineno, clsqual,
                    f"{cls.name} subclasses threading.Thread around a "
                    f"run() loop — use runtime/daemon.py StoppableDaemon "
                    f"(uniform start/stop/join, tick injection, no "
                    f"Thread private-attribute shadowing)"))
        # raw daemon Thread(...) constructions with a looping target
        for qual, info in mod.funcs.items():
            if not isinstance(info.node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local = prog.local_types(mod, info)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                name, _res = mod.call_name(node)
                if not (name.endswith("threading.Thread")
                        or name == "Thread"):
                    continue
                if not _daemon_true(node):
                    continue
                target = _callable_arg(node, "target", -1)
                tqual: Optional[str] = None
                if isinstance(target, ast.Name):
                    tqual = _name_target(mod, info, target.id)
                elif isinstance(target, ast.Attribute):
                    tqual = _attr_target(mod, info, prog, target, local)
                if tqual is None:
                    continue
                tinfo = mod.funcs.get(tqual)
                if tinfo is not None and _has_while(tinfo.node):
                    findings.append(Finding(
                        "TH001", mod.path, node.lineno, qual,
                        f"raw daemon Thread around looping target "
                        f"'{tqual}' — use runtime/daemon.py "
                        f"StoppableDaemon instead of a hand-rolled "
                        f"halt-flag loop"))
    return findings

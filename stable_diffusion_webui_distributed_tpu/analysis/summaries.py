"""Per-function taint summaries, propagated to a fixed point.

The intra-procedural recompile pass (``recompile.py``) sees taint born and
consumed inside one function. This module gives it eyes across calls: for
every function in the package it computes a small transfer summary —

- ``param_to_return``: which positional params flow into the return value
  (``def raw_steps(payload): return payload.steps`` -> {0});
- ``returns_taint``: the return value is request/env-derived regardless of
  what the caller passes (the body reads ``os.environ`` or an attribute
  off its own payload-named param);
- ``sanitizes``: every return passes through the bucketer ladder or a
  constant clamp, so call results are clean whatever went in;
- ``param_to_sink``: which params reach a **static** jit argument inside
  the body (directly, or through further calls) — the caller-side half of
  an interprocedural RC001.

Summaries are computed per function from the AST, then iterated to a fixed
point over the program call graph so taint laundered through helper chains
(``a -> b -> c``, across modules) still resolves. ``recompile.py`` consults
the table at call sites: a call to a function whose summary returns taint
makes the result tainted; a tainted argument in a ``param_to_sink``
position is an RC001 at the call site.

Everything is positional-param based (keywords map by name); *args/**kwargs
and container flows are out of scope — documented under-reporting, same
bias as the rest of the analyzer.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import Program
from .core import FuncInfo, ModuleInfo
from .purity import TRACE_FNS, _resolve_func, _static_positions

#: origin markers: ("param", i) | ("env",) | ("payload", "<p.attr>")
Origin = Tuple


@dataclass
class FuncSummary:
    qualname: str
    params: List[str] = field(default_factory=list)
    param_to_return: Set[int] = field(default_factory=set)
    returns_taint: Optional[str] = None
    sanitizes: bool = False
    param_to_sink: Dict[int, str] = field(default_factory=dict)

    def key(self) -> Tuple:
        return (frozenset(self.param_to_return), self.returns_taint,
                self.sanitizes, frozenset(self.param_to_sink.items()))

    def to_dict(self) -> Dict:
        return {"params": self.params,
                "param_to_return": sorted(self.param_to_return),
                "returns_taint": self.returns_taint,
                "sanitizes": self.sanitizes,
                "param_to_sink": {str(k): v
                                  for k, v in self.param_to_sink.items()}}

    @classmethod
    def from_dict(cls, qualname: str, d: Dict) -> "FuncSummary":
        return cls(qualname, list(d.get("params", [])),
                   set(d.get("param_to_return", [])),
                   d.get("returns_taint"),
                   bool(d.get("sanitizes", False)),
                   {int(k): v
                    for k, v in d.get("param_to_sink", {}).items()})


def _abs_why(origins: Set[Origin]) -> Optional[str]:
    """Caller-independent taint reason carried by an origin set."""
    for o in origins:
        if o[0] == "env":
            return "environment read"
        if o[0] == "payload":
            return o[1]
        if o[0] == "abs":
            return o[1]
    return None


def _param_indices(origins: Set[Origin]) -> Set[int]:
    return {o[1] for o in origins if o[0] == "param"}


class Summaries:
    """The summary table plus call-site resolution helpers."""

    def __init__(self, prog: Program,
                 seed: Optional[Dict[str, Dict]] = None,
                 dirty_paths: Optional[Set[str]] = None):
        """``seed`` (qualname -> serialized FuncSummary) + ``dirty_paths``
        enable incremental recomputation: functions in clean modules keep
        their seeded summaries; only functions in dirty modules iterate.
        Callers must include import-dependents of every changed module in
        ``dirty_paths`` or clean summaries could go stale."""
        self.prog = prog
        self.table: Dict[str, FuncSummary] = {}
        self._local_types: Dict[str, Dict[str, str]] = {}
        self._compute(seed or {}, dirty_paths)

    # -- call-site API (used by recompile.py) --------------------------------

    def callee(self, mod: ModuleInfo, info: FuncInfo, call: ast.Call
               ) -> Optional[Tuple[FuncSummary, int]]:
        """(summary, arg offset) for a resolvable call, else None. The
        offset is 1 for ``obj.method(...)`` calls whose target's first
        param is self/cls — caller arg ``i`` maps to callee param
        ``i + offset``."""
        qual = f"{callgraph_module(mod)}.{info.qualname}"
        cached = self._local_types.get(qual)
        tgt = self.prog.resolve_call(mod, info, call, cached)
        if tgt is None:
            return None
        summ = self.table.get(tgt)
        if summ is None:
            return None
        offset = 0
        if isinstance(call.func, ast.Attribute) and \
                summ.params[:1] and summ.params[0] in ("self", "cls"):
            offset = 1
        return summ, offset

    # -- computation ---------------------------------------------------------

    def _compute(self, seed: Dict[str, Dict],
                 dirty_paths: Optional[Set[str]]) -> None:
        entries = []
        for qual, (mod, info) in self.prog.funcs.items():
            if not isinstance(info.node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in (info.node.args.posonlyargs
                                      + info.node.args.args)]
            clean = dirty_paths is not None and mod.path not in dirty_paths
            if clean and qual in seed:
                self.table[qual] = FuncSummary.from_dict(qual, seed[qual])
            else:
                self.table[qual] = FuncSummary(qual, params)
                clean = False
            self._local_types[qual] = self.prog.local_types(mod, info)
            if not clean:
                entries.append((qual, mod, info))
        for _round in range(10):
            changed = False
            for qual, mod, info in entries:
                new = self._summarize(qual, mod, info)
                if new.key() != self.table[qual].key():
                    self.table[qual] = new
                    changed = True
            if not changed:
                break

    def _summarize(self, qual: str, mod: ModuleInfo, info: FuncInfo
                   ) -> FuncSummary:
        from .recompile import (PAYLOAD_PARAMS, _is_env_read, _jitted_marker,
                                _sanitized)

        fn = info.node
        params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
        summ = FuncSummary(qual, params)
        payload_params = {p for p in params if p in PAYLOAD_PARAMS}
        origins: Dict[str, Set[Origin]] = {
            p: {("param", i)} for i, p in enumerate(params)}
        #: local name -> jit static positions (same detection as recompile)
        jit_statics: Dict[str, Set[int]] = {}
        return_origins: Set[Origin] = set()
        returns_seen = 0
        returns_sanitized = 0

        def call_summary(call: ast.Call) -> Optional[Tuple[FuncSummary, int]]:
            tgt = self.prog.resolve_call(mod, info, call,
                                         self._local_types.get(qual))
            if tgt is None or tgt == qual:
                return None
            got = self.table.get(tgt)
            if got is None:
                return None
            offset = 0
            if isinstance(call.func, ast.Attribute) and \
                    got.params[:1] and got.params[0] in ("self", "cls"):
                offset = 1
            return got, offset

        def eval_origins(expr: ast.AST) -> Set[Origin]:
            if isinstance(expr, ast.Call):
                if _sanitized(mod, expr):
                    return set()
                got = call_summary(expr)
                if got is not None:
                    csumm, offset = got
                    if csumm.sanitizes:
                        return set()
                    out: Set[Origin] = set()
                    if csumm.returns_taint:
                        out.add(("abs", csumm.returns_taint))
                    for j, arg in enumerate(expr.args):
                        if j + offset in csumm.param_to_return:
                            out |= eval_origins(arg)
                    for kw in expr.keywords:
                        if kw.arg in csumm.params and \
                                csumm.params.index(kw.arg) in \
                                csumm.param_to_return:
                            out |= eval_origins(kw.value)
                    return out
            if _is_env_read(mod, expr):
                return {("env",)}
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name):
                base = expr.value.id
                if base in payload_params:
                    return {("payload", f"{base}.{expr.attr}"),
                            ("param", params.index(base))}
            if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load):
                return set(origins.get(expr.id, set()))
            out = set()
            for child in ast.iter_child_nodes(expr):
                out |= eval_origins(child)
            return out

        def check_sinks(call: ast.Call) -> None:
            # direct: call of a local jit binding with static positions
            statics: Optional[Set[int]] = None
            sink_offset = 0
            if isinstance(call.func, ast.Name) and \
                    call.func.id in jit_statics:
                statics = jit_statics[call.func.id]
            if statics is not None:
                for i, arg in enumerate(call.args):
                    if i in statics:
                        for pi in _param_indices(eval_origins(arg)):
                            summ.param_to_sink.setdefault(
                                pi, "static jit argument")
                return
            # transitive: callee forwards a param to its own sink
            got = call_summary(call)
            if got is None:
                return
            csumm, sink_offset = got
            for j, arg in enumerate(call.args):
                why = csumm.param_to_sink.get(j + sink_offset)
                if why is None:
                    continue
                for pi in _param_indices(eval_origins(arg)):
                    summ.param_to_sink.setdefault(
                        pi, f"via {csumm.qualname}")

        def note_assign(target: ast.AST, value: ast.AST) -> None:
            if not isinstance(target, ast.Name):
                return
            if isinstance(value, ast.Call):
                name, _res = mod.call_name(value)
                if name.endswith(("jit", "pjit")) and name in TRACE_FNS:
                    nums, _names = _static_positions(value)
                    jit_statics[target.id] = nums
                    origins.pop(target.id, None)
                    return
                factory = _resolve_func(mod, value.func, info)
                if factory is not None:
                    marked = _jitted_marker(mod, factory)
                    if marked is not None:
                        jit_statics[target.id] = marked
                        origins.pop(target.id, None)
                        return
            got = eval_origins(value)
            if got:
                origins[target.id] = got
            else:
                origins.pop(target.id, None)

        def visit(stmts: List[ast.stmt]) -> None:
            nonlocal return_origins, returns_seen, returns_sanitized
            from .recompile import _sanitized as _san
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # separate scope
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        note_assign(t, st.value)
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    note_assign(st.target, st.value)
                elif isinstance(st, ast.AugAssign) and \
                        isinstance(st.target, ast.Name):
                    got = eval_origins(st.value)
                    if got:
                        origins.setdefault(st.target.id, set()).update(got)
                elif isinstance(st, ast.Return) and st.value is not None:
                    returns_seen += 1
                    sanitized = any(
                        isinstance(n, ast.Call) and _san(mod, n)
                        for n in ast.walk(st.value))
                    got = call_summary(st.value) \
                        if isinstance(st.value, ast.Call) else None
                    if got is not None and got[0].sanitizes:
                        sanitized = True
                    if sanitized:
                        returns_sanitized += 1
                    else:
                        return_origins |= eval_origins(st.value)
                for node in ast.walk(st):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue
                    if isinstance(node, ast.Call):
                        check_sinks(node)
                for block in ("body", "orelse", "finalbody"):
                    sub = getattr(st, block, None)
                    if isinstance(sub, list) and sub and \
                            isinstance(sub[0], ast.stmt):
                        visit(sub)
                for h in getattr(st, "handlers", []) or []:
                    visit(h.body)

        visit(fn.body)
        summ.param_to_return = {
            i for i in _param_indices(return_origins) if i < len(params)}
        summ.returns_taint = _abs_why(return_origins)
        summ.sanitizes = returns_seen > 0 and \
            returns_sanitized == returns_seen
        return summ


def callgraph_module(mod: ModuleInfo) -> str:
    from .callgraph import module_name
    return module_name(mod.path)


def compute(prog: Program,
            seed: Optional[Dict[str, Dict]] = None,
            dirty_paths: Optional[Set[str]] = None) -> Summaries:
    return Summaries(prog, seed=seed, dirty_paths=dirty_paths)


def by_path(summ: Summaries) -> Dict[str, Dict[str, Dict]]:
    """Serialized summaries grouped by module path, for the cache."""
    out: Dict[str, Dict[str, Dict]] = {}
    for qual, s in summ.table.items():
        entry = summ.prog.funcs.get(qual)
        if entry is None:
            continue
        out.setdefault(entry[0].path, {})[qual] = s.to_dict()
    return out

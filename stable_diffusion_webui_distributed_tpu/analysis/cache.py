"""Per-module analysis cache + git-scoped reporting for sdtpu-lint.

The cache file (``.sdtpu-lint-cache.json`` at the repo root, gitignored)
stores one entry per analyzed module, keyed by the sha256 of the module's
content, all salted with a digest of the analyzer's own sources plus the
Python version — editing any rule module or upgrading Python invalidates
everything.

Reuse contract (honest version):

- **All keys hit** → the cached findings are returned without running any
  pass: the repeat-gate case (CI re-runs, pre-commit with no edits) costs
  one hash sweep.
- **Any key misses** → the whole-program passes rerun. Findings are
  whole-program facts (fixed-point taint summaries, the cross-module lock
  graph), so partial reuse of *findings* would be unsound. What IS reused
  on a partial miss is the taint-summary table: summaries for functions in
  unchanged modules (minus import-dependents of the changed set) seed the
  fixed point, so only changed modules + dependents get re-summarized.

``--changed`` mode is a *reporting* scope, not an analysis scope: the full
package is still analyzed (anything less would miss cross-module effects),
then findings are filtered to the git-changed files plus their transitive
import dependents.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo

CACHE_BASENAME = ".sdtpu-lint-cache.json"
_SALT: Optional[str] = None


def analyzer_salt() -> str:
    """Digest of the analyzer's own source files + Python version: any
    rule change invalidates every cache entry."""
    global _SALT
    if _SALT is not None:
        return _SALT
    h = hashlib.sha256()
    h.update(sys.version.encode())
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    for name in sorted(os.listdir(pkg_dir)):
        if not name.endswith(".py"):
            continue
        try:
            with open(os.path.join(pkg_dir, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
        except OSError:
            continue
    _SALT = h.hexdigest()
    return _SALT


def module_key(mod: ModuleInfo) -> str:
    h = hashlib.sha256()
    h.update(analyzer_salt().encode())
    h.update(mod.path.encode())
    h.update(mod.source.encode())
    return h.hexdigest()


class Cache:
    def __init__(self, root: str):
        self.path = os.path.join(root, CACHE_BASENAME)
        self.data: Dict[str, object] = {}
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                loaded = json.load(f)
            if isinstance(loaded, dict) and \
                    loaded.get("salt") == analyzer_salt():
                self.data = loaded
        except (OSError, ValueError):
            pass

    # -- lookup --------------------------------------------------------------

    def split(self, modules: List[ModuleInfo]
              ) -> Tuple[Set[str], Dict[str, str]]:
        """(dirty module paths, path -> key). Dirty = content key differs
        from the cached one, or the module is new; modules that vanished
        count as a miss too (their findings may be stale)."""
        keys = {m.path: module_key(m) for m in modules}
        entries = self.data.get("modules", {})
        dirty = {p for p, k in keys.items()
                 if not isinstance(entries, dict)
                 or entries.get(p, {}).get("key") != k}
        if isinstance(entries, dict):
            dirty |= {p for p in entries if p not in keys}
        return dirty, keys

    def cached_findings(self) -> Optional[List[Finding]]:
        raw = self.data.get("findings")
        if not isinstance(raw, list):
            return None
        out = []
        for d in raw:
            try:
                out.append(Finding(d["rule"], d["path"], d["line"],
                                   d["symbol"], d["message"]))
            except (KeyError, TypeError):
                return None
        return out

    def seed_summaries(self, clean_paths: Set[str]) -> Dict[str, Dict]:
        """Serialized FuncSummary fields for functions defined in clean
        modules, used to seed the fixed point."""
        entries = self.data.get("modules", {})
        out: Dict[str, Dict] = {}
        if not isinstance(entries, dict):
            return out
        for p in clean_paths:
            summ = entries.get(p, {}).get("summaries", {})
            if isinstance(summ, dict):
                out.update(summ)
        return out

    # -- store ---------------------------------------------------------------

    def store(self, keys: Dict[str, str], findings: List[Finding],
              summaries_by_path: Dict[str, Dict[str, Dict]]) -> None:
        self.data = {
            "salt": analyzer_salt(),
            "modules": {p: {"key": k,
                            "summaries": summaries_by_path.get(p, {})}
                        for p, k in keys.items()},
            "findings": [f.as_dict() for f in findings],
        }
        try:
            with open(self.path, "w", encoding="utf-8") as f:
                json.dump(self.data, f)
        except OSError:
            pass  # read-only checkout: cache is best-effort


def git_changed_paths(root: str) -> Set[str]:
    """Repo-relative paths of files modified vs HEAD plus untracked files
    (the working-tree view a pre-commit hook cares about)."""
    out: Set[str] = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return set()
        if proc.returncode != 0:
            return set()
        out.update(l.strip() for l in proc.stdout.splitlines() if l.strip())
    return {p for p in out if p.endswith(".py")}

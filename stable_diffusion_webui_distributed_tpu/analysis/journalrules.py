"""OB003: journal event-type literals outside the registered event set.

``obs/journal.py`` owns the lifecycle event vocabulary: ``emit`` rejects
any event name not in its ``EVENTS`` frozenset, so a misspelled literal
("complete" for "completed") raises at runtime — but only on the first
request that reaches that call site with the journal enabled, which is
exactly when an operator is debugging and least wants a new crash. This
rule moves the check to lint time: every ``*.emit(<literal>, ...)``
journal call in package code must pass an event name that appears in the
registry module's ``EVENTS`` assignment.

The registered set is parsed from ``obs/journal.py``'s AST (same
no-import discipline as every other rule). When the registry module is
not among the analyzed modules — e.g. a fixture-only run — the set is
empty and every journal-emit literal is flagged, which is what the
fixture tests rely on. Call sites that compute the event name
dynamically are not flagged (the runtime check still covers them); a
deliberate out-of-band literal opts out with ``# sdtpu-lint: journal``.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, ModuleInfo
from .envrules import _enclosing_symbol

MARKER_PREFIX = "sdtpu-lint:"
MARKER = "journal"

#: The module that owns the event vocabulary; its own emits (and the
#: EVENTS assignment itself) are exempt.
REGISTRY_MODULE = "obs/journal.py"


def _exempt(mod: ModuleInfo, line: int) -> bool:
    payload = mod.marker(line, MARKER_PREFIX)
    return payload is not None and MARKER in payload.split()


def _registered_events(modules: List[ModuleInfo]) -> Set[str]:
    """String constants assigned to ``EVENTS`` in the registry module."""
    events: Set[str] = set()
    for mod in modules:
        if not mod.path.endswith(REGISTRY_MODULE):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "EVENTS"
                       for t in node.targets):
                continue
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    events.add(sub.value)
    return events


def _event_arg(node: ast.Call):
    """The event-name argument node of a journal emit call, if literal."""
    arg = None
    if node.args:
        arg = node.args[0]
    for kw in node.keywords:
        if kw.arg == "event":
            arg = kw.value
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg
    return None


def check(modules: List[ModuleInfo]) -> List[Finding]:
    registered = _registered_events(modules)
    findings: List[Finding] = []
    for mod in modules:
        if mod.path.endswith(REGISTRY_MODULE):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name, _resolved = mod.call_name(node)
            if not name:
                continue
            dotted = name.lower()
            # any spelling that resolves to the journal's emit: the
            # module-level helper (journal.emit / obs_journal.emit) or
            # the singleton method (JOURNAL.emit / self._journal.emit)
            if not (dotted.endswith("journal.emit")
                    or dotted.endswith("_journal.emit")
                    or dotted == "emit" and "journal" in
                    (_resolved or "").lower()):
                continue
            arg = _event_arg(node)
            if arg is None:
                continue  # dynamic event name: runtime check covers it
            if arg.value in registered:
                continue
            line = arg.lineno
            if _exempt(mod, line):
                continue
            findings.append(Finding(
                "OB003", mod.path, line, _enclosing_symbol(mod, line),
                f"journal event literal {arg.value!r} is not in "
                "obs/journal.py EVENTS; register it there (or mark a "
                "deliberate out-of-band name with "
                "'# sdtpu-lint: journal')"))
    return findings

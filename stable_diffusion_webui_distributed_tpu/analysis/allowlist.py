"""Committed allowlist for accepted findings (AL001/AL002).

Entries match on (rule, path, symbol) — deliberately NOT on line numbers, so
unrelated edits to a file don't invalidate the entry. Every entry must carry
a ``reason``; an optional ``expires`` (ISO date) turns the suppression into
a dated debt: past that date the finding resurfaces AND the stale entry is
reported as AL001. Entries that match nothing are reported as AL002 so the
allowlist can only shrink, never silently rot.

Format (JSON list, committed at analysis/allowlist.json):

    [{"rule": "RC001",
      "path": "stable_diffusion_webui_distributed_tpu/pipeline/engine.py",
      "symbol": "Engine.encode_prompts",
      "reason": "clip_skip is clamped to [0, 12]; bounded cache key",
      "expires": "2026-12-31"}]
"""

from __future__ import annotations

import datetime
import json
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .core import Finding

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "allowlist.json")


@dataclass
class Entry:
    rule: str
    path: str
    symbol: str
    reason: str
    expires: Optional[str] = None
    index: int = 0

    def matches(self, f: Finding) -> bool:
        return (self.rule == f.rule and self.path == f.path
                and self.symbol == f.symbol)

    def expired(self, today: datetime.date) -> bool:
        if not self.expires:
            return False
        try:
            return datetime.date.fromisoformat(self.expires) < today
        except ValueError:
            return True  # unparseable date = expired, fail safe


def load(path: Optional[str] = None) -> Tuple[List[Entry], str]:
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return [], path
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    entries = []
    for i, item in enumerate(raw):
        entries.append(Entry(rule=item["rule"], path=item["path"],
                             symbol=item["symbol"],
                             reason=item.get("reason", ""),
                             expires=item.get("expires"), index=i))
    return entries, path


def apply(findings: List[Finding], entries: List[Entry], list_path: str,
          today: Optional[datetime.date] = None
          ) -> Tuple[List[Finding], List[Finding]]:
    """Partition findings into (reported, suppressed), appending AL001/AL002
    meta-findings about the allowlist itself to the reported set."""
    today = today or datetime.date.today()
    reported: List[Finding] = []
    suppressed: List[Finding] = []
    used = [False] * len(entries)
    for f in findings:
        entry = None
        for e in entries:
            if e.matches(f):
                used[e.index] = True
                entry = e
                break
        if entry is None:
            reported.append(f)
        elif entry.expired(today):
            reported.append(f)
            # the AL001 below explains why the suppression lapsed
        else:
            suppressed.append(f)
    rel = list_path.replace(os.sep, "/")
    for e in entries:
        if e.expired(today) and used[e.index]:
            reported.append(Finding(
                "AL001", rel, e.index + 1, f"{e.rule}:{e.symbol}",
                f"allowlist entry expired {e.expires}; its finding is "
                f"reported again — fix it or renew the entry with a reason"))
        elif not used[e.index]:
            reported.append(Finding(
                "AL002", rel, e.index + 1, f"{e.rule}:{e.symbol}",
                "allowlist entry matched no finding; delete it"))
    return reported, suppressed

"""OB005: outbound-network calls in obs/ outside the sanctioned set.

The observability plane is read-mostly and passive by design — metrics,
traces, journal, TSDB. Exactly four modules are allowed to speak to the
network: ``obs/stitch.py`` (remote trace fetch), ``obs/federation.py``
(the fleet metrics prober), ``obs/notify.py`` (webhook delivery), and
``obs/push.py`` (the delta-stream subscriber). Each of those routes
every call through the single
``SDTPU_OBS_HTTP_TIMEOUT_S`` timeout knob and carries per-node fault
isolation; an HTTP call sneaking into any *other* obs/ module bypasses
both (an unbounded ``urlopen`` inside, say, the alert engine can hang
the evaluation loop on a dead remote).

This rule flags ``urlopen(...)`` and requests-style verb calls
(``requests.get`` / ``session.post`` / ...) inside obs/ modules outside
the sanctioned set. A deliberate exception opts out with
``# sdtpu-lint: netcall`` on the line or the standalone comment line
above, same marker discipline as OB001/OB004/EV001.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleInfo
from .envrules import _enclosing_symbol

MARKER_PREFIX = "sdtpu-lint:"
MARKER = "netcall"

#: The obs/ modules allowed to make outbound network calls.
SANCTIONED = ("obs/federation.py", "obs/notify.py", "obs/push.py",
              "obs/stitch.py")

#: requests/Session HTTP verb method names.
VERBS = frozenset({"get", "post", "put", "patch", "delete", "head",
                   "request"})

#: Attribute owners whose verb calls count as outbound HTTP.
_HTTP_OWNERS = frozenset({"requests", "session"})


def _in_obs(path: str) -> bool:
    path = path.replace("\\", "/")
    return "/obs/" in path or path.startswith("obs/")


def _exempt(mod: ModuleInfo, line: int) -> bool:
    payload = mod.marker(line, MARKER_PREFIX)
    return payload is not None and MARKER in payload.split()


def _is_net_call(name: str) -> bool:
    parts = name.split(".")
    if parts[-1] == "urlopen":
        return True
    if len(parts) >= 2 and parts[-1] in VERBS \
            and parts[-2] in _HTTP_OWNERS:
        return True
    return False


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if not _in_obs(mod.path):
            continue
        if mod.path.replace("\\", "/").endswith(SANCTIONED):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name, _resolved = mod.call_name(node)
            if not name or not _is_net_call(name):
                continue
            line = node.lineno
            if _exempt(mod, line):
                continue
            findings.append(Finding(
                "OB005", mod.path, line, _enclosing_symbol(mod, line),
                "outbound network call in obs/ outside "
                "federation/notify/stitch; route it through one of the "
                "sanctioned modules so the SDTPU_OBS_HTTP_TIMEOUT_S "
                "bound and per-node fault isolation apply (or mark a "
                "deliberate site with '# sdtpu-lint: netcall')"))
    return findings

"""OB001: wall-clock ``time.time()`` on latency-measurement paths.

The observability layer (obs/) defines every span, histogram sample, and
stage timing as a host-side ``time.perf_counter()`` interval: monotonic,
unaffected by NTP slews, and the clock Chrome-trace ``ts``/``dur`` fields
are derived from. A stray ``time.time()`` difference on a serving or
pipeline path silently produces durations that can go negative under clock
adjustment and that disagree with every other span in the trace — so inside
the scoped packages the call is flagged wherever it appears.

Genuine wall-clock uses (timestamps for humans, e.g. the flight recorder's
``recorded_at``) opt out with a ``# sdtpu-lint: wallclock`` marker on the
call line or the standalone comment line above.
"""

from __future__ import annotations

import ast
from typing import List

from .core import PACKAGE, Finding, ModuleInfo
from .envrules import _enclosing_symbol

#: Package subtrees where durations feed spans/histograms and time.time()
#: is presumed to be a (buggy) duration measurement. Other paths — config
#: quarantine stamps, allowlist expiry, schedulers comparing deadlines —
#: legitimately want wall-clock and are out of scope.
SCOPED = (
    f"{PACKAGE}/serving/",
    f"{PACKAGE}/pipeline/",
    f"{PACKAGE}/obs/",
)

MARKER_PREFIX = "sdtpu-lint:"
MARKER = "wallclock"


def _exempt(mod: ModuleInfo, line: int) -> bool:
    payload = mod.marker(line, MARKER_PREFIX)
    return payload is not None and MARKER in payload.split()


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if not any(s in mod.path for s in SCOPED):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name, resolved = mod.call_name(node)
            if not (resolved and name == "time.time"):
                continue
            line = node.lineno
            if _exempt(mod, line):
                continue
            findings.append(Finding(
                "OB001", mod.path, line, _enclosing_symbol(mod, line),
                "time.time() on a serving/pipeline/obs path; durations "
                "must use time.perf_counter() (mark genuine wall-clock "
                "timestamps with '# sdtpu-lint: wallclock')"))
    return findings

"""OB004: alert-rule registration outside the closed obs/alerts.py set.

``obs/alerts.py`` owns the alert-rule registry: the closed rule set is
what makes the chaos-validated recall/false-positive gate meaningful —
``bench.py --alerts`` labels its phases against rule names it knows, the
journal vocabulary pins ``alert_firing``/``alert_resolved`` payload
shapes, and ``sdtpu_alert_state{rule}`` label cardinality stays bounded.
A ``register_rule`` call anywhere else silently grows the evaluated set
without the gate ever exercising the new detector, so this rule flags
any ``register_rule(...)`` / ``AlertRule(...)`` registration spelled
outside the registry module.

Constructing an :class:`AlertRule` alone is fine anywhere (tests build
throwaway rules constantly); only handing one to ``register_rule`` is
confined. A deliberate out-of-module registration (e.g. a deployment
plugin) opts out with ``# sdtpu-lint: alert`` on the line or the
standalone comment line above, same marker discipline as OB001/EV001.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleInfo
from .envrules import _enclosing_symbol

MARKER_PREFIX = "sdtpu-lint:"
MARKER = "alert"

#: The module that owns the rule registry; everything inside it is exempt.
REGISTRY_MODULE = "obs/alerts.py"

#: The confined registration entry point (any dotted spelling).
REGISTRATION_CALLS = ("register_rule",)


def _exempt(mod: ModuleInfo, line: int) -> bool:
    payload = mod.marker(line, MARKER_PREFIX)
    return payload is not None and MARKER in payload.split()


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if mod.path.endswith(REGISTRY_MODULE):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name, _resolved = mod.call_name(node)
            if not name:
                continue
            if name.rsplit(".", 1)[-1] not in REGISTRATION_CALLS:
                continue
            line = node.lineno
            if _exempt(mod, line):
                continue
            findings.append(Finding(
                "OB004", mod.path, line, _enclosing_symbol(mod, line),
                "alert-rule registration outside obs/alerts.py; add the "
                "rule to the closed registry there so the bench recall "
                "gate exercises it (or mark a deliberate plugin site "
                "with '# sdtpu-lint: alert')"))
    return findings

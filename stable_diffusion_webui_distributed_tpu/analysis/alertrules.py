"""OB004: alert-rule registration outside the closed obs/alerts.py set.

``obs/alerts.py`` owns the alert-rule registry: the closed rule set is
what makes the chaos-validated recall/false-positive gate meaningful —
``bench.py --alerts`` labels its phases against rule names it knows, the
journal vocabulary pins ``alert_firing``/``alert_resolved`` payload
shapes, and ``sdtpu_alert_state{rule}`` label cardinality stays bounded.
A ``register_rule`` call anywhere else silently grows the evaluated set
without the gate ever exercising the new detector, so this rule flags
any ``register_rule(...)`` / ``AlertRule(...)`` registration spelled
outside the registry module.

Constructing an :class:`AlertRule` alone is fine anywhere (tests build
throwaway rules constantly); only handing one to ``register_rule`` is
confined. A deliberate out-of-module registration (e.g. a deployment
plugin) opts out with ``# sdtpu-lint: alert`` on the line or the
standalone comment line above, same marker discipline as OB001/EV001.

The rule also checks ``severity=`` literals on *any* ``AlertRule(...)``
construction against the closed page/warn/info set: severity drives the
notifier's channel routing (SDTPU_NOTIFY_ROUTES keys are severities),
so a misspelled literal silently routes a paging alert to no channel at
all. The runtime ``__post_init__`` raises too, but only when the rule
is built — a plugin module's rogue literal should fail lint, not the
first deploy.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, ModuleInfo
from .envrules import _enclosing_symbol

MARKER_PREFIX = "sdtpu-lint:"
MARKER = "alert"

#: The module that owns the rule registry; everything inside it is exempt.
REGISTRY_MODULE = "obs/alerts.py"

#: The confined registration entry point (any dotted spelling).
REGISTRATION_CALLS = ("register_rule",)

#: The closed severity set — must mirror ``obs.alerts.SEVERITIES``
#: (the analysis passes are AST-only and never import the package).
SEVERITIES = frozenset({"page", "warn", "info"})

#: The constructor whose ``severity=`` keyword is checked.
RULE_CONSTRUCTORS = ("AlertRule",)


def _exempt(mod: ModuleInfo, line: int) -> bool:
    payload = mod.marker(line, MARKER_PREFIX)
    return payload is not None and MARKER in payload.split()


def _bad_severity(node: ast.Call) -> Optional[str]:
    """The rogue severity literal of an AlertRule(...) call, if any.

    Only string constants are judged — a computed severity is runtime
    territory (``__post_init__`` raises there)."""
    for kw in node.keywords:
        if kw.arg != "severity":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str) \
                and v.value not in SEVERITIES:
            return v.value
    return None


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        in_registry = mod.path.endswith(REGISTRY_MODULE)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name, _resolved = mod.call_name(node)
            if not name:
                continue
            short = name.rsplit(".", 1)[-1]
            line = node.lineno
            if short in RULE_CONSTRUCTORS:
                bad = _bad_severity(node)
                if bad is not None and not _exempt(mod, line):
                    findings.append(Finding(
                        "OB004", mod.path, line,
                        _enclosing_symbol(mod, line),
                        f"alert severity {bad!r} outside the closed "
                        "page/warn/info set; SDTPU_NOTIFY_ROUTES routes "
                        "by these exact keys, so a rogue literal "
                        "silently un-routes the alert"))
                continue
            if in_registry:
                continue
            if short not in REGISTRATION_CALLS:
                continue
            if _exempt(mod, line):
                continue
            findings.append(Finding(
                "OB004", mod.path, line, _enclosing_symbol(mod, line),
                "alert-rule registration outside obs/alerts.py; add the "
                "rule to the closed registry there so the bench recall "
                "gate exercises it (or mark a deliberate plugin site "
                "with '# sdtpu-lint: alert')"))
    return findings

"""Fleet concurrency rule (FL001).

The fleet tier (``fleet/`` package) is the one place where many HTTP
handler threads, the coalesce leader, and preempted batch threads all
touch the same queue/registry structures, so its lock discipline is held
to a stricter bar than the rest of the package: in any ``fleet/`` class
that owns a threading lock, EVERY mutable container attribute
(list/dict/set/deque display or constructor) must carry a
``# guarded-by: <lockname>`` annotation — the declaration LK001/LK002
then enforce. An unannotated container in a lock-bearing fleet class is
exactly the shape of bug the gate's condition-variable dance makes
likely, and it is invisible to LK001 (which only checks attributes that
were declared).

Scope: path-scoped to ``fleet/`` modules only — elsewhere the annotation
is a convention, here it is mandatory. Classes with no lock attribute
are exempt (immutable-after-init policy tables, frozen dataclasses);
annotating a single-threaded structure would be noise.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import PACKAGE, Finding, ModuleInfo
from .locks import LOCK_TYPES

FLEET_PREFIX = f"{PACKAGE}/fleet/"

#: constructor names whose result is a mutable container
CONTAINER_CALLS = {"list", "dict", "set", "deque", "defaultdict",
                   "OrderedDict", "Counter"}
CONTAINER_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                   ast.SetComp)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_container(value: ast.AST, mod: ModuleInfo) -> bool:
    if isinstance(value, CONTAINER_NODES):
        return True
    if isinstance(value, ast.Call):
        name, _res = mod.call_name(value)
        return name.split(".")[-1] in CONTAINER_CALLS
    return False


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if not mod.path.startswith(FLEET_PREFIX):
            continue
        for qual, cls in mod.classes.items():
            locks, guarded, containers = set(), set(), []
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and \
                        node.value is not None:
                    targets, value = [node.target], node.value
                else:
                    continue
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if isinstance(value, ast.Call):
                        name, _res = mod.call_name(value)
                        if name.split(".")[-1] in LOCK_TYPES:
                            locks.add(attr)
                            continue
                    if mod.marker(node.lineno, "guarded-by:"):
                        guarded.add(attr)
                    elif _is_container(value, mod):
                        containers.append((attr, node.lineno))
            if not locks:
                continue  # immutable-after-init class: nothing to guard
            seen = set()
            for attr, line in containers:
                if attr in guarded or attr in seen:
                    continue
                seen.add(attr)
                findings.append(Finding(
                    "FL001", mod.path, line, f"{cls.name}.{attr}",
                    f"mutable container '{attr}' in lock-bearing fleet "
                    f"class {cls.name} has no guarded-by annotation"))
    return findings

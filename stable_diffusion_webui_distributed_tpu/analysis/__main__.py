"""CLI: ``python -m stable_diffusion_webui_distributed_tpu.analysis``.

Exit code 0 = no unallowlisted findings, 1 = findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import RULES, run_analysis


def repo_root() -> str:
    # package dir is <root>/stable_diffusion_webui_distributed_tpu/analysis
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m stable_diffusion_webui_distributed_tpu.analysis",
        description="sdtpu-lint: trace-purity, recompile-hazard, and "
                    "lock-discipline analysis (pure AST, no device needed)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the package)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON instead of text")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist path (default: analysis/allowlist.json)")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="report raw findings, ignoring the allowlist")
    ap.add_argument("--rules", action="store_true",
                    help="list rule IDs and exit")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in git-changed files and "
                         "their import dependents (full package is still "
                         "analyzed for cross-module soundness)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and don't write the per-module analysis "
                         "cache (.sdtpu-lint-cache.json)")
    args = ap.parse_args(argv)

    if args.rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    result = run_analysis(repo_root(), paths=args.paths or None,
                          allowlist_path=args.allowlist,
                          use_allowlist=not args.no_allowlist,
                          # cache entries are keyed per-module; explicit
                          # path scoping would poison the full-package set
                          use_cache=not args.no_cache and not args.paths,
                          changed_only=args.changed)
    if args.json:
        json.dump({"modules": result.modules,
                   "counts": result.counts,
                   "suppressed": len(result.suppressed),
                   "findings": [f.as_dict() for f in result.findings]},
                  sys.stdout, indent=2)
        print()
    else:
        for f in result.findings:
            print(f.render())
        cached = " (cached)" if result.cache_hit else ""
        print(f"sdtpu-lint: {len(result.findings)} finding(s), "
              f"{len(result.suppressed)} allowlisted, "
              f"{result.modules} module(s) analyzed in "
              f"{result.wall_time_s:.2f}s{cached}", file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())

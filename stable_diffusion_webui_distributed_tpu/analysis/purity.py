"""Trace-purity rules (TP001/TP002/TP003).

A function is *traced* when JAX executes it once at trace time and replays
the captured computation thereafter: anything the Python body does besides
building jaxprs — reading a wall clock, drawing from host RNG, mutating
closed-over state, branching on tracer values — either bakes a stale value
into every replay or crashes with a ConcretizationError on the device. The
serving layer's byte-identical-under-coalescing guarantee (PR 1) rests on
traced code being pure; these rules machine-check it.

Traced roots are found three ways:

1. Direct: a function passed to (or decorated with) ``jax.jit`` / ``pjit``
   / ``pmap`` / ``vmap`` / ``shard_map`` / ``lax.scan`` / ``lax.cond`` /
   ``lax.while_loop`` / ``lax.fori_loop`` / ``lax.switch`` / ``checkpoint``.
   For these we know which parameters are tracers (minus static_argnums /
   static_argnames), so the branch rule TP002 applies.
2. Marked: ``# sdtpu-lint: traced`` on the def — for functions whose trace
   entry point is in another module (sampler step closures the engine
   scans). TP001/TP003 only.
3. ``nn.Module`` methods (class bases ending in ``Module``): their
   ``__call__`` trees run under the engine's jit. TP001/TP003 only —
   module hyperparameters are legitimately branched on at trace time.

Reachability then closes the set over intra-module calls (bare names,
``self.method``), since helpers called from a traced body are traced too.
``jax.random`` is deliberately NOT banned: keyed functional RNG is the
sanctioned randomness (runtime/rng.py derives the keys); only *host*
nondeterminism is flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FuncInfo, ModuleInfo, declared_nonlocal, func_locals

#: Canonical names whose call sites make their function-valued args traced.
TRACE_FNS = {
    "jax.jit", "jax.pjit", "jax.pmap", "jax.vmap",
    "jax.experimental.pjit.pjit",
    "jax.experimental.shard_map.shard_map", "jax.shard_map",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.checkpoint", "jax.remat",
}

#: Host-nondeterminism call prefixes (canonical dotted names).
BANNED_PREFIXES = ("numpy.random.", "random.", "secrets.")
BANNED_EXACT = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
    "uuid.uuid4", "uuid.uuid1", "os.urandom",
}

#: Attribute/introspection uses of a tracer that are trace-time constants
#: and therefore fine to branch on.
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
SHAPE_CALLS = {"len", "isinstance", "getattr", "hasattr", "callable", "type"}


class TracedFn:
    def __init__(self, info: FuncInfo, mod: ModuleInfo,
                 tracer_params: Optional[Set[str]], why: str):
        self.info = info
        self.mod = mod
        # None => unknown signature mapping (marked/nn.Module/reachable):
        # TP001/TP003 only. A set => TP002 applies to those params.
        self.tracer_params = tracer_params
        self.why = why


def _static_positions(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return nums, names


def _params_of(fn: ast.AST) -> List[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return []
    return [a.arg for a in (args.posonlyargs + args.args)]


def _tracer_params(fn: ast.AST, statics: Tuple[Set[int], Set[str]],
                   drop_first: bool = False) -> Set[str]:
    params = _params_of(fn)
    if drop_first and params:
        params = params[1:]
    nums, names = statics
    out = set()
    for i, p in enumerate(params):
        if i in nums or p in names:
            continue
        out.add(p)
    return out


def _resolve_func(mod: ModuleInfo, node: ast.AST, scope: FuncInfo
                  ) -> Optional[FuncInfo]:
    """Resolve a function-valued expression to a FuncInfo: a bare name
    (nested def in the enclosing scope, else module-level def) or
    ``self.method`` of the enclosing class."""
    if isinstance(node, ast.Name):
        for qual in (f"{scope.qualname}.{node.id}", node.id):
            if qual in mod.funcs:
                return mod.funcs[qual]
        if scope.cls and f"{scope.cls}.{node.id}" in mod.funcs:
            return mod.funcs[f"{scope.cls}.{node.id}"]
        return None
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self" and scope.cls:
        qual = f"{scope.cls}.{node.attr}"
        return mod.funcs.get(qual)
    return None


def _is_module_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.attr if isinstance(base, ast.Attribute) else \
            base.id if isinstance(base, ast.Name) else ""
        if name.endswith("Module"):
            return True
    return False


def find_traced(mod: ModuleInfo) -> Dict[str, TracedFn]:
    traced: Dict[str, TracedFn] = {}

    def add(info: FuncInfo, tracer_params: Optional[Set[str]], why: str):
        prev = traced.get(info.qualname)
        # keep the entry with the most knowledge (known tracer params wins)
        if prev is not None and prev.tracer_params is not None:
            return
        traced[info.qualname] = TracedFn(info, mod, tracer_params, why)

    # 1a. decorators
    for qual, info in mod.funcs.items():
        node = info.node
        for dec in getattr(node, "decorator_list", []):
            call = dec if isinstance(dec, ast.Call) else None
            target = call.func if call else dec
            name, _res = mod.dotted(target) or ("", False)
            if name in TRACE_FNS:
                statics = _static_positions(call) if call else (set(), set())
                add(info, _tracer_params(node, statics), f"@{name}")
            elif name.endswith("partial") and call and call.args:
                inner, _ = mod.dotted(call.args[0]) or ("", False)
                if inner in TRACE_FNS:
                    statics = _static_positions(call)
                    add(info, _tracer_params(node, statics),
                        f"@partial({inner})")

        # 2. explicit marker
        if mod.marker(getattr(node, "lineno", 0), "sdtpu-lint:") is not None:
            payload = mod.marker(node.lineno, "sdtpu-lint:") or ""
            if payload.split("(")[0].strip() == "traced":
                add(info, None, "marked traced")

    # 1b. call sites: jit(f, ...), lax.scan(step, ...), shard_map(f, ...)
    for qual, scope in list(mod.funcs.items()):
        for call in ast.walk(scope.node):
            if not isinstance(call, ast.Call):
                continue
            name, _res = mod.call_name(call)
            if name not in TRACE_FNS:
                continue
            statics = _static_positions(call)
            cond_like = name.endswith((".cond", ".switch"))
            fn_args = list(call.args) + \
                [kw.value for kw in call.keywords
                 if kw.arg in ("f", "fun", "body_fun", "cond_fun", "body")]
            for idx, arg in enumerate(fn_args):
                if isinstance(arg, ast.Lambda):
                    continue  # no body statements worth checking
                target = _resolve_func(mod, arg, scope)
                if target is None:
                    continue
                drop = False
                if cond_like and idx == 0:
                    continue  # the predicate operand, not a branch fn
                use_statics = statics if name.endswith(("jit", "pjit")) \
                    else (set(), set())
                is_method = target.cls is not None and \
                    _params_of(target.node)[:1] == ["self"]
                add(target,
                    _tracer_params(target.node, use_statics,
                                   drop_first=is_method or drop),
                    f"passed to {name}")
    # also module-level trace calls (outside any def; don't re-descend into
    # function bodies — those were handled with their proper scope above)
    scope_mod = FuncInfo(mod.tree, "<module>", None, "")

    def _walk_toplevel(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from _walk_toplevel(child)

    for call in _walk_toplevel(mod.tree):
        if isinstance(call, ast.Call):
            name, _res = mod.call_name(call)
            if name in TRACE_FNS and call.args:
                target = _resolve_func(mod, call.args[0], scope_mod)
                if target is not None:
                    add(target, _tracer_params(target.node,
                                               _static_positions(call)),
                        f"passed to {name}")

    # 3. nn.Module methods
    for cls_qual, cls in mod.classes.items():
        if not _is_module_class(cls):
            continue
        for qual, info in mod.funcs.items():
            if info.cls == cls.name and info.parent_qual == cls_qual \
                    and not info.node.name.startswith("__init"):
                add(info, None, "nn.Module method")

    # 4. reachability over intra-module calls
    frontier = list(traced.values())
    while frontier:
        tf = frontier.pop()
        for call in ast.walk(tf.info.node):
            if not isinstance(call, ast.Call):
                continue
            target = _resolve_func(mod, call.func, tf.info)
            if target is None or target.qualname in traced:
                continue
            new = TracedFn(target, mod, None,
                           f"called from traced {tf.info.qualname}")
            traced[target.qualname] = new
            frontier.append(new)
    return traced


# -- TP001 -------------------------------------------------------------------

def _check_host_calls(tf: TracedFn) -> List[Finding]:
    out = []
    for node in ast.walk(tf.info.node):
        if not isinstance(node, ast.Call):
            continue
        name, resolved = tf.mod.call_name(node)
        if not resolved:
            continue
        banned = name in BANNED_EXACT or \
            any(name.startswith(p) for p in BANNED_PREFIXES)
        if banned:
            out.append(Finding(
                "TP001", tf.mod.path, node.lineno, tf.info.qualname,
                f"host-nondeterministic call {name}() inside traced "
                f"function ({tf.why}); key randomness through "
                f"runtime/rng.py + jax.random instead"))
    return out


# -- TP002 -------------------------------------------------------------------

def _tracer_uses(node: ast.AST, tracers: Set[str],
                 mod: ModuleInfo) -> List[ast.Name]:
    """Names in a branch test that would force tracer concretization.
    Shape/dtype introspection and None-checks are trace-time constants."""
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and \
                all(isinstance(c, ast.Constant) and c.value is None
                    for c in node.comparators):
            return []
    if isinstance(node, ast.Attribute):
        if node.attr in SHAPE_ATTRS:
            return []
        return _tracer_uses(node.value, tracers, mod)
    if isinstance(node, ast.Call):
        name, _res = mod.call_name(node)
        if name.split(".")[-1] in SHAPE_CALLS:
            return []
        out: List[ast.Name] = []
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            out.extend(_tracer_uses(a, tracers, mod))
        return out
    if isinstance(node, ast.Name):
        return [node] if node.id in tracers else []
    out = []
    for child in ast.iter_child_nodes(node):
        out.extend(_tracer_uses(child, tracers, mod))
    return out


def _check_branches(tf: TracedFn) -> List[Finding]:
    if not tf.tracer_params:
        return []
    out = []
    for node in ast.walk(tf.info.node):
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
        elif isinstance(node, ast.IfExp):
            test = node.test
        elif isinstance(node, ast.Assert):
            test = node.test
        else:
            continue
        for name in _tracer_uses(test, tf.tracer_params, tf.mod):
            out.append(Finding(
                "TP002", tf.mod.path, name.lineno, tf.info.qualname,
                f"Python branch on tracer '{name.id}' ({tf.why}); use "
                f"lax.cond/jnp.where, or mark the argument static"))
    return out


# -- TP003 -------------------------------------------------------------------

def _check_mutation(tf: TracedFn) -> List[Finding]:
    fn = tf.info.node
    local = func_locals(fn)
    declared = declared_nonlocal(fn)
    out = []

    def base_name(t: ast.AST) -> Optional[ast.Name]:
        while isinstance(t, (ast.Attribute, ast.Subscript)):
            t = t.value
        return t if isinstance(t, ast.Name) else None

    def flag(node: ast.AST, what: str) -> None:
        out.append(Finding(
            "TP003", tf.mod.path, node.lineno, tf.info.qualname,
            f"mutation of closed-over state ({what}) inside traced function "
            f"({tf.why}); traced bodies run once at trace time — return the "
            f"value instead"))

    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                if t.id in declared:
                    flag(t, f"nonlocal/global '{t.id}'")
            elif isinstance(t, (ast.Attribute, ast.Subscript)):
                base = base_name(t)
                if base is not None and base.id not in local \
                        and base.id not in ("self", "cls"):
                    flag(t, f"'{base.id}' is not local here")
    return out


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        for tf in find_traced(mod).values():
            findings.extend(_check_host_calls(tf))
            findings.extend(_check_branches(tf))
            findings.extend(_check_mutation(tf))
    return findings

"""Recompile-hazard rules (RC001/RC002/RC003).

Every distinct value of a static jit argument — and every distinct value a
traced function closes over at trace time — is a new entry in XLA's compile
cache. When those values derive from request payloads, the compile-cache key
space is attacker-sized: one request per unique (width, height, steps, ...)
combination recompiles the pipeline (minutes on TPU) instead of dispatching
(milliseconds). The serving layer bounds this with the ShapeBucketer ladder:
request-derived values may only become static AFTER quantization onto the
ladder (``bucket_shape`` / ``bucket_batch`` / ``bucket_payload``) or an
explicit constant clamp (``min``/``max`` against a literal), both of which
bound the key space by construction.

Taint sources (per function, intra-procedural, forward single pass):

- attribute reads off a parameter named ``payload`` / ``request`` / ``req``
- ``os.environ`` / ``os.getenv`` reads and the sanctioned ``env_*`` helpers
  from runtime/config.py (env values are per-process constants, but a knob
  that silently multiplies compiled executables still deserves a ladder)

Sinks:

- RC001: a tainted expression at a static position of a call to a known
  jitted callable — one bound from ``jax.jit(f, static_argnums=...)`` in
  the same scope, or obtained from a factory marked
  ``# sdtpu-lint: jitted(static=N[,M...])``.
- RC002: a function passed to jit/scan in this scope whose free variables
  include a tainted name (a closed-over trace-time constant).
- RC003: a raw serving-precision read outside the sanctioned resolution
  modules — ``SDTPU_UNET_INT8[_CONV]`` env reads, ``.get("precision")``
  on an override dict, or ``payload.precision`` attribute reads. The
  precision name is a STATIC compile-key and serving-group-key axis
  (pipeline/engine.py / serving/dispatcher.py), so every consumer must go
  through ``pipeline/precision.py``'s ``resolve``/``bucket_precision``
  (which bounds the value domain to the 3-rung ladder); a raw read is
  either an unbounded key or a group-key bypass that would coalesce
  int8 and bf16 requests into one executable.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FuncInfo, ModuleInfo, func_locals
from .purity import TRACE_FNS, _resolve_func, _static_positions

PAYLOAD_PARAMS = {"payload", "request", "req"}
ENV_HELPERS = {"read_env", "env_str", "env_flag", "env_int", "env_float",
               "env_parsed"}


def _is_env_read(mod: ModuleInfo, node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        name, _res = mod.call_name(node)
        if name in ("os.getenv",) or name.split(".")[-1] in ENV_HELPERS:
            return True
        # os.environ.get(...)
        if name.startswith("os.environ"):
            return True
    if isinstance(node, (ast.Attribute, ast.Subscript)):
        got = mod.dotted(node if isinstance(node, ast.Attribute)
                         else node.value)
        if got is not None and got[0].startswith("os.environ") and got[1]:
            return True
    return False


def _sanitized(mod: ModuleInfo, node: ast.Call) -> bool:
    """Bucketer quantization or a constant clamp bounds the value domain."""
    name, _res = mod.call_name(node)
    tail = name.split(".")[-1]
    if "bucket" in tail or tail == "crop":
        return True
    if tail in ("min", "max"):
        return any(isinstance(a, ast.Constant) for a in node.args)
    return False


class _Inter:
    """Interprocedural adapter: resolves call sites against the summary
    table (analysis/summaries.py) so ``_taint_of`` can follow taint
    through helpers in other modules. ``None`` everywhere degrades to the
    old intra-procedural behavior (which the cross-module fixture test
    exercises both ways)."""

    def __init__(self, summaries, mod: ModuleInfo):
        self.summaries = summaries
        self.mod = mod

    def resolve(self, info: FuncInfo, call: ast.Call):
        return self.summaries.callee(self.mod, info, call)

    def sanitizing(self, info: FuncInfo, call: ast.Call) -> bool:
        got = self.resolve(info, call)
        return got is not None and got[0].sanitizes

    def call_taint(self, info: FuncInfo, call: ast.Call, tainted: Set[str],
                   payload_params: Set[str]) -> Optional[str]:
        """Why a summarized call's return value is tainted, or None."""
        got = self.resolve(info, call)
        if got is None:
            return None
        summ, offset = got
        if summ.sanitizes:
            return None
        if summ.returns_taint:
            return f"{summ.qualname}() [{summ.returns_taint}]"
        forwarded = list(enumerate(call.args)) + [
            (summ.params.index(kw.arg) - offset, kw.value)
            for kw in call.keywords if kw.arg in summ.params]
        for j, arg in forwarded:
            if j + offset not in summ.param_to_return:
                continue
            why = _arg_taint(self.mod, arg, tainted, payload_params,
                             self, info)
            if why is not None:
                return f"{summ.qualname}({why})"
        return None


def _arg_taint(mod: ModuleInfo, arg: ast.AST, tainted: Set[str],
               payload_params: Set[str], inter: Optional["_Inter"],
               info: Optional[FuncInfo]) -> Optional[str]:
    """Taint of a call argument: the usual expression taint, plus the
    whole-request-object case (``helper(payload)`` — a bare payload param
    is itself request-derived even though only attribute reads off it are
    taint *sources* intra-procedurally)."""
    why = _taint_of(mod, arg, tainted, payload_params, inter, info)
    if why is None and isinstance(arg, ast.Name) and \
            arg.id in payload_params:
        why = f"'{arg.id}' (request object)"
    return why


def _taint_of(mod: ModuleInfo, expr: ast.AST, tainted: Set[str],
              payload_params: Set[str], inter: Optional[_Inter] = None,
              info: Optional[FuncInfo] = None) -> Optional[str]:
    """Why ``expr`` is tainted (a description), or None if clean."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _sanitized(mod, node):
            return None  # quantized somewhere in the expression
        if inter is not None and info is not None and \
                isinstance(node, ast.Call) and inter.sanitizing(info, node):
            return None  # callee's summary says it bucket/clamps
    for node in ast.walk(expr):
        if _is_env_read(mod, node):
            return "environment read"
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in payload_params:
            return f"{node.value.id}.{node.attr}"
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in tainted:
            return f"'{node.id}'"
        if inter is not None and info is not None and \
                isinstance(node, ast.Call):
            why = inter.call_taint(info, node, tainted, payload_params)
            if why is not None:
                return why
    return None


class _JitBinding:
    def __init__(self, statics: Set[int], static_names: Set[str], why: str):
        self.statics = statics
        self.static_names = static_names
        self.why = why


def _jitted_marker(mod: ModuleInfo, info: FuncInfo) -> Optional[Set[int]]:
    payload = mod.marker(getattr(info.node, "lineno", 0), "sdtpu-lint:")
    if not payload or not payload.startswith("jitted"):
        return None
    inside = payload[payload.find("(") + 1:payload.rfind(")")]
    out: Set[int] = set()
    for part in inside.replace("static=", "").split(","):
        part = part.strip()
        if part.isdigit():
            out.add(int(part))
    return out


def _scope_seed(mod: ModuleInfo, info: FuncInfo,
                memo: Dict[str, Tuple[Set[str], Dict[str, _JitBinding]]],
                inter: Optional[_Inter] = None,
                ) -> Tuple[Set[str], Dict[str, _JitBinding]]:
    """(tainted names, jit bindings) a nested def inherits by closure.

    A closure reads the enclosing scope's variables, so ``skip`` assigned
    from ``payload.clip_skip`` in the enclosing method is just as tainted
    inside the nested helper that finally calls the jitted encoder. The
    seed is the enclosing function's *final* forward-pass state — an
    over-approximation of what is live at the nested def, biased toward
    reporting (names cleanly reassigned later in the parent are rare).
    """
    parent = mod.funcs.get(info.parent_qual)
    if parent is None or not isinstance(
            parent.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set(), {}
    if parent.qualname not in memo:
        tainted, bindings = _forward_pass(
            mod, parent, *_scope_seed(mod, parent, memo, inter),
            findings=None, inter=inter)
        memo[parent.qualname] = (tainted, bindings)
    tainted, bindings = memo[parent.qualname]
    # names the child rebinds locally are its own, not the closure's
    shadowed = func_locals(info.node)
    return ({t for t in tainted if t not in shadowed},
            {k: v for k, v in bindings.items() if k not in shadowed})


def _forward_pass(mod: ModuleInfo, info: FuncInfo,
                  seed_tainted: Set[str],
                  seed_bindings: Dict[str, _JitBinding],
                  findings: Optional[List[Finding]],
                  inter: Optional[_Inter] = None,
                  ) -> Tuple[Set[str], Dict[str, _JitBinding]]:
    fn = info.node
    params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
    payload_params = {p for p in params if p in PAYLOAD_PARAMS}
    tainted: Set[str] = set(seed_tainted)
    bindings: Dict[str, _JitBinding] = dict(seed_bindings)

    def note_assign(target: ast.AST, value: ast.AST) -> None:
        if not isinstance(target, ast.Name):
            return
        # binding of a jitted callable?
        if isinstance(value, ast.Call):
            name, _res = mod.call_name(value)
            if name.endswith(("jit", "pjit")) and name in TRACE_FNS:
                nums, names = _static_positions(value)
                bindings[target.id] = _JitBinding(nums, names, name)
                return
            factory = _resolve_func(mod, value.func, info)
            if factory is not None:
                statics = _jitted_marker(mod, factory)
                if statics is not None:
                    bindings[target.id] = _JitBinding(
                        statics, set(), f"{factory.qualname} (marked jitted)")
                    return
        why = _taint_of(mod, value, tainted, payload_params, inter, info)
        if why is not None:
            tainted.add(target.id)
        else:
            tainted.discard(target.id)  # clean reassignment clears taint

    def visit(stmts: List[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate scope; RC002 handles closures
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    note_assign(t, st.value)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                note_assign(st.target, st.value)
            elif isinstance(st, ast.AugAssign):
                why = _taint_of(mod, st.value, tainted, payload_params,
                                inter, info)
                if why is not None and isinstance(st.target, ast.Name):
                    tainted.add(st.target.id)
            # RC001: calls to known-jitted callables with tainted statics
            for node in ast.walk(st):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                bind = None
                if isinstance(node.func, ast.Name):
                    bind = bindings.get(node.func.id)
                if bind is None:
                    # interprocedural RC001: the callee's summary says one
                    # of its params reaches a static jit sink inside it
                    if inter is not None and findings is not None:
                        _check_summary_sink(mod, info, node, tainted,
                                            payload_params, inter, findings)
                    continue
                for i, arg in enumerate(node.args):
                    if i not in bind.statics:
                        continue
                    why = _taint_of(mod, arg, tainted, payload_params,
                                    inter, info)
                    if why is not None and findings is not None:
                        findings.append(Finding(
                            "RC001", mod.path, node.lineno, info.qualname,
                            f"static argument {i} of jitted callable "
                            f"({bind.why}) derives from {why}: every "
                            f"distinct value recompiles — quantize through "
                            f"the ShapeBucketer ladder or clamp to a "
                            f"constant range first"))
                for kw in node.keywords:
                    if kw.arg in bind.static_names:
                        why = _taint_of(mod, kw.value, tainted,
                                        payload_params, inter, info)
                        if why is not None and findings is not None:
                            findings.append(Finding(
                                "RC001", mod.path, node.lineno,
                                info.qualname,
                                f"static argument '{kw.arg}' of jitted "
                                f"callable ({bind.why}) derives from {why}"))
            # recurse into compound statements, same scope
            for block in ("body", "orelse", "finalbody"):
                sub = getattr(st, block, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    visit(sub)
            for h in getattr(st, "handlers", []) or []:
                visit(h.body)

    visit(fn.body)
    return tainted, bindings


def _check_summary_sink(mod: ModuleInfo, info: FuncInfo, call: ast.Call,
                        tainted: Set[str], payload_params: Set[str],
                        inter: _Inter, findings: List[Finding]) -> None:
    """RC001 at a call whose callee (per its summary) forwards the given
    argument position into a static jit argument."""
    got = inter.resolve(info, call)
    if got is None:
        return
    summ, offset = got
    if not summ.param_to_sink:
        return
    forwarded = list(enumerate(call.args)) + [
        (summ.params.index(kw.arg) - offset, kw.value)
        for kw in call.keywords if kw.arg in summ.params]
    for j, arg in forwarded:
        sink = summ.param_to_sink.get(j + offset)
        if sink is None:
            continue
        why = _arg_taint(mod, arg, tainted, payload_params, inter, info)
        if why is not None:
            findings.append(Finding(
                "RC001", mod.path, call.lineno, info.qualname,
                f"argument {j} of {summ.qualname}() reaches a static jit "
                f"argument inside the callee ({sink}) and derives from "
                f"{why}: every distinct value recompiles — quantize "
                f"through the ShapeBucketer ladder or clamp to a constant "
                f"range first"))


def _check_function(mod: ModuleInfo, info: FuncInfo,
                    memo: Dict[str, Tuple[Set[str], Dict[str, _JitBinding]]],
                    inter: Optional[_Inter] = None,
                    ) -> List[Finding]:
    fn = info.node
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    findings: List[Finding] = []
    tainted, _bindings = _forward_pass(
        mod, info, *_scope_seed(mod, info, memo, inter), findings=findings,
        inter=inter)

    # RC002: functions handed to trace combinators that close over taint
    if tainted:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name, _res = mod.call_name(node)
            if name not in TRACE_FNS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                target = _resolve_func(mod, arg, info)
                if target is None or target.parent_qual != info.qualname:
                    continue
                # free names used by VALUE (a use that is only ever
                # .shape/.dtype/.ndim introspection is a trace-time shape
                # constant — the bucketing rules govern those, not RC002)
                free: Set[str] = set()

                def _free_value_uses(n: ast.AST) -> None:
                    if isinstance(n, ast.Attribute) and \
                            isinstance(n.value, ast.Name) and \
                            n.attr in ("shape", "ndim", "dtype", "size"):
                        return
                    if isinstance(n, ast.Name) and \
                            isinstance(n.ctx, ast.Load):
                        free.add(n.id)
                    for child in ast.iter_child_nodes(n):
                        _free_value_uses(child)

                _free_value_uses(target.node)
                free -= func_locals(target.node)
                hot = sorted(free & tainted)
                if hot:
                    findings.append(Finding(
                        "RC002", mod.path,
                        getattr(target.node, "lineno", node.lineno),
                        info.qualname,
                        f"function '{target.node.name}' passed to {name} "
                        f"closes over request/env-derived {hot}: each "
                        f"distinct value is a new trace — pass it as a "
                        f"(bucketed) argument instead"))
    return findings


#: Modules allowed to read the raw precision knobs/fields — the policy
#: env defaults (runtime/dtypes.py) and the resolution ladder itself
#: (pipeline/precision.py). Everyone else goes through resolve().
RC003_SANCTIONED = ("runtime/dtypes.py", "pipeline/precision.py")

#: env knobs whose raw value is a precision static
RC003_ENV_PREFIX = "SDTPU_UNET_INT8"


def _rc003_offense(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """Why ``node`` is a raw precision read, or None."""
    if isinstance(node, ast.Call):
        if _is_env_read(mod, node) and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str) and \
                node.args[0].value.startswith(RC003_ENV_PREFIX):
            return f"raw {node.args[0].value} env read"
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                node.args[0].value == "precision":
            return 'raw .get("precision") override read'
    if isinstance(node, ast.Attribute) and node.attr == "precision" and \
            isinstance(node.value, ast.Name) and \
            node.value.id in (PAYLOAD_PARAMS | {"run"}):
        return f"raw {node.value.id}.precision attribute read"
    return None


def _check_precision_reads(mod: ModuleInfo) -> List[Finding]:
    """RC003: module-wide scan (module level included); a read nested
    inside a bucket*/clamp call is sanitized like RC001 taint."""
    from .envrules import _enclosing_symbol

    if mod.path.endswith(RC003_SANCTIONED):
        return []
    findings: List[Finding] = []

    def walk(node: ast.AST, sanitized: bool) -> None:
        if isinstance(node, ast.Call) and _sanitized(mod, node):
            sanitized = True
        if not sanitized:
            why = _rc003_offense(mod, node)
            if why is not None:
                findings.append(Finding(
                    "RC003", mod.path, node.lineno,
                    _enclosing_symbol(mod, node.lineno),
                    f"{why}: the serving precision is a static compile-key "
                    f"and group-key axis — resolve it through "
                    f"pipeline/precision.py (resolve/bucket_precision) so "
                    f"the value domain stays on the 3-rung ladder and "
                    f"dispatch grouping sees the same name the engine "
                    f"compiles"))
                return  # one finding per offending expression
        for child in ast.iter_child_nodes(node):
            walk(child, sanitized)

    walk(mod.tree, False)
    return findings


def check(modules: List[ModuleInfo], summaries=None) -> List[Finding]:
    """``summaries`` (analysis/summaries.Summaries) turns RC001/RC002
    interprocedural; None reproduces the historical intra-procedural pass
    (the cross-module fixture test asserts the difference)."""
    findings: List[Finding] = []
    for mod in modules:
        inter = _Inter(summaries, mod) if summaries is not None else None
        memo: Dict[str, Tuple[Set[str], Dict[str, _JitBinding]]] = {}
        for info in mod.funcs.values():
            findings.extend(_check_function(mod, info, memo, inter))
        findings.extend(_check_precision_reads(mod))
    return findings

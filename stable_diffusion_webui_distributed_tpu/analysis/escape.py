"""Tracer-escape rule (TP004).

A traced function's array arguments are *tracers*: symbolic placeholders
that exist only while JAX builds the jaxpr. Storing one on ``self`` or in
a global container smuggles the placeholder out of the trace — the stored
object is not the runtime value (it is a ``Tracer`` whose trace context is
gone: using it later raises ``UnexpectedTracerError``, a leak JAX only
detects lazily, sometimes far from the cause).

TP003 already flags mutation of closed-over *locals* and declared
globals inside traced bodies, but deliberately excludes ``self``/``cls``
bases (nn.Module hyperparameter writes at init are legitimate). TP004
covers exactly that blind spot, with value precision TP003 doesn't have:

- ``self.attr = <expr>`` (or ``self.attr[k] = ...``) inside a traced
  function where the expression derives from a tracer parameter;
- ``self.attr.append/extend/add/update/setdefault(...)`` with a
  tracer-derived argument.

Only traced roots with a *known* parameter mapping participate (direct
``jit``/``scan`` wiring — see purity.find_traced); ``# sdtpu-lint:
traced``-marked functions have unknown signatures and are skipped.
Shape/dtype introspection (``x.shape``, ``len(x)``) is a trace-time
constant, not a tracer, and never taints.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, ModuleInfo
from .purity import SHAPE_ATTRS, SHAPE_CALLS, TracedFn, find_traced

_MUTATORS = {"append", "extend", "add", "update", "setdefault", "insert"}


def _tracer_use(node: ast.AST, tainted: Set[str],
                mod: ModuleInfo) -> Optional[str]:
    """Name of a tracer-derived value used *as a value* in ``node``."""
    if isinstance(node, ast.Attribute):
        if node.attr in SHAPE_ATTRS:
            return None  # trace-time constant
        return _tracer_use(node.value, tainted, mod)
    if isinstance(node, ast.Call):
        name, _res = mod.call_name(node)
        if name.split(".")[-1] in SHAPE_CALLS:
            return None
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            got = _tracer_use(a, tainted, mod)
            if got is not None:
                return got
        return _tracer_use(node.func, tainted, mod) \
            if not isinstance(node.func, ast.Name) else None
    if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
        return node.id if node.id in tainted else None
    for child in ast.iter_child_nodes(node):
        got = _tracer_use(child, tainted, mod)
        if got is not None:
            return got
    return None


def _self_base(t: ast.AST) -> bool:
    while isinstance(t, (ast.Attribute, ast.Subscript)):
        t = t.value
    return isinstance(t, ast.Name) and t.id in ("self", "cls")


def _check_traced(tf: TracedFn) -> List[Finding]:
    if not tf.tracer_params:
        return []
    mod, fn = tf.mod, tf.info.node
    tainted: Set[str] = set(tf.tracer_params)

    # two sweeps: propagate tracer taint through local assignments, so
    # `y = x * sigma; self.cache = y` is still an escape
    for _sweep in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if _tracer_use(node.value, tainted, mod) is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                if _tracer_use(node.value, tainted, mod) is not None:
                    tainted.add(node.target.id)

    out: List[Finding] = []

    def flag(node: ast.AST, where: str, name: str) -> None:
        out.append(Finding(
            "TP004", mod.path, node.lineno, tf.info.qualname,
            f"tracer-derived '{name}' escapes the traced function "
            f"({tf.why}) into {where}: the stored object is a stale "
            f"Tracer, not a value — return it instead"))

    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                    _self_base(t):
                value = getattr(node, "value", None)
                if value is None:
                    continue
                name = _tracer_use(value, tainted, mod)
                if name is not None:
                    dotted = ast.unparse(t) if hasattr(ast, "unparse") \
                        else "self-attribute"
                    flag(t, f"'{dotted}'", name)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and _self_base(node.func.value):
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                name = _tracer_use(a, tainted, mod)
                if name is not None:
                    container = ast.unparse(node.func.value) \
                        if hasattr(ast, "unparse") else "self-container"
                    flag(node, f"'{container}.{node.func.attr}(...)'", name)
                    break

    return out


def check(modules: List[ModuleInfo], prog=None) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        for tf in find_traced(mod).values():
            findings.extend(_check_traced(tf))
    return findings

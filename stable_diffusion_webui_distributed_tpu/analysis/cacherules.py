"""CA001: payload hashing / cache-key construction outside cache/keys.py.

The caching tier's whole correctness story is that every content address
is minted by one module: ``cache/keys.py`` canonicalizes the payload
(post-``fix_seed``, post-scripts), strips the volatile fields, folds in
the model/tower fingerprints, and hashes the result. A second hashing
site — a dispatcher helper that sha256's ``payload.model_dump()`` its
own way, a store call keyed on a hand-built ``(payload.prompt, ...)``
tuple — silently forks the key space: two sites disagree on volatile
fields or canonical ordering and the cache serves stale bytes for one of
them. This rule pins key minting to the sanctioned module at lint time.

Two offense shapes:

- **hashing**: a ``hashlib`` digest constructor (``sha256``/``sha1``/
  ``md5``/``blake2b``/… or ``hashlib.new``) whose argument subtree
  references request-payload content — the name ``payload``, a
  ``.prompt``/``.negative_prompt`` attribute, or a ``.model_dump()``
  call.
- **hand-built key**: a ``get``/``put``/``peek``/``lookup``/``begin``
  call on a cache-ish receiver (name contains ``cache``/``store``/
  ``flight``) whose first argument is an inline tuple referencing
  payload content — a cache keyed on a tuple nobody canonicalized.

Sanctioned sites: ``cache/keys.py`` (the key mint itself) and
``obs/journal.py`` (the journal fingerprints the payload dump for
replay, a digest that never keys a cache). A deliberate out-of-band
site opts out with ``# sdtpu-lint: cachekey`` on the offending line.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleInfo
from .envrules import _enclosing_symbol

MARKER_PREFIX = "sdtpu-lint:"
MARKER = "cachekey"

#: Modules allowed to hash payload content (path suffixes).
SANCTIONED = ("cache/keys.py", "obs/journal.py")

#: hashlib digest constructors (dotted path suffixes after alias
#: resolution).
_HASH_CTORS = ("sha256", "sha1", "md5", "sha384", "sha512",
               "blake2b", "blake2s", "new")

#: Store methods whose first argument is a key.
_STORE_METHODS = {"get", "put", "peek", "lookup", "begin"}

#: Attribute names that identify request-payload content.
_PAYLOAD_ATTRS = {"prompt", "negative_prompt"}


def _exempt(mod: ModuleInfo, line: int) -> bool:
    payload = mod.marker(line, MARKER_PREFIX)
    return payload is not None and MARKER in payload.split()


def _payloadish(node: ast.AST) -> bool:
    """Does this subtree reference request-payload content?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "payload":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _PAYLOAD_ATTRS:
            return True
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "model_dump":
            return True
    return False


def _is_hash_ctor(mod: ModuleInfo, node: ast.Call) -> bool:
    name, resolved = mod.call_name(node)
    if not name:
        return False
    parts = name.split(".")
    return (len(parts) >= 2 and parts[-2] == "hashlib"
            and parts[-1] in _HASH_CTORS)


def _cacheish_receiver(node: ast.Call) -> bool:
    """True for ``<something cache-like>.get/put/...(...)`` calls."""
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _STORE_METHODS:
        return False
    head = func.value
    # peel call chains like store().put(...) down to the callee name
    while isinstance(head, ast.Call):
        head = head.func
    parts: List[str] = []
    while isinstance(head, ast.Attribute):
        parts.append(head.attr)
        head = head.value
    if isinstance(head, ast.Name):
        parts.append(head.id)
    recv = ".".join(parts).lower()
    return any(w in recv for w in ("cache", "store", "flight"))


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if mod.path.endswith(SANCTIONED):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            line = node.lineno
            if _is_hash_ctor(mod, node):
                if not any(_payloadish(a) for a in
                           list(node.args)
                           + [k.value for k in node.keywords]):
                    continue
                if _exempt(mod, line):
                    continue
                findings.append(Finding(
                    "CA001", mod.path, line,
                    _enclosing_symbol(mod, line),
                    "payload content hashed outside cache/keys.py — "
                    "mint cache keys through cache.keys (or mark a "
                    "deliberate non-key digest with "
                    "'# sdtpu-lint: cachekey')"))
            elif _cacheish_receiver(node) and node.args \
                    and isinstance(node.args[0], ast.Tuple) \
                    and _payloadish(node.args[0]):
                if _exempt(mod, line):
                    continue
                findings.append(Finding(
                    "CA001", mod.path, line,
                    _enclosing_symbol(mod, line),
                    "hand-built payload cache key — canonical keys come "
                    "from cache/keys.py, which strips volatile fields "
                    "and folds in the model fingerprint (or mark with "
                    "'# sdtpu-lint: cachekey')"))
    return findings

"""Use-after-donate rule (DN001).

``jax.jit(fn, donate_argnums=(0,))`` hands the argument buffer to XLA for
in-place reuse: after the call returns, the donated array is *deleted* —
touching it raises ``RuntimeError: Array has been deleted`` on device, and
on CPU test runs it silently works, which is exactly why a static rule is
needed (tier-1 cannot catch it dynamically).

The pass is a forward scan per function, same discipline as the recompile
taint pass:

- a local bound from ``jax.jit(..., donate_argnums=...)`` / ``pjit`` —
  or from a factory marked ``# sdtpu-lint: jitted(donate=N[,M])`` — is a
  *donor*; calling it marks the simple-name arguments at donated
  positions **donated-dead**;
- rebinding a dead name revives it — including the same-statement rebind
  idiom the engine uses (``carry, cache = fn(params, carry, cache)``):
  the call's donations are applied before the assignment's stores, matching
  Python evaluation order;
- any later load of a dead name is DN001, unless the line carries the
  ``# sdtpu-lint: donated`` escape hatch (for deliberate aliasing the
  author has audited);
- loop bodies are scanned twice, so a donate-at-the-bottom /
  use-at-the-top cycle is caught on the second sweep.

Only simple ``Name`` arguments are tracked; donated attribute/subscript
expressions are out of scope (documented under-reporting).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FuncInfo, ModuleInfo
from .purity import TRACE_FNS, _resolve_func

_DONATE_MARKER = re.compile(r"donate=([0-9,\s]+)")


def _donate_positions(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    out.add(n.value)
    return out


def _marker_donate(mod: ModuleInfo, info: FuncInfo) -> Optional[Set[int]]:
    """Donated positions from ``# sdtpu-lint: jitted(donate=N[,M])`` on a
    factory def (composes with the existing ``static=`` payload)."""
    payload = mod.marker(getattr(info.node, "lineno", 0), "sdtpu-lint:")
    if not payload or not payload.startswith("jitted"):
        return None
    m = _DONATE_MARKER.search(payload)
    if m is None:
        return None
    return {int(p) for p in m.group(1).split(",") if p.strip().isdigit()}


def _suppressed(mod: ModuleInfo, line: int) -> bool:
    return (mod.marker(line, "sdtpu-lint:") or "").strip() == "donated"


class _DonationScan:
    def __init__(self, mod: ModuleInfo, info: FuncInfo):
        self.mod = mod
        self.info = info
        self.donors: Dict[str, Tuple[Set[int], str]] = {}
        self.dead: Dict[str, str] = {}  # name -> donor description
        self.findings: Dict[Tuple[int, str], Finding] = {}

    def run(self) -> List[Finding]:
        self._visit(self.info.node.body)  # type: ignore[attr-defined]
        return list(self.findings.values())

    # -- statement walk ------------------------------------------------------

    def _visit(self, stmts: List[ast.stmt]) -> None:
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # separate scope
        if isinstance(st, ast.Assign):
            self._scan_expr(st.value)
            for t in st.targets:
                self._store(t)
            if len(st.targets) == 1:
                self._note_donor(st.targets[0], st.value)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._scan_expr(st.value)
                self._store(st.target)
                self._note_donor(st.target, st.value)
            return
        if isinstance(st, ast.AugAssign):
            self._scan_expr(st.value)
            if isinstance(st.target, ast.Name):
                self._use(st.target)  # augmented assign reads the target
                self.dead.pop(st.target.id, None)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(st.iter)
            self._store(st.target)
            self._visit(st.body)
            self._visit(st.body)  # second sweep: catch cross-iteration use
            self._visit(st.orelse)
            return
        if isinstance(st, ast.While):
            self._scan_expr(st.test)
            self._visit(st.body)
            self._scan_expr(st.test)
            self._visit(st.body)
            self._visit(st.orelse)
            return
        if isinstance(st, ast.If):
            self._scan_expr(st.test)
            self._visit(st.body)
            self._visit(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._scan_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._store(item.optional_vars)
            self._visit(st.body)
            return
        if isinstance(st, ast.Try):
            self._visit(st.body)
            for h in st.handlers:
                self._visit(h.body)
            self._visit(st.orelse)
            self._visit(st.finalbody)
            return
        self._scan_expr(st)

    # -- expression scan -----------------------------------------------------

    def _scan_expr(self, node: ast.AST) -> None:
        donations: List[Tuple[str, str]] = []

        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                self._use(sub)
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and \
                    sub.func.id in self.donors:
                positions, why = self.donors[sub.func.id]
                for i, arg in enumerate(sub.args):
                    if i in positions and isinstance(arg, ast.Name):
                        donations.append((arg.id, why))
        # donations take effect after the expression finishes evaluating
        for name, why in donations:
            self.dead[name] = why

    def _use(self, node: ast.Name) -> None:
        why = self.dead.get(node.id)
        if why is None or _suppressed(self.mod, node.lineno):
            return
        key = (node.lineno, node.id)
        if key in self.findings:
            return
        self.findings[key] = Finding(
            "DN001", self.mod.path, node.lineno, self.info.qualname,
            f"'{node.id}' was donated to {why} and is dead here: the "
            f"buffer is deleted after the call (CPU runs won't catch it) "
            f"— use the call's result, or drop donate_argnums")

    def _store(self, target: ast.AST) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                self.dead.pop(sub.id, None)

    def _note_donor(self, target: ast.AST, value: ast.AST) -> None:
        if not isinstance(target, ast.Name) or \
                not isinstance(value, ast.Call):
            return
        name, _res = self.mod.call_name(value)
        if name.endswith(("jit", "pjit")) and name in TRACE_FNS:
            positions = _donate_positions(value)
            if positions:
                self.donors[target.id] = (positions, f"{name} donate_argnums")
            return
        factory = _resolve_func(self.mod, value.func, self.info)
        if factory is not None:
            positions = _marker_donate(self.mod, factory)
            if positions:
                self.donors[target.id] = (
                    positions, f"{factory.qualname} (marked donating)")


def check(modules: List[ModuleInfo], prog=None) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        for info in mod.funcs.values():
            if not isinstance(info.node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(_DonationScan(mod, info).run())
    return findings

"""sdtpu-lint: AST static analysis for trace purity, recompile hazards,
and lock discipline.

Run over the repo:   python -m stable_diffusion_webui_distributed_tpu.analysis
Tier-1 gate:         tests/test_lint.py (zero findings vs the committed
                     allowlist); tools/lint_report.py emits the JSON summary.
Rule reference:      ANALYSIS.md at the repo root.

Pure ``ast``/``tokenize`` — importable and runnable with no JAX device and
without importing any of the code under analysis.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import allowlist as allowlist_mod
from . import (envrules, fleetrules, journalrules, locks, metricrules,
               purity, recompile, timerules)
from .core import RULES, Finding, ModuleInfo, walk_package

__all__ = ["Finding", "RULES", "AnalysisResult", "run_analysis"]


@dataclass
class AnalysisResult:
    findings: List[Finding]  # unsuppressed (includes AL001/AL002)
    suppressed: List[Finding]
    modules: int = 0
    counts: Dict[str, int] = field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.findings


def analyze_modules(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(purity.check(modules))
    findings.extend(recompile.check(modules))
    findings.extend(envrules.check(modules))
    findings.extend(timerules.check(modules))
    findings.extend(metricrules.check(modules))
    findings.extend(journalrules.check(modules))
    findings.extend(locks.check(modules))
    findings.extend(fleetrules.check(modules))
    # rule passes may re-walk nested statements; dedupe identical findings
    seen = set()
    out = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.symbol, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def run_analysis(root: str,
                 paths: Optional[Sequence[str]] = None,
                 allowlist_path: Optional[str] = None,
                 use_allowlist: bool = True,
                 today: Optional[datetime.date] = None) -> AnalysisResult:
    modules = walk_package(root, paths)
    findings = analyze_modules(modules)
    suppressed: List[Finding] = []
    if use_allowlist:
        entries, list_path = allowlist_mod.load(allowlist_path)
        findings, suppressed = allowlist_mod.apply(findings, entries,
                                                   list_path, today=today)
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return AnalysisResult(findings=findings, suppressed=suppressed,
                          modules=len(modules), counts=counts)

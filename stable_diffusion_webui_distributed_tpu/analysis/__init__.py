"""sdtpu-lint: AST static analysis for trace purity, recompile hazards,
and lock discipline.

Run over the repo:   python -m stable_diffusion_webui_distributed_tpu.analysis
Tier-1 gate:         tests/test_lint.py (zero findings vs the committed
                     allowlist); tools/lint_report.py emits the JSON summary.
Rule reference:      ANALYSIS.md at the repo root.

Pure ``ast``/``tokenize`` — importable and runnable with no JAX device and
without importing any of the code under analysis.
"""

from __future__ import annotations

import datetime
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import allowlist as allowlist_mod
from . import cache as cache_mod
from . import callgraph as callgraph_mod
from . import summaries as summaries_mod
from . import (alertrules, atomicity, cacherules, donation, envrules,
               escape, fleetrules, journalrules, lockorder, locks,
               metricrules, netrules, purity, recompile, threadrules,
               timerules)
from .core import RULES, Finding, ModuleInfo, walk_package

__all__ = ["Finding", "RULES", "AnalysisResult", "run_analysis",
           "analyze_modules"]


@dataclass
class AnalysisResult:
    findings: List[Finding]  # unsuppressed (includes AL001/AL002)
    suppressed: List[Finding]
    modules: int = 0
    counts: Dict[str, int] = field(default_factory=dict)
    wall_time_s: float = 0.0
    cache_hit: bool = False  # every module key hit; no pass ran

    @property
    def clean(self) -> bool:
        return not self.findings


def analyze_modules(modules: List[ModuleInfo],
                    interprocedural: bool = True,
                    prog=None, summaries=None) -> List[Finding]:
    """Run every rule pass. ``interprocedural=False`` reproduces the
    historical per-module taint engine (no summaries) — kept so the
    cross-module fixture test can assert what the old pass missed.
    ``prog``/``summaries`` accept prebuilt indexes (the cache path)."""
    if interprocedural:
        prog = prog if prog is not None else callgraph_mod.build(modules)
        summaries = summaries if summaries is not None \
            else summaries_mod.compute(prog)
    else:
        prog = summaries = None
    findings: List[Finding] = []
    findings.extend(purity.check(modules))
    findings.extend(recompile.check(modules, summaries=summaries))
    findings.extend(envrules.check(modules))
    findings.extend(timerules.check(modules))
    findings.extend(metricrules.check(modules))
    findings.extend(journalrules.check(modules))
    findings.extend(alertrules.check(modules))
    findings.extend(netrules.check(modules))
    lock_res = locks.analyze(modules, prog=prog)
    findings.extend(lock_res.findings)
    findings.extend(lockorder.check(modules, prog=prog, base=lock_res))
    findings.extend(atomicity.check(modules, prog=prog))
    findings.extend(threadrules.check(modules, prog=prog))
    findings.extend(donation.check(modules, prog=prog))
    findings.extend(escape.check(modules, prog=prog))
    findings.extend(fleetrules.check(modules))
    findings.extend(cacherules.check(modules))
    # rule passes may re-walk nested statements; dedupe identical findings
    seen = set()
    out = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.symbol, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def run_analysis(root: str,
                 paths: Optional[Sequence[str]] = None,
                 allowlist_path: Optional[str] = None,
                 use_allowlist: bool = True,
                 today: Optional[datetime.date] = None,
                 use_cache: bool = False,
                 changed_only: bool = False) -> AnalysisResult:
    t0 = time.perf_counter()
    modules = walk_package(root, paths)
    prog = callgraph_mod.build(modules)
    findings: Optional[List[Finding]] = None
    cache_hit = False
    if use_cache:
        store = cache_mod.Cache(root)
        dirty, keys = store.split(modules)
        if not dirty:
            findings = store.cached_findings()
            cache_hit = findings is not None
        if findings is None:
            dirty_closure = prog.dependents(dirty) if dirty else None
            seed = store.seed_summaries(
                {m.path for m in modules} - (dirty_closure or set()))
            summaries = summaries_mod.compute(
                prog, seed=seed, dirty_paths=dirty_closure)
            findings = analyze_modules(modules, prog=prog,
                                       summaries=summaries)
            store.store(keys, findings, summaries_mod.by_path(summaries))
    if findings is None:
        findings = analyze_modules(modules, prog=prog)
    if changed_only:
        changed = cache_mod.git_changed_paths(root)
        scope = prog.dependents(changed) if changed else set()
        findings = [f for f in findings if f.path in scope]
    suppressed: List[Finding] = []
    if use_allowlist:
        entries, list_path = allowlist_mod.load(allowlist_path)
        findings, suppressed = allowlist_mod.apply(findings, entries,
                                                   list_path, today=today)
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return AnalysisResult(findings=findings, suppressed=suppressed,
                          modules=len(modules), counts=counts,
                          wall_time_s=time.perf_counter() - t0,
                          cache_hit=cache_hit)

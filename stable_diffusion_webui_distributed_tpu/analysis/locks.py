"""Lock-discipline rules (LK001/LK002/LK003).

Convention: a ``# guarded-by: <lockname>`` comment on a ``self.<attr> = ...``
line in ``__init__`` (or the line directly above it) declares that attribute
protected by ``self.<lockname>``. The analyzer then verifies, lexically and
per class, that every ``self.<attr>`` access outside ``__init__`` happens
inside a ``with self.<lockname>:`` block (LK001), that the named lock is a
real ``threading.Lock/RLock/Condition`` attribute of the class (LK002), and
that no two locks are ever acquired in opposite orders anywhere in the
package (LK003 — the deadlock precondition).

Scope and honesty about limits (documented in ANALYSIS.md): guarding is
checked *intra-class* — ``self.attr`` in the declaring class's methods.
Cross-object accesses (``worker.state`` from the scheduler) are out of
lexical reach; classes expose locked accessors for those paths instead.
``__init__`` is exempt (construction is single-threaded), as are nested
``def``s spawned as threads — they start with no locks held, which is
exactly how the checker treats them.

Lock-order edges come from three places: lexically nested ``with`` blocks;
method calls made while holding a lock, closed transitively over same-class
``self.method()`` calls; and cross-class calls resolved through a small
attribute->class hint table (``self.engine`` is an Engine, the module
singletons METRICS/STATE are DispatchMetrics/GenerationState). A cycle in
the resulting digraph is reported once per cycle as LK003.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo

#: attribute/variable name -> class name, for cross-class lock-order edges.
CLASS_HINTS = {
    "engine": "Engine",
    "state": "GenerationState",
    "metrics": "DispatchMetrics",
    "METRICS": "DispatchMetrics",
    "STATE": "GenerationState",
    "registry": "ModelRegistry",
    "dispatcher": "ServingDispatcher",
    "bucketer": "ShapeBucketer",
}

LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


class ClassLocks:
    def __init__(self, name: str, mod: ModuleInfo, node: ast.ClassDef):
        self.name = name
        self.mod = mod
        self.node = node
        self.locks: Set[str] = set()  # attr names holding threading locks
        self.guarded: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.methods: Dict[str, ast.AST] = {}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _collect_classes(modules: List[ModuleInfo]) -> Dict[str, ClassLocks]:
    out: Dict[str, ClassLocks] = {}
    for mod in modules:
        for qual, cls in mod.classes.items():
            info = ClassLocks(cls.name, mod, cls)
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods[item.name] = item
            # find lock attributes + guarded-by annotations anywhere in the
            # class body (usually __init__)
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [node.target]
                else:
                    continue
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if isinstance(node.value, ast.Call):
                        name, _res = mod.call_name(node.value)
                        if name.split(".")[-1] in LOCK_TYPES:
                            info.locks.add(attr)
                    g = mod.marker(node.lineno, "guarded-by:")
                    if g:
                        info.guarded[attr] = (g.split()[0], node.lineno)
            if info.locks or info.guarded:
                # last definition wins on duplicate class names; the package
                # has none, and fixtures are analyzed in isolation
                out[info.name] = info
    return out


def _with_locks(item: ast.withitem, cls: ClassLocks) -> Optional[str]:
    attr = _self_attr(item.context_expr)
    if attr is not None and attr in cls.locks:
        return attr
    return None


# -- per-method traversal ----------------------------------------------------

class _MethodScan:
    """One pass over a method body: LK001 guarded-access checks, direct
    lock acquisitions, and (held-lock -> call / held-lock -> lock) edges."""

    def __init__(self, cls: ClassLocks, method_name: str):
        self.cls = cls
        self.method = method_name
        self.findings: List[Finding] = []
        self.acquired: Set[str] = set()  # locks this method may take
        # (held_lock, callee) where callee is ("self", meth) or (Class, meth)
        self.calls_under: Set[Tuple[str, Tuple[str, str]]] = set()
        self.edges: Set[Tuple[str, str]] = set()  # lock -> lock, same class
        self.local_hints: Dict[str, str] = {}  # var -> class name

    def run(self, node: ast.AST) -> None:
        self._body(getattr(node, "body", []), frozenset())

    def _body(self, stmts: List[ast.stmt], held: frozenset) -> None:
        for st in stmts:
            self._stmt(st, held)

    def _stmt(self, st: ast.stmt, held: frozenset) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later (thread target / callback): no locks
            # are held when it starts
            self._body(st.body, frozenset())
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            newly = []
            for item in st.items:
                self._expr(item.context_expr, held)
                lock = _with_locks(item, self.cls)
                if lock is not None:
                    newly.append(lock)
                    self.acquired.add(lock)
                    for h in held:
                        self.edges.add((h, lock))
            self._body(st.body, held | frozenset(newly))
            return
        if isinstance(st, ast.Try):
            self._body(st.body, held)
            for h in st.handlers:
                self._body(h.body, held)
            self._body(st.orelse, held)
            self._body(st.finalbody, held)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, held)
            self._body(st.body, held)
            self._body(st.orelse, held)
            return
        if isinstance(st, ast.For):
            self._expr(st.iter, held)
            self._body(st.body, held)
            self._body(st.orelse, held)
            return
        # track `engine = self.engine` style aliases for lock-order hints
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                isinstance(st.targets[0], ast.Name):
            src = _self_attr(st.value)
            if src is not None and src in CLASS_HINTS:
                self.local_hints[st.targets[0].id] = CLASS_HINTS[src]
        self._expr(st, held)

    def _expr(self, node: ast.AST, held: frozenset) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            attr = _self_attr(sub) if isinstance(sub, ast.Attribute) else None
            if attr is not None and attr in self.cls.guarded:
                lock, _ln = self.cls.guarded[attr]
                if lock not in held:
                    self.findings.append(Finding(
                        "LK001", self.cls.mod.path, sub.lineno,
                        f"{self.cls.name}.{self.method}",
                        f"access to '{attr}' (guarded-by {lock}) without "
                        f"holding self.{lock}"))
            if isinstance(sub, ast.Call):
                self._call(sub, held)

    def _call(self, call: ast.Call, held: frozenset) -> None:
        if not held:
            return
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return
        base = fn.value
        callee: Optional[Tuple[str, str]] = None
        if isinstance(base, ast.Name):
            if base.id == "self":
                callee = ("self", fn.attr)
            elif base.id in self.local_hints:
                callee = (self.local_hints[base.id], fn.attr)
            elif base.id in CLASS_HINTS:
                callee = (CLASS_HINTS[base.id], fn.attr)
        else:
            attr = _self_attr(base)
            if attr is not None and attr in CLASS_HINTS:
                callee = (CLASS_HINTS[attr], fn.attr)
        if callee is not None:
            for h in held:
                self.calls_under.add((h, callee))


def check(modules: List[ModuleInfo]) -> List[Finding]:
    findings: List[Finding] = []
    classes = _collect_classes(modules)

    # LK002: guarded-by names an attribute that is not a lock of the class
    for cls in classes.values():
        for attr, (lock, line) in cls.guarded.items():
            if lock not in cls.locks:
                findings.append(Finding(
                    "LK002", cls.mod.path, line, f"{cls.name}.{attr}",
                    f"guarded-by names '{lock}', which is not a "
                    f"threading lock attribute of {cls.name}"))

    # per-method scans
    scans: Dict[Tuple[str, str], _MethodScan] = {}
    for cls in classes.values():
        for mname, mnode in cls.methods.items():
            scan = _MethodScan(cls, mname)
            scan.run(mnode)
            scans[(cls.name, mname)] = scan
            if mname != "__init__":
                findings.extend(scan.findings)

    # transitive lock-acquisition sets per method (fixpoint)
    acquired: Dict[Tuple[str, str], Set[str]] = {
        key: {f"{key[0]}.{lk}" for lk in scan.acquired}
        for key, scan in scans.items()}
    changed = True
    while changed:
        changed = False
        for key, scan in scans.items():
            for _h, (tgt_cls, tgt_meth) in scan.calls_under:
                tgt = (key[0] if tgt_cls == "self" else tgt_cls, tgt_meth)
                extra = acquired.get(tgt, set())
                if not extra <= acquired[key]:
                    acquired[key] |= extra
                    changed = True

    # lock-order edges: nested withs + calls made while holding a lock
    edges: Dict[str, Set[str]] = {}
    edge_src: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, mod: ModuleInfo, line: int, sym: str):
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        edge_src.setdefault((a, b), (mod.path, line, sym))

    for key, scan in scans.items():
        cls = scan.cls
        for (a, b) in scan.edges:
            add_edge(f"{cls.name}.{a}", f"{cls.name}.{b}", cls.mod,
                     cls.node.lineno, f"{cls.name}.{key[1]}")
        for h, (tgt_cls, tgt_meth) in scan.calls_under:
            tgt = (key[0] if tgt_cls == "self" else tgt_cls, tgt_meth)
            for lk in acquired.get(tgt, set()):
                add_edge(f"{cls.name}.{h}", lk, cls.mod, cls.node.lineno,
                         f"{cls.name}.{key[1]} -> {tgt[0]}.{tgt[1]}")

    # LK003: cycles in the lock digraph
    seen_cycles: Set[frozenset] = set()

    def dfs(node: str, stack: List[str], on_stack: Set[str],
            visited: Set[str]) -> None:
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(edges.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                cyc_key = frozenset(cyc)
                if cyc_key not in seen_cycles:
                    seen_cycles.add(cyc_key)
                    path, line, sym = edge_src.get(
                        (node, nxt), ("<unknown>", 0, "<unknown>"))
                    findings.append(Finding(
                        "LK003", path, line, sym,
                        "lock-order inversion: " + " -> ".join(cyc) +
                        " (acquire these locks in one global order)"))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: Set[str] = set()
    for node in sorted(edges):
        if node not in visited:
            dfs(node, [], set(), visited)

    return findings
